"""Model-weights registry and staging.

Equivalent capability of the reference's weights management
(cosmos_curate/configs/all_models.json registry + core/utils/model/
model_utils.py:56-778 download/staging flow): a registry of model ids with
their local weight locations, a per-node staging hook, and loading that is
explicit about provenance.

In this image there is no network egress and no pretrained cache, so
``load_params`` falls back to **seeded random initialization** with a
prominent warning when no weights are staged — architecture, sharding and
throughput are exercised identically; real deployments drop orbax
checkpoints into ``$CURATE_MODEL_WEIGHTS_DIR/<model-id>/``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

WEIGHTS_DIR_ENV = "CURATE_MODEL_WEIGHTS_DIR"


class WeightsIntegrityError(RuntimeError):
    """A pulled checkpoint failed its sha256 manifest — never silently
    degraded to random init (corrupted staging must abort, not caption
    a dataset with garbage at full cost)."""
# Remote prefix weights are pulled from on demand (s3:// gs:// az:// or a
# local/NFS path) — the reference's download/staging flow
# (model_utils.py:139 pulls from HF/S3 to node-local disk; here the pull
# rides the SDK-free storage clients).
WEIGHTS_URI_ENV = "CURATE_WEIGHTS_URI"


@dataclass(frozen=True)
class ModelEntry:
    model_id: str
    description: str = ""


_REGISTRY: dict[str, ModelEntry] = {}


def register_model(model_id: str, description: str = "") -> None:
    _REGISTRY[model_id] = ModelEntry(model_id, description)


def registered_models() -> list[str]:
    return sorted(_REGISTRY)


for _mid, _desc in [
    ("transnetv2-tpu", "shot transition detector (Flax DDCNN)"),
    ("clip-vit-l14-tpu", "CLIP ViT-L/14 image embedder (Flax)"),
    ("clip-vit-b16-tpu", "CLIP ViT-B/16 image embedder (Flax)"),
    ("aesthetics-mlp-tpu", "aesthetic score head over CLIP embeddings"),
    ("video-embed-tpu", "temporal-transformer video embedder"),
    ("internvideo2-1b-tpu", "InternVideo2-1B stage2 video embedder (converted checkpoint slot)"),
    ("internvideo2-tiny-test", "InternVideo2 tiny test config"),
    ("caption-vlm-tpu", "vision-language captioning model (Flax)"),
    ("caption-qwen2vl-2b-tpu", "Qwen2-VL-2B-class captioner (converted checkpoint slot)"),
    ("caption-qwen25vl-7b-tpu", "Qwen2.5-VL-7B/CosmosReason-class captioner (converted checkpoint slot)"),
    ("caption-qwen3moe-a3b-tpu", "Qwen3-MoE-A3B-class chat LM, expert-parallel (converted checkpoint slot)"),
    ("caption-qwen3vl-moe-a3b-tpu", "Qwen3-VL-MoE-A3B captioner: deepstack vision + sparse LM (converted checkpoint slot)"),
    ("t5-encoder-tpu", "text encoder for caption embeddings"),
    ("ocr-detector-tpu", "overlay-text region detector (Flax FCN)"),
    ("ocr-recognizer-tpu", "text recognizer CRNN with CTC decoding"),
    ("tracker-siamese-tpu", "learned single-object appearance tracker"),
]:
    register_model(_mid, _desc)


def weights_root() -> Path:
    return Path(os.environ.get(WEIGHTS_DIR_ENV, "/tmp/curate_model_weights"))


# Weights committed with the framework itself (e.g. the synthetically
# trained TransNetV2 checkpoint) — searched after the staging dir so a
# staged real checkpoint always wins.
REPO_WEIGHTS_DIR = Path(__file__).resolve().parent.parent.parent / "weights"


def local_dir_for(model_id: str) -> Path:
    return weights_root() / model_id


def find_checkpoint(model_id: str) -> Path | None:
    return find_model_file(model_id, "params.msgpack")


def find_model_file(model_id: str, filename: str) -> Path | None:
    """A staged/committed auxiliary model file (tokenizer vocab, config,
    ...), staging dir first so a pulled real asset wins over a committed
    test fixture."""
    for root in (weights_root(), REPO_WEIGHTS_DIR):
        p = root / model_id / filename
        if p.exists():
            return p
    return None


# Non-checkpoint files pulled alongside a caption model's weights: converted
# HF checkpoints are unusable without their exact-id tokenizer files
# (GPT-2-format pair for Qwen; tokenizer.json for T5/unigram checkpoints).
TOKENIZER_AUX_FILES = ("vocab.json", "merges.txt", "tokenizer.json")


def stage_weights_on_node(model_ids: list[str]) -> None:
    """Per-node staging hook (reference: one Ray task per node copies weights
    to local SSD, model_utils.py:139). Ensures dirs exist and, when
    ``CURATE_WEIGHTS_URI`` names a remote prefix, pulls each model's
    checkpoint down to node-local disk."""
    for mid in model_ids:
        local_dir_for(mid).mkdir(parents=True, exist_ok=True)
        maybe_pull_remote_weights(mid)


def maybe_pull_remote_weights(model_id: str) -> Path | None:
    """Pull ``{CURATE_WEIGHTS_URI}/{model_id}/params.msgpack`` to the local
    staging dir if it is not already there.

    Fan-out safe: concurrent worker processes on one node serialize on a
    file lock and land the bytes via atomic rename, so every node pays the
    download ONCE regardless of worker count (the reference's one-Ray-task-
    per-node staging property). A ``params.msgpack.sha256`` sidecar, when
    present, is verified before the rename — a truncated or corrupted pull
    never becomes a "staged checkpoint".
    """
    uri = os.environ.get(WEIGHTS_URI_ENV, "").rstrip("/")
    if not uri:
        return None
    dest = local_dir_for(model_id) / "params.msgpack"
    if dest.exists():
        return dest
    from cosmos_curate_tpu.storage.client import get_storage_client
    from cosmos_curate_tpu.utils.file_lock import file_lock

    dest.parent.mkdir(parents=True, exist_ok=True)
    lock_path = dest.parent / ".staging.lock"
    with file_lock(lock_path):
        if dest.exists():  # another worker won the race while we waited
            return dest
        remote = f"{uri}/{model_id}/params.msgpack"
        client = get_storage_client(remote)
        try:
            want = client.read_bytes(f"{remote}.sha256").decode().split()[0]
        except FileNotFoundError:
            want = ""
        import hashlib

        tmp = dest.with_suffix(".msgpack.tmp")
        digest = hashlib.sha256()
        chunk = 32 * 1024 * 1024
        # stream ranged reads through the hash into the temp file: a
        # multi-GB checkpoint never sits fully in RAM (the realistic VLM
        # case this plane exists for)
        read_range = getattr(client, "read_range", None)
        if getattr(client, "size", None) is None:
            read_range = None  # ranged streaming needs the object size too
        size = 0
        try:
            with tmp.open("wb") as fh:
                if read_range is not None:
                    total = client.size(remote)
                    for start in range(0, total, chunk):
                        part = read_range(remote, start, min(start + chunk, total) - 1)
                        digest.update(part)
                        fh.write(part)
                        size += len(part)
                else:
                    data = client.read_bytes(remote)
                    digest.update(data)
                    fh.write(data)
                    size = len(data)
        except FileNotFoundError:
            tmp.unlink(missing_ok=True)
            logger.info("no remote weights at %s", remote)
            return None
        except Exception:
            tmp.unlink(missing_ok=True)
            raise
        if want and digest.hexdigest() != want:
            tmp.unlink(missing_ok=True)
            raise WeightsIntegrityError(
                f"weights integrity check failed for {remote}: "
                f"sha256 {digest.hexdigest()} != manifest {want}"
            )
        tmp.rename(dest)  # atomic: readers never see a partial file
        logger.info("staged %s from %s (%d bytes)", model_id, remote, size)
        return dest


def maybe_pull_tokenizer_files(model_id: str) -> None:
    """Best-effort pull of the tokenizer sidecar files a converted HF
    checkpoint needs. Called only when a converted checkpoint is in play
    (hf_chat caption flavors; T5 after its checkpoint is staged) —
    repo-native flavors must not pay doomed remote GETs on every setup."""
    uri = os.environ.get(WEIGHTS_URI_ENV, "").rstrip("/")
    if not uri:
        return
    from cosmos_curate_tpu.storage.client import get_storage_client

    for name in TOKENIZER_AUX_FILES:
        dest = local_dir_for(model_id) / name
        if dest.exists():
            continue
        remote = f"{uri}/{model_id}/{name}"
        try:
            data = get_storage_client(remote).read_bytes(remote)
        except FileNotFoundError:
            continue
        dest.parent.mkdir(parents=True, exist_ok=True)
        tmp = dest.with_name(dest.name + ".tmp")
        tmp.write_bytes(data)
        tmp.rename(dest)
        logger.info("staged %s for %s", name, model_id)


def load_params(
    model_id: str,
    init_fn: Callable[[int], Any],
    *,
    seed: int = 0,
    require: bool = False,
) -> Any:
    """Load staged weights for ``model_id`` if present, else fall back to
    ``init_fn(seed)`` (random init) with a warning.

    ``require=True`` raises instead of falling back — for callers whose
    behavior would silently invert on random weights (e.g. a filter stage
    that must NOT fail open to discarding every clip).

    Format: flax msgpack (``flax.serialization``) — synchronous and
    self-contained; the tree structure comes from ``init_fn``."""
    from cosmos_curate_tpu.utils.jax_cache import enable_persistent_cache

    # Every model load precedes that model's compiles; enabling here makes
    # repeat compiles (fresh processes, re-created stage instances) disk hits.
    enable_persistent_cache()
    ckpt = find_checkpoint(model_id)
    if ckpt is None:
        try:
            ckpt = maybe_pull_remote_weights(model_id)
        except WeightsIntegrityError:
            raise  # corruption must abort, not fall back to random init
        except Exception:
            logger.exception("remote weight staging failed for %s", model_id)
            ckpt = None
    if ckpt is not None:
        import flax.serialization

        logger.info("loading %s weights from %s", model_id, ckpt)
        template = init_fn(seed)
        data = ckpt.read_bytes()
        try:
            # canonical format: UNBOXED raw arrays (what converters emit
            # and save_params writes); sharding metadata is re-attached
            # from the init template so pjit layouts survive the roundtrip
            restored = flax.serialization.from_bytes(_unbox_tree(template), data)
            # from_bytes does NOT validate leaf shapes: a checkpoint staged
            # for other model shapes restores "successfully" and then dies
            # deep inside apply (observed: default-config transnet weights
            # loaded into TRANSNET_TINY_TEST). Check here so the mismatch
            # takes the architecture-mismatch path below.
            _assert_shapes_match(_unbox_tree(template), restored, model_id)
            return _rebox_like(template, restored)
        except (ValueError, KeyError, TypeError) as unboxed_err:
            # legacy format: checkpoints written before the unboxed
            # canonicalization serialized Partitioned leaves as
            # {'value': ...} state dicts — restore against the boxed
            # template keeps them loadable (shape-validated like the
            # canonical path: this fallback must not smuggle in a
            # wrong-architecture checkpoint the canonical path rejected)
            try:
                restored = flax.serialization.from_bytes(template, data)
                _assert_shapes_match(
                    _unbox_tree(template), _unbox_tree(restored), model_id
                )
                return restored
            except (ValueError, KeyError, TypeError):
                e = unboxed_err  # report the canonical-format error
            if require:
                raise RuntimeError(
                    f"staged weights at {ckpt} do not match {model_id}'s "
                    f"current architecture: {e}"
                ) from e
            # a checkpoint staged for different model shapes (e.g. an old
            # config) must not hard-crash the pipeline at stage setup
            logger.error(
                "staged weights at %s do not match %s's current architecture "
                "(%s); falling back to random init", ckpt, model_id, e,
            )
            return init_fn(seed)
    elif require:
        raise RuntimeError(
            f"no staged weights for {model_id} under "
            f"{local_dir_for(model_id) / 'params.msgpack'}"
        )
    logger.warning(
        "no staged weights for %s under %s — using seeded random init "
        "(stage a params.msgpack there for real inference)",
        model_id,
        local_dir_for(model_id) / "params.msgpack",
    )
    return init_fn(seed)


# checkpoint digests cached per (path, mtime): hashing a multi-GB
# checkpoint once per process is fine, once per written chunk is not
_PROVENANCE_CACHE: dict[tuple[str, int], str] = {}


def weights_provenance(model_id: str) -> str:
    """Where ``model_id``'s weights would come from RIGHT NOW:
    ``"checkpoint:<sha256-12>"`` when a checkpoint is staged/committed,
    ``"random"`` otherwise (the seeded-init fallback ``load_params`` warns
    about). Downstream consumers use this to refuse noise — e.g. the corpus
    index (dedup/index_store.py) never ingests random-provenance
    embeddings. Only positive results are cached (keyed by path + mtime),
    so weights staged later in-process are picked up."""
    ckpt = find_checkpoint(model_id)
    if ckpt is None:
        return "random"
    try:
        key = (str(ckpt), ckpt.stat().st_mtime_ns)
    except OSError:
        return "random"
    cached = _PROVENANCE_CACHE.get(key)
    if cached is not None:
        return cached
    import hashlib

    digest = hashlib.sha256()
    with ckpt.open("rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 22), b""):
            digest.update(chunk)
    prov = f"checkpoint:{digest.hexdigest()[:12]}"
    _PROVENANCE_CACHE[key] = prov
    return prov


def save_params(model_id: str, params: Any, *, root: Path | str | None = None) -> Path:
    """Write staged weights into the registry location (or under ``root``
    — e.g. the repo's committed weights/ tree). Single source of truth for
    the checkpoint layout: trainers must not re-implement it."""
    import flax.serialization

    base = Path(root) if root is not None else weights_root()
    ckpt = base / model_id / "params.msgpack"
    ckpt.parent.mkdir(parents=True, exist_ok=True)
    # Canonical checkpoint format: unboxed raw arrays. Partitioned sharding
    # boxes are process-local compile metadata, not weights — converters
    # emit raw arrays and load_params re-boxes from the init template.
    # Atomic publish: a trainer killed mid-write (watcher timeouts) must not
    # leave a truncated params.msgpack that later passes exists() checks.
    tmp = ckpt.with_name(ckpt.name + ".tmp")
    tmp.write_bytes(flax.serialization.to_bytes(_unbox_tree(params)))
    tmp.replace(ckpt)
    return ckpt


def _unbox_tree(tree: Any) -> Any:
    """Strip flax AxisMetadata boxes (nn.Partitioned) down to raw arrays."""
    import jax
    from flax import linen as fnn

    return jax.tree_util.tree_map(
        lambda x: x.unbox() if isinstance(x, fnn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, fnn.Partitioned),
    )


def _assert_shapes_match(template: Any, restored: Any, model_id: str) -> None:
    """Raise ValueError naming the first leaf whose shape disagrees with the
    init template (both trees unboxed; same treedef by construction of the
    from_bytes target)."""
    import jax

    t_leaves = jax.tree_util.tree_leaves_with_path(template)
    r_leaves = jax.tree_util.tree_leaves(restored)
    if len(t_leaves) != len(r_leaves):
        raise ValueError(
            f"{model_id} checkpoint has {len(r_leaves)} leaves, "
            f"model expects {len(t_leaves)}"
        )
    for (path, t), r in zip(t_leaves, r_leaves):
        t_shape = getattr(t, "shape", None)
        r_shape = getattr(r, "shape", None)
        if t_shape != r_shape:
            raise ValueError(
                f"{model_id} checkpoint leaf {jax.tree_util.keystr(path)} has "
                f"shape {r_shape}, model expects {t_shape}"
            )


def _rebox_like(template: Any, values: Any) -> Any:
    """Wrap restored raw arrays back into the template's Partitioned boxes
    (positional zip over the flattened trees; structures match because the
    unboxed template produced the restore target)."""
    import jax
    from flax import linen as fnn

    t_leaves, treedef = jax.tree_util.tree_flatten(
        template, is_leaf=lambda x: isinstance(x, fnn.Partitioned)
    )
    v_leaves = jax.tree_util.tree_leaves(values)
    out = [
        t.replace_boxed(v) if isinstance(t, fnn.Partitioned) else v
        for t, v in zip(t_leaves, v_leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)
