"""Synthetic-data training for the TransNet shot detector.

The reference ships pretrained TransNetV2 weights
(cosmos_curate/models/transnetv2.py:530 loads the published checkpoint); this
image has no network egress, so functional shot detection comes from
training our own Flax DDCNN (models/transnetv2.py) on synthesized
scene-cut clips: random per-scene texture generators (solid drift, panning
gradients, moving shapes, noise) concatenated with hard cuts, labels 1 at
transition frames. The trained checkpoint is staged through the registry
(committed under ``weights/transnetv2-tpu/`` so every run loads it); staging
a converted real checkpoint under $CURATE_MODEL_WEIGHTS_DIR still wins.

TPU-first: one jitted train step (conv3d-heavy → MXU); data synthesis on
host numpy, overlapped only trivially (the model is small).
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.transnetv2 import INPUT_H, INPUT_W, TransNet, TransNetConfig
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _scene(rng: np.random.Generator, t: int, h: int, w: int) -> np.ndarray:
    """One synthetic scene: [t, h, w, 3] uint8 with temporal coherence."""
    kind = rng.integers(0, 4)
    base = rng.integers(0, 256, 3).astype(np.float32)
    out = np.empty((t, h, w, 3), np.float32)
    if kind == 0:  # solid color with brightness drift
        drift = rng.uniform(-1.5, 1.5)
        for i in range(t):
            out[i] = np.clip(base + drift * i, 0, 255)
    elif kind == 1:  # panning linear gradient
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        angle = rng.uniform(0, 2 * np.pi)
        grad = np.cos(angle) * xx / w + np.sin(angle) * yy / h
        speed = rng.uniform(-0.05, 0.05)
        for i in range(t):
            g = (grad + speed * i) % 1.0
            out[i] = base * 0.4 + g[..., None] * rng.uniform(80, 175)
    elif kind == 2:  # moving rectangle on solid background
        fg = rng.integers(0, 256, 3).astype(np.float32)
        rw, rh = int(rng.integers(w // 6, w // 2)), int(rng.integers(h // 6, h // 2))
        x0, y0 = rng.integers(0, w - rw), rng.integers(0, h - rh)
        vx, vy = rng.uniform(-2, 2, 2)
        for i in range(t):
            out[i] = base
            x = int(np.clip(x0 + vx * i, 0, w - rw))
            y = int(np.clip(y0 + vy * i, 0, h - rh))
            out[i, y : y + rh, x : x + rw] = fg
    else:  # static texture + small per-frame noise
        tex = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        for i in range(t):
            out[i] = np.clip(tex + rng.normal(0, 4, (h, w, 3)), 0, 255)
    return out.astype(np.uint8)


def synthesize_batch(
    rng: np.random.Generator,
    batch: int,
    t: int,
    *,
    h: int = INPUT_H,
    w: int = INPUT_W,
    single_scene_frac: float = 0.35,
) -> tuple[np.ndarray, np.ndarray]:
    """-> (frames uint8 [B, T, h, w, 3], labels float32 [B, T]).

    Label 1 marks the first frame of each new scene (the transition frame,
    matching the published TransNetV2 target definition).

    ``single_scene_frac`` of rows are a SINGLE scene with all-zero labels:
    multi-scene windows alone never show the model "no cut anywhere", and
    false-positive suppression stalls without them (observed: false-cut
    probability stuck ~0.65 over the first 75 CPU training steps)."""
    frames = np.empty((batch, t, h, w, 3), np.uint8)
    labels = np.zeros((batch, t), np.float32)
    for b in range(batch):
        if rng.random() < single_scene_frac:
            frames[b] = _scene(rng, t, h, w)
            continue
        pos = 0
        while pos < t:
            scene_len = int(rng.integers(max(4, t // 8), max(8, t // 2)))
            end = min(pos + scene_len, t)
            frames[b, pos:end] = _scene(rng, end - pos, h, w)
            if pos > 0:
                labels[b, pos] = 1.0
            pos = end
    return frames, labels


def train(
    cfg: TransNetConfig = TransNetConfig(),
    *,
    steps: int = 600,
    batch: int = 8,
    window: int | None = None,
    lr: float = 1e-3,
    pos_weight: float = 8.0,
    seed: int = 0,
    log_every: int = 100,
):
    from cosmos_curate_tpu.models.transnetv2 import WINDOW

    if window is None:
        window = WINDOW
    elif window != WINDOW:
        # the dilated convs' SAME-padding stamps an edge signature on every
        # in-window position: a model trained at one window length emits
        # positional, content-free predictions under another (observed with
        # 16-frame training at 100-frame inference) — staging such a
        # checkpoint would ship a silently broken shot detector
        raise ValueError(
            f"train window {window} != inference WINDOW {WINDOW} "
            "(transnetv2.py); train at the inference window"
        )
    """Train on synthetic cuts; returns (params, final_loss)."""
    import jax
    import jax.numpy as jnp
    import optax

    model = TransNet(cfg)
    rng = np.random.default_rng(seed)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, window, INPUT_H, INPUT_W, 3), jnp.uint8)
    )
    opt = optax.adamw(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, frames, labels):
        def loss_fn(p):
            logits = model.apply(p, frames)
            per = optax.sigmoid_binary_cross_entropy(logits, labels)
            weight = 1.0 + (pos_weight - 1.0) * labels
            return (per * weight).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for i in range(steps):
        frames, labels = synthesize_batch(rng, batch, window)
        params, opt_state, loss = step(params, opt_state, jnp.asarray(frames), jnp.asarray(labels))
        if log_every and (i + 1) % log_every == 0:
            logger.info("transnet train step %d/%d loss %.4f", i + 1, steps, float(loss))
    return params, float(loss) if loss is not None else float("nan")


def train_and_stage(
    cfg: TransNetConfig = TransNetConfig(),
    *,
    model_id: str = "transnetv2-tpu",
    out_dir: str | None = None,
    **train_kw,
):
    """Train and write params.msgpack into the registry location (or
    ``out_dir`` — e.g. the repo's committed ``weights/`` tree)."""
    from cosmos_curate_tpu.models import registry

    params, loss = train(cfg, **train_kw)
    ckpt = registry.save_params(model_id, params, root=out_dir)
    logger.info("staged %s (final loss %.4f) at %s", model_id, loss, ckpt)
    return ckpt, loss


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="Train TransNet on synthetic scene cuts")
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--window", type=int, default=None, help="default: the inference WINDOW")
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None, help="e.g. <repo>/weights to commit the result")
    a = ap.parse_args()
    train_and_stage(
        steps=a.steps, batch=a.batch, window=a.window, lr=a.lr, seed=a.seed, out_dir=a.out_dir
    )
