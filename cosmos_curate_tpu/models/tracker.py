"""Promptable object tracking on TPU.

Equivalent capability of the reference's SAM3 tracking integration
(cosmos_curate/models/sam3.py:41 + pipelines/video/tracking/ — promptable
object tracking producing per-frame boxes/instances and annotated mp4s).
Own TPU-first design rather than a SAM port: normalized cross-correlation
template tracking where the WHOLE clip is tracked in one jitted
``lax.scan`` over frames — the per-frame correlation is a conv on the MXU,
there is no per-frame Python, and the search is windowed around the last
position with an EMA-updated template (classic NCC/KCF-family technique,
public). Quality is below a learned tracker; the pipeline surface (prompt
box in, per-frame boxes out) is the same, and a learned model can drop in
behind the identical stage interface.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class TrackerConfig:
    template_size: int = 32  # template patch edge (resized)
    search_radius: int = 24  # pixels around last center searched
    ema: float = 0.1  # template update rate
    work_size: int = 128  # frames resized to work_size x work_size


def _to_gray(frames_u8):
    return frames_u8.astype(jnp.float32).mean(axis=-1) / 255.0


def _normalize(patch):
    mu = patch.mean()
    sd = jnp.sqrt(jnp.maximum(patch.var(), 1e-8))
    return (patch - mu) / sd


@functools.partial(jax.jit, static_argnames=("cfg", "ts"))
def _track_scan(frames_u8, box0, cfg: TrackerConfig, ts: int):
    """frames_u8: [T, S, S, 3] (work size); box0: [4] (cx, cy, w, h) in
    work coords; ts: template edge (static, derived from the prompt box so
    small objects get small templates). Returns centers [T,2], scores [T]."""
    gray = _to_gray(frames_u8)  # [T, S, S]
    s = gray.shape[1]
    r = min(cfg.search_radius, (s - ts) // 2)

    cx0, cy0 = box0[0], box0[1]

    def crop(img, cx, cy, size):
        x0 = jnp.clip(cx - size // 2, 0, s - size).astype(jnp.int32)
        y0 = jnp.clip(cy - size // 2, 0, s - size).astype(jnp.int32)
        return jax.lax.dynamic_slice(img, (y0, x0), (size, size)), x0, y0

    template0, tx0, ty0 = crop(gray[0], cx0, cy0, ts)
    template0 = _normalize(template0)
    # crop() clamps at image edges, so the template's center can differ from
    # the prompted center; the target sits at this constant offset from
    # every matched template center
    delta = jnp.stack(
        [cx0 - (tx0 + ts // 2), cy0 - (ty0 + ts // 2)]
    ).astype(jnp.float32)

    search_size = ts + 2 * r

    def step(carry, frame):
        template, cx, cy = carry
        window, wx0, wy0 = crop(frame, cx, cy, search_size)
        window = _normalize(window)
        # NCC via conv: correlate template over the search window (MXU path)
        corr = jax.lax.conv_general_dilated(
            window[None, None],
            template[None, None],
            window_strides=(1, 1),
            padding="VALID",
        )[0, 0]  # [2r+1, 2r+1]
        idx = jnp.argmax(corr)
        dy, dx = jnp.unravel_index(idx, corr.shape)
        score = corr.reshape(-1)[idx] / (ts * ts)
        ncx = wx0 + dx + ts // 2
        ncy = wy0 + dy + ts // 2
        new_patch, _, _ = crop(frame, ncx, ncy, ts)
        new_template = _normalize(
            (1.0 - cfg.ema) * template + cfg.ema * _normalize(new_patch)
        )
        return (new_template, ncx, ncy), (jnp.stack([ncx, ncy]), score)

    (_, _, _), (centers, scores) = jax.lax.scan(
        step, (template0, cx0.astype(jnp.int32), cy0.astype(jnp.int32)), gray
    )
    return centers.astype(jnp.float32) + delta[None, :], scores


def host_track(
    frames: np.ndarray,
    box_xywh: tuple[float, float, float, float],
    work_size: int,
    scan_fn,
) -> tuple[np.ndarray, np.ndarray]:
    """Shared host wrapper for whole-clip trackers: resize the clip to the
    square work size, map the prompt box into work coordinates, pad T to a
    pow2 bucket (per-clip frame counts must not each cost an XLA compile),
    run ``scan_fn(padded_u8, box0_np) -> (centers, scores)``, and map the
    track back to original pixels. Keeps the coordinate math in ONE place
    for the NCC and siamese trackers."""
    import cv2

    t, h, w = frames.shape[:3]
    small = np.stack(
        [cv2.resize(f, (work_size, work_size), interpolation=cv2.INTER_AREA) for f in frames]
    )
    sx, sy = work_size / w, work_size / h
    x, y, bw, bh = box_xywh
    box0 = np.asarray(
        [(x + bw / 2) * sx, (y + bh / 2) * sy, bw * sx, bh * sy], np.float32
    )
    from cosmos_curate_tpu.models.batching import pad_batch

    padded, _ = pad_batch(small)
    centers, scores = scan_fn(padded, box0)
    centers = np.asarray(centers, np.float32)[:t]
    boxes = np.stack(
        [
            centers[:, 0] / sx - bw / 2,
            centers[:, 1] / sy - bh / 2,
            np.full(t, bw, np.float32),
            np.full(t, bh, np.float32),
        ],
        axis=1,
    )
    return boxes, np.asarray(scores)[:t]


class TemplateTracker:
    """Track a prompted box through a clip; host-facing wrapper."""

    def __init__(self, cfg: TrackerConfig = TrackerConfig()) -> None:
        self.cfg = cfg

    def track(
        self, frames: np.ndarray, box_xywh: tuple[float, float, float, float]
    ) -> tuple[np.ndarray, np.ndarray]:
        """frames: uint8 [T, H, W, 3]; box: (x, y, w, h) in pixels of the
        FIRST frame. Returns (boxes [T, 4] xywh in original coords,
        scores [T])."""

        def scan(padded, box0):
            # template edge = 2x the scaled prompt extent (context margin:
            # an exact-extent template over a uniform object has ~zero
            # variance and NCC degenerates), pow2 so few sizes compile
            extent = max(8.0, 2.0 * float(max(box0[2], box0[3])))
            ts = min(1 << int(np.ceil(np.log2(extent))), self.cfg.work_size // 2)
            return _track_scan(padded, jnp.asarray(box0), self.cfg, ts)

        return host_track(frames, box_xywh, self.cfg.work_size, scan)
