"""Vision Transformer backbone (shared by CLIP-style image embedding and the
video embedder).

Equivalent capability of the reference's CLIP vision tower usage
(cosmos_curate/models/clip.py:36-118 drives HF transformers' CLIP on CUDA);
this is our own Flax implementation, TPU-first: patchify as a single conv
(maps to MXU), bf16 compute, TP head sharding from models/layers.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from cosmos_curate_tpu.models.layers import TransformerBlock, dense


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 14
    width: int = 1024
    layers: int = 24
    heads: int = 16
    projection_dim: int = 768
    # "gelu" | "quick_gelu" — OpenAI CLIP checkpoints use quick_gelu; set it
    # when loading converted HF weights (models/convert_hf.py)
    act: str = "gelu"
    ln_eps: float = 1e-6  # 1e-5 for HF-converted checkpoints
    # "simple" ([-1,1], full-image bilinear) | "clip" (CLIP mean/std,
    # bicubic shortest-side + center crop — what converted CLIP checkpoints
    # were trained with; reference cosmos_curate/models/clip.py:48-62)
    preprocess: str = "simple"

    @property
    def head_dim(self) -> int:
        return self.width // self.heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


VIT_L_14 = ViTConfig()
VIT_B_16 = ViTConfig(patch_size=16, width=768, layers=12, heads=12, projection_dim=512)
VIT_TINY_TEST = ViTConfig(image_size=32, patch_size=8, width=64, layers=2, heads=4, projection_dim=32)


class ViT(nn.Module):
    """Image encoder: pixels [B, H, W, 3] float in [-1, 1] -> (pooled [B, P],
    tokens [B, N, W])."""

    cfg: ViTConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pixels):
        cfg = self.cfg
        x = nn.Conv(
            cfg.width,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(pixels.astype(self.dtype))
        b, gh, gw, w = x.shape
        x = x.reshape(b, gh * gw, w)
        cls = self.param("cls", nn.initializers.normal(0.02), (1, 1, w), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(self.dtype), (b, 1, w)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, cfg.num_patches + 1, w), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        x = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.ln_eps, name="ln_pre")(x)
        for i in range(cfg.layers):
            x = TransformerBlock(
                cfg.heads,
                cfg.head_dim,
                dtype=self.dtype,
                act=cfg.act,
                ln_eps=cfg.ln_eps,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.ln_eps, name="ln_post")(x)
        pooled = dense(cfg.projection_dim, None, name="proj", use_bias=False, dtype=self.dtype)(
            x[:, 0]
        )
        return pooled, x


# OpenAI CLIP training normalization (HF CLIPImageProcessor defaults).
CLIP_IMAGE_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_IMAGE_STD = (0.26862954, 0.26130258, 0.27577711)


def preprocess_frames(frames, *, image_size: int, mode: str = "simple"):
    """uint8 [..., H, W, 3] -> float model input, entirely device-side.

    ``mode="simple"``: scale to [-1, 1] + full-image bilinear resize (the
    from-scratch models' convention). ``mode="clip"``: CLIP's pipeline —
    bicubic shortest-side resize, center crop, scale to [0, 1], per-channel
    mean/std normalization — required for converted CLIP checkpoints
    (reference cosmos_curate/models/clip.py:48-62). All shape math is static
    at trace time, so both modes stay inside one jitted program.
    """
    import jax

    x = frames.astype(jnp.float32)
    if mode == "clip":
        h, w = x.shape[-3], x.shape[-2]
        batch_dims = x.shape[:-3]
        x = x.reshape((-1, h, w, 3))
        if (h, w) != (image_size, image_size):
            scale = image_size / min(h, w)
            nh = max(image_size, int(round(h * scale)))
            nw = max(image_size, int(round(w * scale)))
            x = jax.image.resize(x, (x.shape[0], nh, nw, 3), method="bicubic")
            top = (nh - image_size) // 2
            left = (nw - image_size) // 2
            x = x[:, top : top + image_size, left : left + image_size, :]
        x = x / 255.0
        x = (x - jnp.asarray(CLIP_IMAGE_MEAN)) / jnp.asarray(CLIP_IMAGE_STD)
        return x.reshape((*batch_dims, image_size, image_size, 3))
    if mode != "simple":
        raise ValueError(f"unknown preprocess mode {mode!r}")
    x = x / 127.5 - 1.0
    if x.shape[-3] != image_size or x.shape[-2] != image_size:
        batch_dims = x.shape[:-3]
        x = x.reshape((-1, *x.shape[-3:]))
        x = jax.image.resize(
            x, (x.shape[0], image_size, image_size, 3), method="bilinear"
        )
        x = x.reshape((*batch_dims, image_size, image_size, 3))
    return x
