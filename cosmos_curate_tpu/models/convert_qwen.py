"""Qwen2(-VL) checkpoint converter for the CurateVLM LM stack.

Equivalent capability of the reference's Qwen-family caption models, which
vLLM loads directly (cosmos_curate/models/vllm_qwen.py:122-260). Our
``VLM_QWEN2_2B`` config matches Qwen2-VL-2B-Instruct's language model
tensor-for-tensor (GQA, SwiGLU, q/k/v biases, tied embeddings, RMS norm,
rope 1e6), so this converter maps every LM tensor name exactly — numeric
parity is proven against a randomly initialized HF Qwen2 in
tests/models/test_convert_qwen.py.

The Qwen2-VL *vision* encoder (``visual.*`` tensors) is architecturally
different (3D-conv patchify, windowed attention, m-rope); our ViT vision
tower is retained instead, and ``convert_qwen2_lm`` reports those tensors as
intentionally unmapped rather than silently dropping them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _t(w) -> np.ndarray:
    return np.asarray(w.detach().cpu().numpy() if hasattr(w, "detach") else w)


@dataclass
class ConversionReport:
    mapped: list[str] = field(default_factory=list)
    vision_skipped: list[str] = field(default_factory=list)
    unmapped: list[str] = field(default_factory=list)


def qwen2_lm_config(hf_config, **overrides):
    """Our VLMConfig from an HF Qwen2(-VL) text config."""
    from cosmos_curate_tpu.models.vlm.model import VLMConfig

    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    kw = dict(
        vocab=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        head_dim=head_dim,
        hidden_mult=hf_config.intermediate_size / hf_config.hidden_size,
        rope_theta=hf_config.rope_theta,
        qkv_bias=True,
    )
    kw.update(overrides)
    return VLMConfig(**kw)


def convert_qwen2_lm(state_dict, n_layers: int) -> tuple[dict, ConversionReport]:
    """HF Qwen2(-VL) state dict → our VLM LM params subtree + report.

    Accepts both bare Qwen2 (``model.``) and Qwen2-VL (``model.`` +
    ``visual.``) layouts. Returns params covering embed / layer_i / ln_f;
    merge into a full init tree with ``merge_lm_params``.
    """
    sd = {k: v for k, v in state_dict.items()}
    report = ConversionReport()

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    # Qwen2-VL-2B prefixes text tensors with "model."; some exports use
    # "model.language_model." — probe which exists.
    prefix = "model."
    if f"{prefix}embed_tokens.weight" not in sd:
        for cand in ("model.language_model.", "language_model.model.", ""):
            if f"{cand}embed_tokens.weight" in sd:
                prefix = cand
                break
    params: dict = {"embed": {"embedding": take(f"{prefix}embed_tokens.weight")}}
    for i in range(n_layers):
        e = f"{prefix}layers.{i}."

        def lin(name: str, bias: bool) -> dict:
            d = {"kernel": take(f"{e}{name}.weight").T}
            if bias:
                d["bias"] = take(f"{e}{name}.bias")
            return d

        params[f"layer_{i}"] = {
            "ln1": {"scale": take(f"{e}input_layernorm.weight")},
            "ln2": {"scale": take(f"{e}post_attention_layernorm.weight")},
            "q": lin("self_attn.q_proj", True),
            "k": lin("self_attn.k_proj", True),
            "v": lin("self_attn.v_proj", True),
            "o": lin("self_attn.o_proj", False),
            "gate": lin("mlp.gate_proj", False),
            "up": lin("mlp.up_proj", False),
            "down": lin("mlp.down_proj", False),
        }
    params["ln_f"] = {"scale": take(f"{prefix}norm.weight")}

    mapped = set(report.mapped)
    for k in sd:
        if k in mapped:
            continue
        if k.startswith(("visual.", "model.visual.")):
            report.vision_skipped.append(k)
        elif k == "lm_head.weight":
            # tied-embedding checkpoints may still serialize the head; our
            # logits use embed.attend, so a TIED head is already covered.
            head, emb = _t(sd[k]), params["embed"]["embedding"]
            if head.shape == emb.shape and np.array_equal(head, emb):
                report.mapped.append(k)
            else:
                report.unmapped.append(k)
        else:
            report.unmapped.append(k)
    logger.info(
        "converted Qwen2 LM: %d tensors mapped, %d vision skipped, %d unmapped",
        len(report.mapped),
        len(report.vision_skipped),
        len(report.unmapped),
    )
    return {"params": params}, report


def merge_lm_params(init_tree: dict, lm_params: dict) -> dict:
    """Overlay converted LM params onto a full init tree (vision tower +
    projector keep their existing — e.g. self-trained — values)."""
    import flax

    merged = flax.core.unfreeze(init_tree)
    for key, val in lm_params["params"].items():
        merged["params"][key] = val
    return merged
