"""Qwen2(-VL) checkpoint converter for the CurateVLM LM stack.

Equivalent capability of the reference's Qwen-family caption models, which
vLLM loads directly (cosmos_curate/models/vllm_qwen.py:122-260). Our
``VLM_QWEN2_2B`` config matches Qwen2-VL-2B-Instruct's language model
tensor-for-tensor (GQA, SwiGLU, q/k/v biases, tied embeddings, RMS norm,
rope 1e6), so this converter maps every LM tensor name exactly — numeric
parity is proven against a randomly initialized HF Qwen2 in
tests/models/test_convert_qwen.py.

The Qwen2-VL *vision* encoder (``visual.*`` tensors) maps onto our Flax
``QwenVisionTower`` (models/vlm/vision_qwen.py — 3D-conv patchify as a
matmul, 2D rope, patch merger) via ``convert_qwen2_vision``; numeric parity
vs a randomly initialized HF `Qwen2VisionTransformerPretrainedModel` is
proven in tests/models/test_convert_qwen.py. ``convert_qwen2_lm`` alone
still reports vision tensors as intentionally unmapped for the LM-only
path; ``convert_qwen2_vl`` maps both halves.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _t(w) -> np.ndarray:
    return np.asarray(w.detach().cpu().numpy() if hasattr(w, "detach") else w)


@dataclass
class ConversionReport:
    mapped: list[str] = field(default_factory=list)
    vision_skipped: list[str] = field(default_factory=list)
    unmapped: list[str] = field(default_factory=list)


def qwen2_lm_config(hf_config, **overrides):
    """Our VLMConfig from an HF Qwen2(-VL) text config."""
    from cosmos_curate_tpu.models.vlm.model import VLMConfig

    head_dim = getattr(hf_config, "head_dim", None) or (
        hf_config.hidden_size // hf_config.num_attention_heads
    )
    rope_scaling = getattr(hf_config, "rope_scaling", None) or {}
    mrope = rope_scaling.get("mrope_section")
    kw = dict(
        vocab=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        head_dim=head_dim,
        hidden_mult=hf_config.intermediate_size / hf_config.hidden_size,
        rope_theta=hf_config.rope_theta,
        qkv_bias=True,
        mrope_section=tuple(mrope) if mrope else None,
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
        tied_embeddings=getattr(hf_config, "tie_word_embeddings", True),
    )
    kw.update(overrides)
    return VLMConfig(**kw)


def convert_qwen2_lm(
    state_dict, n_layers: int, *, tied_embeddings: bool | None = None
) -> tuple[dict, ConversionReport]:
    """HF Qwen2(-VL) state dict → our VLM LM params subtree + report.

    Accepts both bare Qwen2 (``model.``) and Qwen2-VL (``model.`` +
    ``visual.``) layouts. Returns params covering embed / layer_i / ln_f;
    merge into a full init tree with ``merge_lm_params``.
    """
    sd = {k: v for k, v in state_dict.items()}
    report = ConversionReport()

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    # Qwen2-VL-2B prefixes text tensors with "model."; some exports use
    # "model.language_model." — probe which exists.
    prefix = "model."
    if f"{prefix}embed_tokens.weight" not in sd:
        for cand in ("model.language_model.", "language_model.model.", ""):
            if f"{cand}embed_tokens.weight" in sd:
                prefix = cand
                break
    params: dict = {"embed": {"embedding": take(f"{prefix}embed_tokens.weight")}}
    for i in range(n_layers):
        e = f"{prefix}layers.{i}."

        def lin(name: str, bias: bool) -> dict:
            d = {"kernel": take(f"{e}{name}.weight").T}
            if bias:
                d["bias"] = take(f"{e}{name}.bias")
            return d

        params[f"layer_{i}"] = {
            "ln1": {"scale": take(f"{e}input_layernorm.weight")},
            "ln2": {"scale": take(f"{e}post_attention_layernorm.weight")},
            "q": lin("self_attn.q_proj", True),
            "k": lin("self_attn.k_proj", True),
            "v": lin("self_attn.v_proj", True),
            "o": lin("self_attn.o_proj", False),
            "gate": lin("mlp.gate_proj", False),
            "up": lin("mlp.up_proj", False),
            "down": lin("mlp.down_proj", False),
        }
    params["ln_f"] = {"scale": take(f"{prefix}norm.weight")}

    mapped = set(report.mapped)
    for k in sd:
        if k in mapped:
            continue
        if k.startswith(("visual.", "model.visual.")):
            report.vision_skipped.append(k)
        elif k == "lm_head.weight":
            head, emb = _t(sd[k]), params["embed"]["embedding"]
            # tied checkpoints may still serialize the head (covered by
            # embed.attend) — but only drop it when the TARGET config does
            # not expect a separate lm_head (see convert_qwen3_moe_lm)
            redundant = head.shape == emb.shape and np.array_equal(head, emb)
            if redundant and tied_embeddings is not False:
                report.mapped.append(k)
            else:
                # untied head (Qwen2.5-VL): its own projection matrix
                params["lm_head"] = {"kernel": head.T}
                report.mapped.append(k)
        else:
            report.unmapped.append(k)
    logger.info(
        "converted Qwen2 LM: %d tensors mapped, %d vision skipped, %d unmapped",
        len(report.mapped),
        len(report.vision_skipped),
        len(report.unmapped),
    )
    return {"params": params}, report


def qwen3_moe_lm_config(hf_text_config, **overrides):
    """Our VLMConfig from an HF Qwen3(-VL)-MoE text config: per-head qk
    RMSNorm, no attention bias, sparse MoE FFN on every layer."""
    from cosmos_curate_tpu.models.vlm.model import MoEConfig, VLMConfig

    c = hf_text_config
    rope_scaling = getattr(c, "rope_scaling", None) or {}
    mrope = rope_scaling.get("mrope_section")
    kw = dict(
        vocab=c.vocab_size,
        dim=c.hidden_size,
        n_layers=c.num_hidden_layers,
        n_heads=c.num_attention_heads,
        n_kv_heads=c.num_key_value_heads,
        head_dim=getattr(c, "head_dim", c.hidden_size // c.num_attention_heads),
        hidden_mult=c.intermediate_size / c.hidden_size,
        rope_theta=c.rope_theta,
        qkv_bias=getattr(c, "attention_bias", False),
        qk_norm=True,
        # Qwen3-VL m-rope is INTERLEAVED across frequency dims
        mrope_section=tuple(mrope) if mrope else None,
        mrope_interleaved=bool(mrope),
        rms_eps=getattr(c, "rms_norm_eps", 1e-6),
        tied_embeddings=getattr(c, "tie_word_embeddings", True),
        moe=MoEConfig(
            n_experts=c.num_experts,
            top_k=c.num_experts_per_tok,
            hidden=c.moe_intermediate_size,
        ),
    )
    kw.update(overrides)
    return VLMConfig(**kw)


def convert_qwen3_moe_lm(
    state_dict, n_layers: int, *, tied_embeddings: bool | None = None
) -> tuple[dict, ConversionReport]:
    """HF Qwen3(-VL)-MoE text state dict → our VLM params subtree + report
    (reference serves this family via vLLM EP, models/vllm_qwen.py:313-349).

    Accepts the bare text-model layout (``embed_tokens.weight``, ...) and
    prefixed exports (``model.`` / ``model.language_model.``). Expert
    tensors map verbatim: HF fuses gate|up as ``experts.gate_up_proj``
    [E, D, 2H] and ``experts.down_proj`` [E, H, D] — exactly our MoEFFN's
    parameter layout."""
    sd = dict(state_dict)
    report = ConversionReport()

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    prefix = ""
    for cand in ("", "model.", "model.language_model.", "language_model.model."):
        if f"{cand}embed_tokens.weight" in sd:
            prefix = cand
            break
    params: dict = {"embed": {"embedding": take(f"{prefix}embed_tokens.weight")}}
    for i in range(n_layers):
        e = f"{prefix}layers.{i}."

        def lin(name: str) -> dict:
            return {"kernel": take(f"{e}{name}.weight").T}

        params[f"layer_{i}"] = {
            "ln1": {"scale": take(f"{e}input_layernorm.weight")},
            "ln2": {"scale": take(f"{e}post_attention_layernorm.weight")},
            "q": lin("self_attn.q_proj"),
            "k": lin("self_attn.k_proj"),
            "v": lin("self_attn.v_proj"),
            "o": lin("self_attn.o_proj"),
            "q_norm": {"scale": take(f"{e}self_attn.q_norm.weight")},
            "k_norm": {"scale": take(f"{e}self_attn.k_norm.weight")},
            "moe": {
                "router": {"kernel": take(f"{e}mlp.gate.weight").T},
                "gate_up": take(f"{e}mlp.experts.gate_up_proj"),
                "down": take(f"{e}mlp.experts.down_proj"),
            },
        }
    params["ln_f"] = {"scale": take(f"{prefix}norm.weight")}
    mapped = set(report.mapped)
    for k in sd:
        if k in mapped:
            continue
        if k.startswith(("visual.", "model.visual.")):
            report.vision_skipped.append(k)
        elif k.endswith("lm_head.weight"):
            head, emb = _t(sd[k]), params["embed"]["embedding"]
            # drop the head ONLY when it is redundant (equals the embedding)
            # AND the target config does not expect a separate lm_head: an
            # untied config whose head happens to equal the embedding must
            # still carry lm_head or the restore fails spuriously. Pass
            # tied_embeddings from the target VLMConfig; None keeps the
            # equality heuristic for bare state-dict conversions.
            redundant = head.shape == emb.shape and np.array_equal(head, emb)
            if redundant and tied_embeddings is not False:
                report.mapped.append(k)
            else:
                params["lm_head"] = {"kernel": head.T}
                report.mapped.append(k)
        else:
            report.unmapped.append(k)
    logger.info(
        "converted Qwen3-MoE LM: %d tensors mapped, %d vision skipped, %d unmapped",
        len(report.mapped),
        len(report.vision_skipped),
        len(report.unmapped),
    )
    return {"params": params}, report


def qwen2_vision_config(hf_vision_config, **overrides):
    """Our QwenVisionConfig from an HF Qwen2VLVisionConfig OR
    Qwen2_5_VLVisionConfig (detected by ``out_hidden_size``, the 2.5
    layout where ``hidden_size`` is the EMBED dim)."""
    from cosmos_curate_tpu.models.vlm.vision_qwen import QwenVisionConfig

    c = hf_vision_config
    if hasattr(c, "out_hidden_size"):  # Qwen2.5-VL
        kw = dict(
            depth=c.depth,
            embed_dim=c.hidden_size,
            num_heads=c.num_heads,
            hidden_size=c.out_hidden_size,
            intermediate_size=c.intermediate_size,
            patch_size=c.patch_size,
            temporal_patch_size=c.temporal_patch_size,
            spatial_merge_size=c.spatial_merge_size,
            in_channels=c.in_channels,
            variant="qwen2_5",
            window_size=c.window_size,
            fullatt_block_indexes=tuple(c.fullatt_block_indexes),
        )
    else:
        kw = dict(
            depth=c.depth,
            embed_dim=c.embed_dim,
            num_heads=c.num_heads,
            hidden_size=c.hidden_size,
            mlp_ratio=c.mlp_ratio,
            patch_size=c.patch_size,
            temporal_patch_size=c.temporal_patch_size,
            spatial_merge_size=c.spatial_merge_size,
            in_channels=c.in_channels,
        )
    kw.update(overrides)
    return QwenVisionConfig(**kw)


def qwen3_vision_config(hf_vision_config, **overrides):
    """Our QwenVisionConfig (variant="qwen3") from an HF
    Qwen3VL(Moe)VisionConfig."""
    from cosmos_curate_tpu.models.vlm.vision_qwen import QwenVisionConfig

    c = hf_vision_config
    kw = dict(
        depth=c.depth,
        embed_dim=c.hidden_size,
        num_heads=c.num_heads,
        hidden_size=c.out_hidden_size,
        intermediate_size=c.intermediate_size,
        patch_size=c.patch_size,
        temporal_patch_size=c.temporal_patch_size,
        spatial_merge_size=c.spatial_merge_size,
        in_channels=c.in_channels,
        variant="qwen3",
        pos_embed_side=int(round(c.num_position_embeddings**0.5)),
        deepstack_indexes=tuple(c.deepstack_visual_indexes),
    )
    kw.update(overrides)
    return QwenVisionConfig(**kw)


def convert_qwen3_vision(state_dict, cfg) -> tuple[dict, ConversionReport]:
    """HF Qwen3-VL vision tensors → our qwen3-variant tower params.

    Accepts the standalone vision-model layout and ``model.visual.`` /
    ``visual.`` prefixed exports. Conv3d patchify flattens exactly like
    convert_qwen2_vision; the learned pos-embed Embedding maps verbatim;
    deepstack mergers land as ds{level}_{norm,fc1,fc2}."""
    sd = dict(state_dict)
    report = ConversionReport()
    prefix = ""
    for cand in ("", "visual.", "model.visual."):
        if f"{cand}patch_embed.proj.weight" in sd:
            prefix = cand
            break

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    def lin(stem: str) -> dict:
        return {"kernel": take(f"{stem}.weight").T, "bias": take(f"{stem}.bias")}

    def ln(stem: str) -> dict:
        return {"scale": take(f"{stem}.weight"), "bias": take(f"{stem}.bias")}

    conv = take(f"{prefix}patch_embed.proj.weight")  # [E, C, tps, ps, ps]
    params: dict = {
        "patch_embed": {
            "kernel": conv.reshape(conv.shape[0], -1).T,
            "bias": take(f"{prefix}patch_embed.proj.bias"),
        },
        "pos_embed": take(f"{prefix}pos_embed.weight"),
    }
    for i in range(cfg.depth):
        e = f"{prefix}blocks.{i}."
        params[f"block_{i}"] = {
            "ln1": ln(f"{e}norm1"),
            "ln2": ln(f"{e}norm2"),
            "qkv": lin(f"{e}attn.qkv"),
            "proj": lin(f"{e}attn.proj"),
            "fc1": lin(f"{e}mlp.linear_fc1"),
            "fc2": lin(f"{e}mlp.linear_fc2"),
        }
    params["ln_q"] = ln(f"{prefix}merger.norm")
    params["merger_fc1"] = lin(f"{prefix}merger.linear_fc1")
    params["merger_fc2"] = lin(f"{prefix}merger.linear_fc2")
    for level in range(len(cfg.deepstack_indexes)):
        d = f"{prefix}deepstack_merger_list.{level}."
        params[f"ds{level}_norm"] = ln(f"{d}norm")
        params[f"ds{level}_fc1"] = lin(f"{d}linear_fc1")
        params[f"ds{level}_fc2"] = lin(f"{d}linear_fc2")
    mapped = set(report.mapped)
    report.unmapped.extend(
        k for k in sd if k not in mapped and (not prefix or k.startswith(prefix))
    )
    logger.info(
        "converted Qwen3 vision tower: %d tensors mapped, %d unmapped",
        len(report.mapped), len(report.unmapped),
    )
    return {"params": params}, report


def convert_qwen2_vision(state_dict, depth: int) -> tuple[dict, ConversionReport]:
    """HF ``visual.*`` tensors → our QwenVisionTower params subtree.

    The Conv3d patchify (kernel == stride) becomes the dense patch_embed
    kernel: ``[E, C, tps, ps, ps]`` flattens to ``[E, patch_dim]`` and
    transposes — valid because both sides consume patches flattened in
    (C, tps, ps, ps) order (HF PatchEmbed.forward views exactly that
    shape; frames_to_patches emits it).
    """
    sd = dict(state_dict)
    report = ConversionReport()
    prefix = "visual."
    if f"{prefix}patch_embed.proj.weight" not in sd:
        if "model.visual.patch_embed.proj.weight" in sd:
            prefix = "model.visual."
        else:
            raise KeyError("no visual.* tensors found in state dict")

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    def lin(stem: str) -> dict:
        return {
            "kernel": take(f"{stem}.weight").T,
            "bias": take(f"{stem}.bias"),
        }

    def ln(stem: str) -> dict:
        return {"scale": take(f"{stem}.weight"), "bias": take(f"{stem}.bias")}

    # Qwen2.5-VL: RMSNorm blocks (weight-only norms) + SwiGLU MLP
    is_25 = f"{prefix}blocks.0.mlp.gate_proj.weight" in sd

    def rms(stem: str) -> dict:
        return {"scale": take(f"{stem}.weight")}

    conv = take(f"{prefix}patch_embed.proj.weight")  # [E, C, tps, ps, ps]
    params: dict = {"patch_embed": {"kernel": conv.reshape(conv.shape[0], -1).T}}
    for i in range(depth):
        e = f"{prefix}blocks.{i}."
        block = {
            "ln1": rms(f"{e}norm1") if is_25 else ln(f"{e}norm1"),
            "ln2": rms(f"{e}norm2") if is_25 else ln(f"{e}norm2"),
            "qkv": lin(f"{e}attn.qkv"),
            "proj": lin(f"{e}attn.proj"),
        }
        if is_25:
            block["gate"] = lin(f"{e}mlp.gate_proj")
            block["up"] = lin(f"{e}mlp.up_proj")
            block["down"] = lin(f"{e}mlp.down_proj")
        else:
            block["fc1"] = lin(f"{e}mlp.fc1")
            block["fc2"] = lin(f"{e}mlp.fc2")
        params[f"block_{i}"] = block
    params["ln_q"] = rms(f"{prefix}merger.ln_q") if is_25 else ln(f"{prefix}merger.ln_q")
    params["merger_fc1"] = lin(f"{prefix}merger.mlp.0")
    params["merger_fc2"] = lin(f"{prefix}merger.mlp.2")

    mapped = set(report.mapped)
    for k in sd:
        if k not in mapped and k.startswith(prefix):
            report.unmapped.append(k)
    logger.info(
        "converted Qwen2-VL vision: %d tensors mapped, %d unmapped",
        len(report.mapped),
        len(report.unmapped),
    )
    return {"params": params}, report


def convert_qwen2_vl(
    state_dict, n_layers: int, vision_depth: int
) -> tuple[dict, dict, ConversionReport]:
    """Full Qwen2-VL checkpoint → (lm_params, vision_params, report).

    Unlike ``convert_qwen2_lm`` alone, nothing is "intentionally skipped":
    a Qwen2-VL checkpoint converts completely, so ``report.vision_skipped``
    is empty and multimodal forwards see the trained tower.
    """
    lm_params, lm_report = convert_qwen2_lm(state_dict, n_layers)
    vision_params, v_report = convert_qwen2_vision(state_dict, vision_depth)
    report = ConversionReport(
        mapped=lm_report.mapped + v_report.mapped,
        vision_skipped=[],
        unmapped=[u for u in lm_report.unmapped if not u.startswith(("visual.", "model.visual."))]
        + v_report.unmapped,
    )
    return lm_params, vision_params, report


def merge_lm_params(init_tree: dict, lm_params: dict) -> dict:
    """Overlay converted LM params onto a full init tree (vision tower +
    projector keep their existing — e.g. self-trained — values)."""
    import flax

    merged = flax.core.unfreeze(init_tree)
    for key, val in lm_params["params"].items():
        merged["params"][key] = val
    return merged


def merge_vision_params(init_tree: dict, vision_params: dict) -> dict:
    """Overlay converted Qwen vision-tower params under the VLM's
    ``vision`` submodule (plus the top-level merger ln_q, which QwenVisionTower
    owns)."""
    import flax

    merged = flax.core.unfreeze(init_tree)
    merged["params"]["vision"] = vision_params["params"]
    return merged
