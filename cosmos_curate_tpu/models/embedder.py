"""Video embedder: frame features + temporal transformer -> one embedding.

Equivalent capability of the reference's video embedders (InternVideo2
cosmos_curate/models/internvideo2_mm.py:334, Cosmos-Embed1
models/cosmos_embed1.py:42 — 256/512/768-d video embeddings used for
semantic dedup and search). Our own architecture, TPU-first: a (shared) ViT
encodes N sampled frames in one batched pass, a small temporal transformer
with a learned query token pools them into a single L2-normalized vector.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.layers import TransformerBlock
from cosmos_curate_tpu.models.vit import VIT_B_16, VIT_TINY_TEST, ViT, ViTConfig, preprocess_frames


@dataclass(frozen=True)
class VideoEmbedConfig:
    vit: ViTConfig = VIT_B_16
    temporal_layers: int = 4
    temporal_heads: int = 8
    num_frames: int = 8
    output_dim: int = 768


VIDEO_EMBED_BASE = VideoEmbedConfig()
# The reference ships two embedder families (InternVideo2 512-d,
# Cosmos-Embed1 256/768-d, SURVEY.md §2.3); these configs cover the same
# output spaces under one architecture.
VIDEO_EMBED_512 = VideoEmbedConfig(output_dim=512)
VIDEO_EMBED_256 = VideoEmbedConfig(temporal_layers=2, output_dim=256)
VIDEO_EMBED_TINY_TEST = VideoEmbedConfig(
    vit=VIT_TINY_TEST, temporal_layers=1, temporal_heads=2, num_frames=4, output_dim=32
)

# variant name -> (config, registry model id): each output space has its own
# weights slot — a 768-d checkpoint cannot serve the 512/256-d variants.
VIDEO_EMBED_VARIANTS = {
    "video": (VIDEO_EMBED_BASE, "video-embed-tpu"),
    "video-512": (VIDEO_EMBED_512, "video-embed-512-tpu"),
    "video-256": (VIDEO_EMBED_256, "video-embed-256-tpu"),
}

registry.register_model("video-embed-512-tpu", "512-d temporal-transformer video embedder")
registry.register_model("video-embed-256-tpu", "256-d temporal-transformer video embedder")


class TemporalPooler(nn.Module):
    cfg: VideoEmbedConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, frame_feats):
        """frame_feats: [B, T, D] -> [B, output_dim]."""
        b, t, d = frame_feats.shape
        query = self.param("query", nn.initializers.normal(0.02), (1, 1, d), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(query.astype(self.dtype), (b, 1, d)), frame_feats.astype(self.dtype)],
            axis=1,
        )
        pos = self.param(
            "time_embed", nn.initializers.normal(0.02), (1, self.cfg.num_frames + 1, d), jnp.float32
        )
        x = x + pos[:, : t + 1].astype(self.dtype)
        head_dim = d // self.cfg.temporal_heads
        for i in range(self.cfg.temporal_layers):
            x = TransformerBlock(self.cfg.temporal_heads, head_dim, dtype=self.dtype, name=f"t{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln")(x[:, 0])
        return nn.Dense(self.cfg.output_dim, param_dtype=jnp.float32, name="proj")(x)


class VideoEmbedModel(nn.Module):
    cfg: VideoEmbedConfig

    @nn.compact
    def __call__(self, frames_u8):
        """frames_u8: uint8 [B, T, H, W, 3] -> [B, output_dim] normalized."""
        b, t = frames_u8.shape[:2]
        pixels = preprocess_frames(
            frames_u8, image_size=self.cfg.vit.image_size, mode=self.cfg.vit.preprocess
        )
        pooled, _ = ViT(self.cfg.vit, name="vit")(pixels.reshape(b * t, *pixels.shape[2:]))
        feats = pooled.reshape(b, t, -1)
        emb = TemporalPooler(self.cfg, name="pooler")(feats).astype(jnp.float32)
        return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)


@functools.lru_cache(maxsize=8)
def _jitted_apply(cfg: VideoEmbedConfig):
    """Compiled apply shared across instances of the same config — jit
    caches are per function object, so per-instance jits would recompile
    (and defeat warmup) every time a stage constructs its own model.
    The frame batch (arg 1) is donated on TPU/GPU: HBM churn, not a
    result alias (uint8 in, f32 out)."""
    from cosmos_curate_tpu.models.device_pipeline import donate_kwargs

    model = VideoEmbedModel(cfg)
    return jax.jit(model.apply, **donate_kwargs(1))


class VideoEmbedder(ModelInterface):
    MODEL_ID = "video-embed-tpu"

    def __init__(
        self, cfg: VideoEmbedConfig = VIDEO_EMBED_BASE, *, model_id: str | None = None
    ) -> None:
        self.cfg = cfg
        self.model_id = model_id or self.MODEL_ID
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.model_id]

    @property
    def embedding_dim(self) -> int:
        return self.cfg.output_dim

    def setup(self) -> None:
        model = VideoEmbedModel(self.cfg)

        def init(seed: int):
            s = self.cfg.vit.image_size
            dummy = jnp.zeros((1, self.cfg.num_frames, s, s, 3), jnp.uint8)
            return model.init(jax.random.PRNGKey(seed), dummy)

        self._params = registry.load_params(self.model_id, init)
        self._apply = _jitted_apply(self.cfg)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

        self._pipeline = DevicePipeline(f"embed/{self.model_id}", self._apply)

    def sample_frame_indices(self, total: int) -> np.ndarray:
        """Uniform temporal sampling to cfg.num_frames indices."""
        n = self.cfg.num_frames
        if total <= 0:
            return np.zeros(0, np.int64)
        return np.linspace(0, max(total - 1, 0), n).round().astype(np.int64)

    def encode_clips(self, clips_frames: np.ndarray) -> np.ndarray:
        """uint8 [B, T, H, W, 3] -> float32 [B, output_dim] normalized.
        Dispatched through the shared DevicePipeline: pow2 bucket
        micro-batches, H2D/compute/D2H overlapped, readback deferred."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        return self._pipeline.run(self._params, clips_frames)
