"""InternVideo2 checkpoint -> Flax tower conversion.

Maps a stage-2 InternVideo2 state dict (the `.pth` the reference loads in
internvideo2.py:728 `pretrain_internvideo2_1b_patch14_224`, optionally
wrapped in the multimodal model whose tensors carry a `vision_encoder.`
prefix, internvideo2_mm.py:74) onto
:class:`cosmos_curate_tpu.models.internvideo2.InternVideo2Tower` params.

Training-only tensors are intentionally skipped and recorded in the
report: the masked-distillation decoders (`clip_decoder.*`,
`final_clip_decoder.*`), their private position table (`clip_pos_embed*`),
and the image-only table (`img_pos_embed*`) — `get_vid_feat` inference
(internvideo2_mm.py:203) never touches them.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.convert_qwen import ConversionReport, _t
from cosmos_curate_tpu.models.internvideo2 import IV2Config

# prefixes of tensors the inference tower deliberately does not carry
_SKIP_PREFIXES = (
    "clip_decoder.",
    "final_clip_decoder.",
    "clip_pos_embed",
    "clip_img_pos_embed",
    "img_pos_embed",
    "text_encoder.",
    "text_proj.",
    "temp",
    "itm_head.",
)


def convert_internvideo2(state_dict, cfg: IV2Config) -> tuple[dict, ConversionReport]:
    """State dict -> ({'params': tower_params}, report).

    Accepts the bare tower layout (`patch_embed.proj.weight`, ...) and the
    multimodal wrapper layout (`vision_encoder.` prefix + top-level
    `vision_proj.{weight,bias}`). A missing `vision_proj` (bare tower
    checkpoint without the contrastive head) is reported unmapped —
    the caller must decide whether pooled-only embeddings are acceptable.
    """
    sd = dict(state_dict)
    # normalize the multimodal wrapper prefix away; vision_proj stays
    if any(k.startswith("vision_encoder.") for k in sd):
        sd = {
            (k[len("vision_encoder.") :] if k.startswith("vision_encoder.") else k): v
            for k, v in sd.items()
        }
    report = ConversionReport()

    def take(name: str) -> np.ndarray:
        report.mapped.append(name)
        return _t(sd[name])

    def lin(name: str, bias: bool = True) -> dict:
        d = {"kernel": take(f"{name}.weight").T}
        if bias:
            d["bias"] = take(f"{name}.bias")
        return d

    params: dict = {}
    # Conv3d [C, 3, kt, kh, kw] -> dense kernel [patch_dim, C]; the flatten
    # order (c, kt, kh, kw) matches frames_to_tubelets
    w = take("patch_embed.proj.weight")
    params["patch_proj"] = {
        "kernel": w.reshape(w.shape[0], -1).T,
        "bias": take("patch_embed.proj.bias"),
    }
    params["cls"] = take("cls_token")
    params["pos_embed"] = take("pos_embed")
    for i in range(cfg.depth):
        e = f"blocks.{i}."
        blk = {
            "ln1": {"scale": take(f"{e}norm1.weight")},
            "qkv": lin(f"{e}attn.qkv", bias=cfg.qkv_bias),
            "attn_out": lin(f"{e}attn.proj"),
            "ls1": take(f"{e}ls1.gamma"),
            "ln2": {"scale": take(f"{e}norm2.weight")},
            "fc1": lin(f"{e}mlp.fc1"),
            "fc2": lin(f"{e}mlp.fc2"),
            "ls2": take(f"{e}ls2.gamma"),
        }
        if cfg.qk_normalization:
            blk["q_norm"] = {"scale": take(f"{e}attn.q_norm.weight")}
            blk["k_norm"] = {"scale": take(f"{e}attn.k_norm.weight")}
        params[f"block_{i}"] = blk
    # attentive pooling projector: separate q/k/v weights with separate
    # bias parameters (qkv_bias=True path, internvideo2.py:59)
    cp = "clip_projector."
    params["pool"] = {
        "ln_q": {
            "scale": take(f"{cp}norm1_q.weight"),
            "bias": take(f"{cp}norm1_q.bias"),
        },
        "ln_k": {
            "scale": take(f"{cp}norm1_k.weight"),
            "bias": take(f"{cp}norm1_k.bias"),
        },
        "ln_v": {
            "scale": take(f"{cp}norm1_v.weight"),
            "bias": take(f"{cp}norm1_v.bias"),
        },
        "q": {
            "kernel": take(f"{cp}cross_attn.q.weight").T,
            "bias": take(f"{cp}cross_attn.q_bias"),
        },
        "k": {
            "kernel": take(f"{cp}cross_attn.k.weight").T,
            "bias": take(f"{cp}cross_attn.k_bias"),
        },
        "v": {
            "kernel": take(f"{cp}cross_attn.v.weight").T,
            "bias": take(f"{cp}cross_attn.v_bias"),
        },
        "out": lin(f"{cp}cross_attn.proj"),
    }
    if "vision_proj.weight" in sd:
        params["vision_proj"] = lin("vision_proj")
    mapped = set(report.mapped)
    for k in sd:
        if k in mapped:
            continue
        if k.startswith(_SKIP_PREFIXES):
            report.vision_skipped.append(k)
        else:
            report.unmapped.append(k)
    if "vision_proj.weight" not in sd:
        report.unmapped.append("vision_proj.weight (absent in checkpoint)")
    return {"params": params}, report
