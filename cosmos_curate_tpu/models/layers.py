"""Shared Flax building blocks with tensor-parallel sharding annotations.

TPU-first design: every weight matrix carries a ``nn.with_partitioning``
annotation over the ``model`` mesh axis following the standard Megatron
sharding recipe (public technique): attention QKV and MLP-up shard their
*output* features; attention-out and MLP-down shard their *input* features,
so each block needs exactly one ``psum`` (inserted automatically by XLA at
the sharded->replicated boundary). Replaces the reference's reliance on
vLLM-internal NCCL TP (SURVEY.md §2.7).

Compute dtype is bf16 by default (MXU-native); params stay f32.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from cosmos_curate_tpu.parallel.axes import MODEL as MODEL_AXIS

Dtype = Any


def dense(features: int, shard: str | None, name: str | None = None, use_bias: bool = True, dtype=jnp.bfloat16):
    """Dense with kernel sharding: shard='out' partitions output features,
    'in' partitions input features, None replicates."""
    if shard == "out":
        spec = (None, MODEL_AXIS)
        bias_spec = (MODEL_AXIS,)
    elif shard == "in":
        spec = (MODEL_AXIS, None)
        bias_spec = None  # bias on replicated output
    else:
        spec = (None, None)
        bias_spec = None
    kernel_init = nn.with_partitioning(nn.initializers.xavier_uniform(), spec)
    bias_init = nn.initializers.zeros
    if bias_spec is not None:
        bias_init = nn.with_partitioning(nn.initializers.zeros, bias_spec)
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=kernel_init,
        bias_init=bias_init,
        name=name,
    )


# Above this sequence length self-attention is HBM-bound and the Pallas
# flash kernel wins (measured 1.9x at S=8192 on v5e); below it XLA's own
# fusion is as good or better, so we let the compiler handle it.
FLASH_MIN_SEQ = 2048


def _use_flash(s: int, mask) -> bool:
    import jax

    return (
        mask is None
        and s >= FLASH_MIN_SEQ
        and jax.devices()[0].platform == "tpu"
    )


class Attention(nn.Module):
    """Multi-head attention, heads sharded over the model axis."""

    num_heads: int
    head_dim: int
    dtype: Dtype = jnp.bfloat16
    causal: bool = False

    @nn.compact
    def __call__(self, x, mask=None):
        inner = self.num_heads * self.head_dim
        q = dense(inner, "out", name="q", dtype=self.dtype)(x)
        k = dense(inner, "out", name="k", dtype=self.dtype)(x)
        v = dense(inner, "out", name="v", dtype=self.dtype)(x)
        b, s, _ = x.shape
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        if _use_flash(s, mask):
            from cosmos_curate_tpu.ops.flash_attention import flash_attention

            out = flash_attention(
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
                causal=self.causal,
            ).transpose(0, 2, 1, 3)
        else:
            scale = self.head_dim**-0.5
            logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k).astype(jnp.float32)
            if self.causal:
                cm = jnp.tril(jnp.ones((s, s), bool))
                logits = jnp.where(cm[None, None], logits, -jnp.inf)
            if mask is not None:
                logits = jnp.where(mask, logits, -jnp.inf)
            probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
            probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(self.dtype), v)
        out = out.reshape(b, s, inner)
        return dense(x.shape[-1], "in", name="out", dtype=self.dtype)(out)


def quick_gelu(x):
    """OpenAI CLIP's activation: x * sigmoid(1.702 x)."""
    return x * jax.nn.sigmoid(1.702 * x)


_ACTIVATIONS: dict[str, Callable] = {"gelu": nn.gelu, "quick_gelu": quick_gelu}


class MlpBlock(nn.Module):
    hidden_mult: float = 4.0
    dtype: Dtype = jnp.bfloat16
    act: str = "gelu"

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        h = dense(int(d * self.hidden_mult), "out", name="up", dtype=self.dtype)(x)
        h = _ACTIVATIONS[self.act](h)
        return dense(d, "in", name="down", dtype=self.dtype)(h)


class TransformerBlock(nn.Module):
    num_heads: int
    head_dim: int
    hidden_mult: float = 4.0
    dtype: Dtype = jnp.bfloat16
    causal: bool = False
    act: str = "gelu"
    ln_eps: float = 1e-6

    @nn.compact
    def __call__(self, x, mask=None):
        y = nn.LayerNorm(dtype=jnp.float32, epsilon=self.ln_eps, name="ln1")(x)
        x = x + Attention(
            self.num_heads, self.head_dim, dtype=self.dtype, causal=self.causal, name="attn"
        )(y, mask)
        y = nn.LayerNorm(dtype=jnp.float32, epsilon=self.ln_eps, name="ln2")(x)
        x = x + MlpBlock(self.hidden_mult, dtype=self.dtype, act=self.act, name="mlp")(y)
        return x
