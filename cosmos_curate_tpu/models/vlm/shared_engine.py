"""Process-level shared caption engine registry: cross-job continuous
batching.

Equivalent capability of the reference's single vLLM deployment serving
every caption consumer (cosmos_curate/models/vllm_interface.py — one engine
process, many request streams): engines are registered per
``(model, dtype, mesh)``, so every caption-family stage — captioning,
enhancement, semantic filter, per-event — and every CONCURRENT pipeline in
the process (the pipelined runner's pinned caption workers included)
submits into ONE engine per served model. Requests carry an ``owner`` tag
and the engine's admission interleaves owners fairly (Orca-style
iteration-level scheduling across jobs), so two pipelines decode in one
continuous batch instead of each paying for a half-idle private engine —
and weights + the KV block pool exist once per model, not once per
pipeline.

The key deliberately EXCLUDES serving geometry (max_batch, kv_lanes,
block_size): sharing one engine across stages that ask for different batch
sizes is the point, so the first creator's geometry wins and later getters
join it (logged when they asked for something else).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

from cosmos_curate_tpu.models.vlm.engine import CaptionEngine
from cosmos_curate_tpu.models.vlm.model import VLMConfig
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class EngineKey:
    """What must match for two callers to share one engine: the served
    checkpoint (model_id — the same architecture under two weight ids must
    NOT share, the second would caption with the first's weights), the
    architecture (cfg), the compute dtype, the device mesh the engine was
    built on, and the SHARDING geometry over that mesh (two engines sharding
    the KV pool over different model-axis extents compile different
    programs and must never collide on one registry slot)."""

    model_id: str
    cfg: VLMConfig
    dtype: str
    mesh: tuple
    geometry: tuple = ()


class SharedCaptionEngine:
    """The process-level registry. All methods are classmethods — there is
    exactly one registry per process, like the device mesh itself."""

    _lock = threading.Lock()
    _engines: "dict[EngineKey, CaptionEngine]" = {}
    # per-key build locks: engine setup + weight loading can take minutes,
    # and must not stall registry reads or a DIFFERENT model's creation
    _building: "dict[EngineKey, threading.Lock]" = {}

    @staticmethod
    def _mesh_fingerprint() -> tuple:
        import jax

        return tuple((d.platform, int(d.id)) for d in jax.devices())

    @staticmethod
    def _mesh_geometry(mesh) -> tuple:
        """Hashable (axis, extent) tuple for a serving mesh (empty when
        unsharded) — matches CaptionEngine.mesh_geometry."""
        if mesh is None:
            return ()
        return tuple((str(n), int(mesh.shape[n])) for n in mesh.axis_names)

    @classmethod
    def key_for(
        cls, cfg: VLMConfig, model_id: str, dtype: str = "bfloat16", mesh: Any = None
    ) -> EngineKey:
        return EngineKey(
            model_id, cfg, dtype, cls._mesh_fingerprint(), cls._mesh_geometry(mesh)
        )

    @classmethod
    def get(
        cls,
        cfg: VLMConfig,
        *,
        model_id: str,
        max_batch: int = 8,
        kv_lanes: tuple | None = None,
        tokenizer: Any = None,
        dtype: str = "bfloat16",
        async_prep: bool = True,
        loader: "Callable[[CaptionEngine], Any] | None" = None,
        mesh: Any = None,
    ) -> CaptionEngine:
        """The shared engine for (model, dtype, mesh, sharding geometry),
        building + setting it up on first use. ``loader`` (called once,
        with the fresh engine) returns the params to serve — weight loading
        stays the caller's policy (require_weights etc.) without the
        registry re-running it per stage. ``mesh`` selects the head-parallel
        paged-attention geometry and is part of the key: differently
        sharded engines never share."""
        key = cls.key_for(cfg, model_id, dtype, mesh=mesh)

        def existing() -> "CaptionEngine | None":
            engine = cls._engines.get(key)
            if engine is None:
                return None
            actual = [(l.length, l.n_slots) for l in engine.lanes]
            wanted = (
                sorted((int(a), int(b)) for a, b in kv_lanes)
                if kv_lanes is not None
                else None
            )
            if (wanted is not None and wanted != actual) or (
                wanted is None and max_batch != engine.max_batch
            ):
                logger.info(
                    "sharing caption engine %s: requested geometry "
                    "(max_batch=%s, kv_lanes=%s) differs from the creator's "
                    "lanes %s (geometry is fixed at first creation)",
                    model_id,
                    max_batch,
                    kv_lanes,
                    actual,
                )
            return engine

        with cls._lock:
            engine = existing()
            if engine is not None:
                return engine
            build_lock = cls._building.setdefault(key, threading.Lock())
        # build OUTSIDE the registry lock (setup compiles, loader may pull
        # checkpoints for minutes) — only same-key callers wait
        with build_lock:
            with cls._lock:
                engine = existing()
            if engine is not None:
                return engine
            engine = CaptionEngine(
                cfg,
                max_batch=max_batch,
                tokenizer=tokenizer,
                kv_lanes=kv_lanes,
                # production engines prep in the background so vision
                # encoding of request N+1 overlaps decode of request N
                async_prep=async_prep,
                mesh=mesh,
            )
            engine.setup()
            if loader is not None:
                engine.params = loader(engine)
            with cls._lock:
                cls._engines[key] = engine
                cls._building.pop(key, None)
            return engine

    @classmethod
    def adopt(
        cls, engine: CaptionEngine, *, cfg: VLMConfig, model_id: str,
        dtype: str = "bfloat16",
    ) -> None:
        """Register an externally built engine (benchmarks seed their warm
        engine so the CaptionStage pass shares it instead of doubling
        weight memory). The engine's own mesh decides the geometry slot."""
        with cls._lock:
            key = cls.key_for(cfg, model_id, dtype, mesh=getattr(engine, "mesh", None))
            cls._engines[key] = engine

    @classmethod
    def stats(cls) -> dict:
        """Registry-wide occupancy + per-owner gauges, keyed by model_id —
        the cross-job observability surface."""
        with cls._lock:
            engines = dict(cls._engines)
        out: dict[str, dict] = {}
        for key, engine in engines.items():
            out[key.model_id] = {
                "kv_blocks_used": engine.kv_blocks_used,
                "kv_blocks_total": engine.kv_blocks_total,
                "prefix_block_refs": engine.prefix_block_refs,
                "interleaved_decode_steps": engine.interleaved_decode_steps,
                "owners": engine.owner_stats(),
            }
        return out

    @classmethod
    def reset(cls) -> None:
        """Drop every registered engine (tests). Engines are shut down so
        prep threads stop and prefix-cache block references release."""
        with cls._lock:
            engines = list(cls._engines.values())
            cls._engines.clear()
            cls._building.clear()
        for engine in engines:
            try:
                engine.shutdown()
            except Exception:  # a wedged prep thread must not fail teardown
                logger.exception("engine shutdown failed during registry reset")
