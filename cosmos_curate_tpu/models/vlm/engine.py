"""Continuous-batching caption engine.

Equivalent capability of the reference's vLLM engine driver
(cosmos_curate/models/vllm_interface.py:390-703 — ``add_request``/``step``
in-flight batching with two-stage caption refinement; async variant
vllm_async_stage.py). TPU-first re-design:

- **paged KV cache**: KV memory is ONE block pool ``[L, n_blocks,
  block_size, Hkv, Dh]`` (models/vlm/paged_kv.py) and every admitted slot
  holds a block *table* instead of a worst-case-length cache row — a
  request reserves ``ceil((prompt + max_new + 1) / block_size)`` blocks, so
  pool occupancy (not slot count) is the admission limit, vLLM
  PagedAttention-style. Prefill/decode programs gather each slot's blocks
  into a contiguous lane-length view (the exact shapes the slot-row engine
  compiled — greedy outputs stay byte-identical), run the unchanged model,
  and scatter the written blocks back. Lanes survive as decode-batch
  shapes: a lane bounds the gathered view length and groups slots into one
  static-shape decode program.
- **continuous batching**: slots join/leave between decode steps; the decode
  step always runs the full slot batch with an active mask (idle rows write
  into the reserved garbage block — dead work, bounded by max_batch, in
  exchange for zero recompiles).
- **tokens/s** is tracked per engine — THE caption-throughput metric
  (reference docs/curator/design/SPEED_OF_LIGHT.md).
- **refcounted shared-prefix blocks**: every caption request in a run opens
  with the same system-prompt/template text (SGLang RadixAttention's core
  insight, Zheng et al. 2024 — and the caption workload is its best case:
  the prefix is identical across ALL requests of a (flavor,
  prompt_variant)). The prefix prefills ONCE into pool blocks that admitted
  requests REFERENCE through their block tables with a refcount — zero
  device copies at admission (the round-7 per-slot ``insert_prefix`` copy is
  gone); copy-on-write duplicates only a partially-filled shared tail
  block. Per-request prefill starts at the prefix boundary with absolute
  rope positions, producing byte-identical greedy output while skipping
  ``len(prefix) x (requests - 1)`` prefill tokens. Evicting a prefix whose
  blocks are still referenced defers the free to the last referencing slot.
- **cross-job continuous batching**: requests carry an ``owner`` and the
  admission loop interleaves owners fairly (least-recently-admitted owner
  first, per-owner in-flight cap), so several concurrent pipelines/stages
  sharing one engine (models/vlm/shared_engine.py) decode in ONE batch
  instead of serializing whole jobs — Orca-style iteration-level
  scheduling across jobs.
- **prep/decode overlap** (``async_prep=True``): a background thread runs
  vision encoding + token embedding for waiting requests while the caller's
  ``step()`` loop decodes, so frame prep of request N+1 hides behind decode
  of request N instead of serializing with it.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.models.batching import next_pow2
from cosmos_curate_tpu.models.tokenizer import ByteTokenizer, default_caption_tokenizer
from cosmos_curate_tpu.models.vlm.model import VLM, VLMConfig, init_cache
from cosmos_curate_tpu.models.vlm.paged_kv import (
    BlockAllocator,
    PoolExhausted,
    gather_block_views,
    init_block_pool,
    scatter_block_views,
)

# full sampling surface (top_p/min_p/penalties/min_tokens) lives in
# models/vlm/sampling.py; re-exported here for the existing import paths
from cosmos_curate_tpu.models.vlm.sampling import SamplingConfig, sample_token
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CaptionRequest:
    request_id: str
    prompt_ids: list[int]
    frames: np.ndarray | None = None  # uint8 [N, H, W, 3]
    # rate the frames were sampled at (frames/sec of source time); drives
    # Qwen2.5-VL's absolute-time temporal m-rope (None = unscaled)
    frame_fps: float | None = None
    # text tokens embedded BEFORE the vision block (chat templates put the
    # system turn + <|vision_start|> ahead of the image pads); prompt_ids
    # follow the vision block
    prefix_ids: list[int] = field(default_factory=list)
    sampling: SamplingConfig = field(default_factory=SamplingConfig)
    # called with the finished text; may return a follow-up request
    # (two-stage caption refinement, reference vllm_interface.py:543)
    on_complete: Callable[[str], "CaptionRequest | None"] | None = None
    metadata: dict[str, Any] = field(default_factory=dict)
    # set by add_request: which caller's run_until_complete owns this request
    # (several caption-family stages share one engine; see run_until_complete)
    owner: Any = None
    # Whether this request's text prefix may be served from / inserted into
    # the shared-prefix KV cache. Stages set False for one-shot prefixes
    # (the refinement pass bakes the stage-1 caption into its prefix, so
    # caching it would only thrash the LRU).
    share_prefix: bool = True
    # Encoded vision-tower output reused across passes of the SAME frames
    # (the engine fills this after the first encode; a refinement follow-up
    # carrying the identical frames array inherits it automatically).
    vision_features: Any = field(default=None, repr=False)


@dataclass
class _Slot:
    request: CaptionRequest
    position: int  # next cache position to write (== current length)
    # next ROPE position — under m-rope this lags the cache position
    # (vision tokens share t/h/w coordinates; text resumes at max(grid)+1)
    rope_position: int = 0
    generated: list[int] = field(default_factory=list)
    # per-request generator when sampling.seed is set (reproducible
    # captions regardless of batch interleaving); None = engine-shared rng
    rng: np.random.Generator | None = None
    # incrementally decoded output bytes (exact: decode is per-token byte
    # concatenation) — stop-string checks scan a bounded tail of this
    raw: bytearray = field(default_factory=bytearray)
    # prompt+output token counts maintained incrementally for penalties
    # (None when no penalty is configured)
    penalty_counts: dict[int, int] | None = None


def _truncate_at_stop(text: str, stops: tuple[str, ...]) -> str | None:
    """Text before the EARLIEST stop-string match (tuple order must not
    matter), or None when nothing matches."""
    idx = min((i for i in (text.find(s) for s in stops) if i >= 0), default=-1)
    return text[:idx] if idx >= 0 else None


@dataclass
class CaptionResult:
    request_id: str
    text: str
    num_prompt_tokens: int
    num_output_tokens: int
    metadata: dict[str, Any] = field(default_factory=dict)
    owner: Any = None


@dataclass
class _VisionFeatures:
    """One window's encoded vision-tower output, cached on the request so a
    refinement follow-up over the SAME frames skips the tower entirely."""

    embeds: Any  # [T_vis, D] device array
    ds: np.ndarray | None  # qwen3 deepstack levels [L_ds, T_vis, D]
    grid: tuple[int, int, int] | None
    eff_fps: float | None
    n_tokens: int


@dataclass
class _Prepared:
    """A request after host/vision prep, ready for admission.

    ``embeds`` hold only the SUFFIX (everything after the shared text
    prefix) when ``base > 0``: the prefix's K/V come from the shared-prefix
    cache and are device-copied into the slot's cache rows at admission, so
    prefill starts at cache position ``base`` (rope positions stay
    absolute — ``rope`` rows are the suffix slice of the full layout)."""

    request: CaptionRequest
    embeds: np.ndarray  # [T_suffix, D] float32
    t_suffix: int
    rope: np.ndarray  # [T_suffix] or [T_suffix, 3]
    next_rope: int
    ds: np.ndarray | None  # [L_ds, T_suffix, D] deepstack (suffix-aligned)
    base: int = 0  # cached prefix length already in the KV cache
    prefix_key: tuple | None = None

    @property
    def total(self) -> int:
        return self.base + self.t_suffix


@dataclass
class _PrefixEntry:
    """One shared text prefix, prefilled ONCE and resident in pool blocks.

    Admitted requests reference ``blocks[:n_full]`` directly through their
    block tables (refcounted — zero device copies); a partially-filled
    ``tail_block`` (``length % block_size != 0``) is copy-on-write
    duplicated at admission, since the referencing slot's own K/V writes
    would otherwise extend into shared memory."""

    blocks: list[int]  # ceil(length / block_size) pool block ids
    n_full: int  # length // block_size — the directly-shareable prefix
    tail_block: int | None  # blocks[-1] when partially filled, else None
    length: int


@dataclass
class _BlockClaim:
    """The pool blocks one admitted slot holds: ``shared`` prefix blocks it
    incref'd (freed back to the prefix entry's refcount on release) and
    ``private`` blocks it owns outright (freed on release)."""

    shared: list[int]
    private: list[int]

    @property
    def all_blocks(self) -> list[int]:
        return self.shared + self.private


@dataclass
class _PendingPrefill:
    """A slot whose prompt is being prefilled chunk by chunk.

    Long prompts are admitted in fixed-size chunks interleaved with decode
    steps (vLLM chunked prefill, reference models/vllm_interface.py:543 +
    SPEED_OF_LIGHT.md:116-121): one prefill group no longer stalls every
    in-flight request's decode for its whole duration. The chunk program is
    the same compiled family as bucket prefill (static [N, C, D] shapes,
    per-row write_index), so chunking adds zero recompiles."""

    request: CaptionRequest
    embeds: np.ndarray  # [T, D] prompt embeds (suffix-only when base > 0)
    t_valid: int
    rope_pos: np.ndarray  # [T] or [T, 3]
    next_rope: int
    progress: int = 0  # prompt tokens already written to the cache
    # qwen3 deepstack visual features [L_ds, T, D] (zeros at text
    # positions), chunk-sliced alongside embeds; None otherwise
    ds: np.ndarray | None = None
    # cache offset where this prompt's writes start (= cached shared-prefix
    # length; chunk k writes at base + progress)
    base: int = 0


@dataclass
class _Lane:
    """One decode-batch shape: ``n_slots`` block tables of ``length``
    gathered positions each.

    With the paged pool, a lane no longer OWNS KV memory — blocks come from
    the engine-wide pool and occupancy is the admission limit. What a lane
    still bounds is compiled-program shape: its slots decode as one static
    ``[n_slots, length]`` batch, and ``length`` caps the gathered view (so
    short requests ride cheap short-view programs instead of the worst-case
    gather). ``table`` rows are the slot block tables; free/unused entries
    point at the reserved garbage block 0."""

    length: int
    base: int  # global slot-id offset (lane-local idx + base = public id)
    n_slots: int
    # [n_slots, length // block_size] int32 pool block ids (host-side; a
    # snapshot rides into every prefill/decode program call)
    table: np.ndarray | None = None
    slots: dict = field(default_factory=dict)
    pending: dict = field(default_factory=dict)
    # slot indices claimed by _admit's current grouping pass (released when
    # the group prefill runs)
    reserved: set = field(default_factory=set)
    # slot idx -> _BlockClaim, held from admission until release
    claims: dict = field(default_factory=dict)


class CaptionEngine:
    def __init__(
        self,
        cfg: VLMConfig,
        *,
        max_batch: int = 8,
        params: Any = None,
        tokenizer: ByteTokenizer | None = None,
        prefill_chunk: int = 256,
        kv_lanes: tuple[tuple[int, int], ...] | None = None,
        async_prep: bool = False,
        enable_prefix_cache: bool = True,
        prefix_cache_size: int = 8,
        min_prefix_len: int = 4,
        admission_linger_s: float = 0.05,
        block_size: int = 16,
        kv_pool_blocks: int | None = None,
        owner_inflight_cap: int | None = None,
        paged_attention: str = "auto",
        mesh: Any = None,
    ) -> None:
        self.cfg = cfg
        self.max_batch = max_batch
        # prompts longer than this prefill in chunks of this size,
        # interleaved with decode steps
        self.prefill_chunk = min(prefill_chunk, cfg.max_seq)
        self.tokenizer = tokenizer or default_caption_tokenizer()
        # paged-attention path selection. "auto"/"kernel" run the paged
        # programs (attention reads the pool through the block table —
        # ops/paged_attention.py picks Pallas on TPU, the byte-parity XLA
        # reference elsewhere); "gather" keeps the legacy
        # gather-view/scatter-back programs as fallback and parity
        # reference. CURATE_PAGED_ATTENTION overrides the constructor.
        env_mode = os.environ.get("CURATE_PAGED_ATTENTION")
        mode = env_mode if env_mode is not None else paged_attention
        if mode not in ("auto", "kernel", "gather"):
            raise ValueError(
                f"paged_attention must be auto|kernel|gather, got {mode!r}"
            )
        self.paged_attention = mode
        self._use_paged = mode != "gather"
        # optional device mesh: threads into the model so the paged path
        # runs head-parallel over parallel/axes.MODEL when the mesh names
        # that axis (KV pool + heads sharded, block tables replicated)
        self.mesh = mesh
        self.model = VLM(cfg, mesh=mesh)
        self.params = params
        self.waiting: list[CaptionRequest] = []
        # (length, n_slots) per decode-batch lane; default = one
        # worst-case-length lane, the round-2 behavior
        spec = kv_lanes or ((cfg.max_seq, max_batch),)
        # every lane length must tile into whole blocks (the gathered view
        # must equal the lane length EXACTLY for shape parity with the
        # slot-row programs): shrink the block size to the largest common
        # divisor when a lane length doesn't tile
        bs = max(1, int(block_size))
        for length, _ in spec:
            bs = math.gcd(bs, int(length))
        if bs != block_size:
            logger.warning(
                "block_size %d does not divide every KV lane length; using %d",
                block_size, bs,
            )
        # both sides of the fallback are surfaced (stats() / bench row) so
        # bench comparisons across block sizes aren't apples-to-oranges
        # when the gcd silently shrank the divisor
        self.block_size_requested = int(block_size)
        self.block_size = bs
        base = 0
        self.lanes: list[_Lane] = []
        for length, n in sorted(spec):
            if length > cfg.max_seq:
                raise ValueError(f"lane length {length} exceeds max_seq {cfg.max_seq}")
            self.lanes.append(
                _Lane(
                    length=length,
                    base=base,
                    n_slots=n,
                    table=np.zeros((n, length // bs), np.int32),
                )
            )
            base += n
        self.prefix_cache_size = prefix_cache_size
        lane_blocks = sum((l.length // bs) * l.n_slots for l in self.lanes)
        if kv_pool_blocks is None:
            # pool capacity = the memory the per-lane rows used to pin, plus
            # headroom for the shared-prefix entries that now live in pool
            # blocks, plus the reserved garbage block 0
            prefix_reserve = (
                prefix_cache_size * max(1, min(256, self.lanes[-1].length) // bs)
                if enable_prefix_cache
                else 0
            )
            kv_pool_blocks = 1 + lane_blocks + prefix_reserve
        # a pool smaller than the lane sum could deadlock a full slot load
        self.kv_pool_blocks = max(int(kv_pool_blocks), 1 + lane_blocks)
        self._allocator = BlockAllocator(self.kv_pool_blocks)
        self._pool_k = None
        self._pool_v = None
        self.completed: list[CaptionResult] = []
        self._decode_tokens = 0
        self._decode_time = 0.0
        # dead-work accounting: every decode step runs a lane's FULL slot
        # batch (static shapes); rows without an active slot are wasted.
        # utilization = tokens produced / rows executed
        self._decode_rows = 0
        # per-phase accounting (seconds): host+vision prep, vision-tower
        # share of prep, prefill programs (incl. shared-prefix builds),
        # decode is _decode_time above. Feeds stage_timer caption phases.
        # _stats_lock guards every counter '+=': the prep thread (prep /
        # vision / prefix-build counters) and the step thread (prefill /
        # decode counters) would otherwise lose updates racing on the same
        # attributes — and prefill_tokens is the acceptance metric.
        #
        # CANONICAL LOCK ORDER (checked by `lint --concurrency`):
        #   _lock (== _work_cv)  ->  _prefix_lock  ->  _stats_lock
        # _stats_lock is innermost and leaf-only: never acquire any other
        # engine lock while holding it.
        self._stats_lock = threading.Lock()
        self._prep_time = 0.0
        self._vision_time = 0.0
        self._prefill_time = 0.0
        self._prefill_tokens = 0  # prompt tokens pushed through prefill
        self._vision_encodes = 0
        self._vision_reuses = 0
        # shared-prefix KV cache: LRU over prefix token tuples. Entries are
        # small ([L, Tp, Hkv, Dh] per prefix) next to the lane caches.
        self.enable_prefix_cache = enable_prefix_cache
        self.prefix_cache_size = prefix_cache_size
        self.min_prefix_len = min_prefix_len
        self._prefix_cache: "OrderedDict[tuple, _PrefixEntry]" = OrderedDict()  # guarded-by: _prefix_lock
        # Middle of the canonical order: taken AFTER _lock (engine mutation)
        # and BEFORE _stats_lock, never the other way around — see the order
        # note at _stats_lock above.
        self._prefix_lock = threading.Lock()
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_evictions = 0
        self._prefix_tokens_saved = 0
        # paged-KV accounting (all under _stats_lock): cumulative block
        # reservations per admitted request (the kv_bytes_per_request bench
        # field), the worst-case tokens the slot-row engine would have
        # reserved for the same admissions, shared-prefix block references
        # handed out (the zero-copy successor of insert_prefix dispatches),
        # and copy-on-write tail duplications
        self._requests_admitted = 0
        self._kv_blocks_reserved = 0
        self._kv_private_blocks = 0
        self._kv_worstcase_tokens = 0
        self._prefix_block_refs = 0
        self._kv_cow_copies = 0
        self._kv_blocks_used_peak = 0
        # paged-attention accounting (under _stats_lock): decode steps
        # served by the paged programs (no gathered working set — the
        # structural assertion that the per-step copy is gone), bytes of
        # contiguous KV view the gather programs would have materialized
        # and scattered back for the same calls, and the tight wall time of
        # the decode program call + sync (same site both paths, so
        # kernel-vs-gather step time is directly comparable)
        self._paged_kernel_steps = 0
        self._kv_gather_bytes_avoided = 0
        self._decode_attn_time = 0.0
        # cross-job fairness: least-recently-admitted owner goes first, and
        # no owner may hold more than its in-flight share of the slots
        # (owner_inflight_cap; None = ceil(total slots / active owners))
        self.owner_inflight_cap = owner_inflight_cap
        self._owner_last_admit: dict[Any, int] = {}
        self._owner_last_prep: dict[Any, int] = {}
        self._admit_seq = 0
        self._prep_seq = 0
        self._interleaved_steps = 0
        self._owner_decode_tokens: dict[Any, int] = {}
        self._owner_requests: dict[Any, int] = {}
        # async prep: a background thread runs vision encode + embedding for
        # waiting requests while the caller's step() loop decodes — prep of
        # request N+1 overlaps decode of request N (the caption stage's
        # prep/decode stall was ~70% of its engine budget). Sync mode
        # (default) preps inline at admission: the round-5 behavior,
        # deterministic step() semantics for tests.
        self.async_prep = async_prep
        self._ready: "deque[_Prepared]" = deque()
        self._prep_inflight: CaptionRequest | None = None
        self._prep_thread: threading.Thread | None = None
        self._prep_stop = False
        # admission linger: when EVERY lane is idle and a burst is still
        # prepping, opening a lane for the first ready request decodes it
        # solo (full-batch rows for one token). Hold admission up to this
        # long so fast preps pack a batch; slow preps (vision-heavy real
        # configs) blow the deadline and overlap decode instead.
        self.admission_linger_s = admission_linger_s
        self._linger_until: float | None = None
        self._built = False
        # One engine is shared by every caption-family stage in a pipeline
        # (weights + KV cache are too big to duplicate). Stages run in
        # separate pool threads, and the jitted prefill/decode donate the
        # cache buffers — concurrent steps would be use-after-donate. This
        # lock serializes all engine mutation; completions are owner-tagged
        # so one stage's run cannot steal another stage's results.
        # OUTERMOST in the canonical order (_lock -> _prefix_lock ->
        # _stats_lock): always acquired first, via `with self._lock` or its
        # condition alias `with self._work_cv`.
        self._lock = threading.RLock()
        # signaled when prep lands a ready request / a follow-up is queued;
        # run_until_complete waits on it instead of spinning when the only
        # outstanding work is an in-flight background prep
        self._work_cv = threading.Condition(self._lock)

    # read-only aggregate views over the lanes (public slot id = lane.base
    # + lane-local index, unique across lanes)
    @property
    def slots(self) -> dict[int, _Slot]:
        return {l.base + i: s for l in self.lanes for i, s in l.slots.items()}

    @property
    def pending(self) -> dict[int, _PendingPrefill]:
        return {l.base + i: p for l in self.lanes for i, p in l.pending.items()}

    def kv_bytes(self) -> int:
        """Total device bytes the KV block pool pins."""
        if self._pool_k is None:
            return 0
        return self._pool_k.nbytes + self._pool_v.nbytes

    # -- setup ----------------------------------------------------------
    def setup(self, seed: int = 0) -> None:
        cfg = self.cfg
        if self.params is None:
            size = (
                cfg.qwen_vision.image_size
                if cfg.vision_variant in ("qwen2", "qwen3")
                else cfg.vision.image_size
            )
            frames = jnp.zeros((1, 1, size, size, 3), jnp.uint8)
            ids = jnp.zeros((1, 4), jnp.int32)
            ck, cv = init_cache(cfg, 1)
            self.params = self.model.init(
                jax.random.PRNGKey(seed),
                frames,
                ids,
                ck,
                cv,
                method=self.model.init_everything,
            )
        self._pool_k, self._pool_v = init_block_pool(
            cfg, self.kv_pool_blocks, self.block_size
        )

        model = self.model
        bs = self.block_size

        @jax.jit
        def encode_images(params, frames_u8):
            return model.apply(params, frames_u8, method=model.encode_images)

        @jax.jit
        def embed_tokens(params, ids):
            return model.apply(params, ids, method=model.embed_tokens)

        mrope = cfg.mrope_section is not None
        # qwen3 deepstack: number of LM layers receiving visual injections
        self._ds_levels = (
            len(cfg.qwen_vision.deepstack_indexes)
            if cfg.vision_variant == "qwen3" and cfg.qwen_vision is not None
            else 0
        )

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_batch(params, pool_k, pool_v, tables, embeds, write_index, t_valid, rope_pos, ds=None):
            """Batched prefill through the block tables (replaces the
            round-1 one-request-at-a-time admission — the reference leans
            on vLLM's batched prefill, vllm_interface.py:543). embeds:
            [N, Tb, D] (bucket- or chunk-padded); tables: [N, nbl] block
            ids; write_index/t_valid: [N]; rope_pos: [N, Tb] (or [N, Tb, 3]
            m-rope). write_index > 0 rows are later chunks of a chunked
            prefill, or shared-prefix suffixes starting past their cached
            blocks. Gathers each row's blocks into a contiguous view (the
            slot-row shapes — byte-identical math), writes every row's
            cells in one program, scatters the blocks back, and returns
            each row's logits at its last valid position: [N, V]."""
            ck, cv = gather_block_views(pool_k, pool_v, tables)
            logits, nk, nv = model.apply(
                params,
                embeds,
                ck,
                cv,
                rope_pos,
                write_index,
                write_index + t_valid,
                deepstack=ds,
            )
            pool_k, pool_v = scatter_block_views(pool_k, pool_v, tables, nk, nv)
            last = jnp.take_along_axis(
                logits, (t_valid - 1)[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
            return last, pool_k, pool_v

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode_step(params, pool_k, pool_v, tables, tokens, positions, rope_positions):
            """tokens/positions/rope_positions: [n_slots]; one token per
            slot. positions index the gathered view; rope_positions are the
            rotary positions (identical unless m-rope lagged them at
            prefill). tables: [n_slots, nbl] — idle rows point at the
            garbage block, shared prefix blocks scatter back unchanged (the
            paged_kv module docstring's duplicate-write invariant).

            Greedy argmax happens ON DEVICE for the whole batch — per-slot
            host argmaxes were the decode loop's bottleneck (one device
            sync per slot per token)."""
            embeds = model.apply(params, tokens[:, None], method=model.embed_tokens)
            rp = rope_positions[:, None]
            if mrope:
                # decode is always text: all three components equal
                rp = jnp.broadcast_to(rp[..., None], (*rp.shape, 3))
            ck, cv = gather_block_views(pool_k, pool_v, tables)
            logits, nk, nv = model.apply(
                params,
                embeds,
                ck,
                cv,
                rp,
                positions,
                positions + 1,
            )
            pool_k, pool_v = scatter_block_views(pool_k, pool_v, tables, nk, nv)
            step_logits = logits[:, 0]
            greedy = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            return greedy, step_logits, pool_k, pool_v

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_batch_paged(
            params, pool_k, pool_v, tables, embeds, write_index, t_valid, rope_pos, ds=None
        ):
            """prefill_batch without the working set: the model's paged
            forward scatters each row's chunk through its block table and
            attends straight out of the pool (ops/paged_attention.py) — no
            gather_block_views, no scatter_block_views. Same arguments,
            same returns, bit-equal logits on the reference path."""
            logits, pool_k, pool_v = model.apply(
                params,
                embeds,
                pool_k,
                pool_v,
                rope_pos,
                write_index,
                write_index + t_valid,
                tables,
                deepstack=ds,
                method=model.paged_forward,
            )
            last = jnp.take_along_axis(
                logits, (t_valid - 1)[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
            return last, pool_k, pool_v

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode_step_paged(params, pool_k, pool_v, tables, tokens, positions, rope_positions):
            """decode_step without the working set — see prefill_batch_paged.
            The per-step O(context) gathered copy and its scatter-back are
            gone; each row writes exactly ONE pool cell."""
            embeds = model.apply(params, tokens[:, None], method=model.embed_tokens)
            rp = rope_positions[:, None]
            if mrope:
                # decode is always text: all three components equal
                rp = jnp.broadcast_to(rp[..., None], (*rp.shape, 3))
            logits, pool_k, pool_v = model.apply(
                params,
                embeds,
                pool_k,
                pool_v,
                rp,
                positions,
                positions + 1,
                tables,
                method=model.paged_forward,
            )
            step_logits = logits[:, 0]
            greedy = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            return greedy, step_logits, pool_k, pool_v

        @jax.jit
        def prefix_prefill(params, embeds, rope_pos, t_valid):
            """Prefill ONE text prefix into a scratch cache and return its
            K/V block [L, Sp, Hkv, Dh] (sliced to the true length by the
            caller). embeds: [1, Sp, D] (pow2-padded); t_valid: scalar.
            Compiled once per Sp bucket — prefixes are per (flavor,
            prompt_variant), so this runs once per variant, not per
            request."""
            ck, cv = init_cache(cfg, 1, length=embeds.shape[1])
            _logits, nk, nv = model.apply(
                params,
                embeds,
                ck,
                cv,
                rope_pos,
                jnp.zeros((1,), jnp.int32),
                jnp.full((1,), t_valid, jnp.int32),
            )
            return nk[:, 0], nv[:, 0]

        @partial(jax.jit, donate_argnums=(0, 1))
        def write_prefix_blocks(pool_k, pool_v, pk, pv, ids):
            """Store one freshly built prefix K/V ([L, Tp, Hkv, Dh]) into
            its allocated pool blocks ``ids`` ([nb]) — the ONE device write
            per prefix build; admitted requests then reference these blocks
            with zero further copies. Compiled once per Tp (prefixes are
            per (flavor, prompt_variant), so this runs once per variant)."""
            pad = ids.shape[0] * bs - pk.shape[1]
            pk = jnp.pad(pk.astype(pool_k.dtype), ((0, 0), (0, pad), (0, 0), (0, 0)))
            pv = jnp.pad(pv.astype(pool_v.dtype), ((0, 0), (0, pad), (0, 0), (0, 0)))
            pool_k = pool_k.at[:, ids].set(pk.reshape(pk.shape[0], -1, bs, *pk.shape[2:]))
            pool_v = pool_v.at[:, ids].set(pv.reshape(pv.shape[0], -1, bs, *pv.shape[2:]))
            return pool_k, pool_v

        @partial(jax.jit, donate_argnums=(0, 1))
        def copy_blocks(pool_k, pool_v, src, dst):
            """Copy-on-write: duplicate blocks ``src`` into ``dst`` ([m]
            each) — used ONLY when a request must extend a partially-filled
            shared prefix tail block (one block, not the whole prefix)."""
            pool_k = pool_k.at[:, dst].set(pool_k[:, src])
            pool_v = pool_v.at[:, dst].set(pool_v[:, src])
            return pool_k, pool_v

        self._host_rng = np.random.default_rng(seed)
        self._encode_images = encode_images
        self._embed_tokens = embed_tokens
        self._prefill_batch = prefill_batch_paged if self._use_paged else prefill_batch
        self._decode = decode_step_paged if self._use_paged else decode_step
        self._prefix_prefill = prefix_prefill
        self._write_prefix_blocks = write_prefix_blocks
        self._copy_blocks = copy_blocks
        self._built = True
        if self.async_prep:
            # requests may already be waiting (queued before setup)
            with self._work_cv:
                self._start_prep_thread()
                self._work_cv.notify_all()

    # -- public API -----------------------------------------------------
    @property
    def _max_len(self) -> int:
        return self.lanes[-1].length  # lanes are sorted by length

    def add_request(self, request: CaptionRequest, owner: Any = None) -> None:
        budget = self._max_len - request.sampling.max_new_tokens - 1
        if budget <= 0:
            raise ValueError(
                f"max_new_tokens={request.sampling.max_new_tokens} leaves no "
                f"prompt budget in the longest KV lane ({self._max_len})"
            )
        if any(not s for s in request.sampling.stop):
            # '' in tail is always True — an empty stop string would finish
            # the request after one token with empty text
            raise ValueError("stop strings must be non-empty")
        if request.owner is None:
            request.owner = owner if owner is not None else threading.get_ident()
        with self._work_cv:
            self.waiting.append(request)
            # only a BUILT engine may prep (the thread calls the jitted
            # encoders setup() creates); requests queued before setup()
            # wait — setup() starts the thread for them, and the sync
            # step() path keeps raising 'call setup() first'
            if self.async_prep and self._built:
                self._start_prep_thread()
            self._work_cv.notify_all()

    def _prep_requests(self) -> list[CaptionRequest]:
        """Requests past ``waiting`` but not yet admitted (prepared or
        mid-prep in the background thread). Lock held by caller."""
        reqs = [p.request for p in self._ready]
        if self._prep_inflight is not None:
            reqs.append(self._prep_inflight)
        return reqs

    def has_work(self, owner: Any = None) -> bool:
        with self._lock:
            if owner is None:
                return bool(
                    self.waiting or self._prep_requests() or self.slots or self.pending
                )
            return (
                any(r.owner == owner for r in self.waiting)
                or any(r.owner == owner for r in self._prep_requests())
                or any(s.request.owner == owner for s in self.slots.values())
                or any(p.request.owner == owner for p in self.pending.values())
            )

    def run_until_complete(self, owner: Any = None) -> list[CaptionResult]:
        """Drive the engine until this caller's requests are done.

        ``owner`` defaults to the calling thread's ident — the same default
        ``add_request`` tags requests with — so the existing
        add-then-run-in-one-thread usage is unchanged. Requests queued by
        other owners still ride along in the continuous batch (free
        throughput), but their completions stay queued for *their*
        ``run_until_complete``.
        """
        if owner is None:
            owner = threading.get_ident()
        while True:
            # Lock per step, not across the drain: another stage's
            # add_request must be able to slip in between decode steps so
            # its requests actually join the continuous batch.
            with self._work_cv:
                if not self.has_work(owner):
                    mine = [r for r in self.completed if r.owner == owner]
                    self.completed = [r for r in self.completed if r.owner != owner]
                    # keep THIS owner's entries: the caller reads its
                    # per-owner accounting deltas right after this returns
                    self._prune_owner_state(keep=owner)
                    return mine
                steppable = (
                    bool(self._ready)
                    or (not self.async_prep and bool(self.waiting))
                    or any(l.slots or l.pending for l in self.lanes)
                )
                if not steppable or self._should_linger():
                    # only background prep is outstanding (or admission is
                    # lingering for the burst's prep to pack a batch) —
                    # sleep until it lands instead of spinning empty steps
                    self._work_cv.wait(0.02)
                    continue
                self.step()

    @property
    def tokens_per_second(self) -> float:
        return self._decode_tokens / self._decode_time if self._decode_time > 0 else 0.0

    @property
    def decode_tokens(self) -> int:
        return self._decode_tokens

    @property
    def decode_time_s(self) -> float:
        return self._decode_time

    @property
    def prefill_tokens(self) -> int:
        """Prompt tokens pushed through prefill programs (bucket, chunk,
        and shared-prefix builds; cache-inserted prefix copies are NOT
        prefill). With the shared-prefix cache, n requests sharing a
        Tp-token prefix prefill Tp fewer tokens each after the first."""
        return self._prefill_tokens

    @property
    def prefix_cache_hits(self) -> int:
        return self._prefix_hits

    @property
    def prefix_cache_misses(self) -> int:
        return self._prefix_misses

    @property
    def prefix_cache_evictions(self) -> int:
        return self._prefix_evictions

    @property
    def prefix_tokens_saved(self) -> int:
        """Prefill tokens NOT recomputed thanks to shared-prefix hits."""
        return self._prefix_tokens_saved

    @property
    def vision_encodes(self) -> int:
        return self._vision_encodes

    @property
    def vision_reuses(self) -> int:
        return self._vision_reuses

    # -- paged-KV occupancy and cross-job accounting --------------------
    @property
    def kv_blocks_total(self) -> int:
        """Allocatable pool blocks (admission limit; garbage block excluded)."""
        return self._allocator.capacity

    @property
    def kv_blocks_used(self) -> int:
        return self._allocator.used_blocks

    @property
    def kv_blocks_used_peak(self) -> int:
        """High-water pool occupancy since the last reset_stats()."""
        return self._kv_blocks_used_peak

    @property
    def kv_block_bytes(self) -> int:
        """Device bytes one block pins (K + V across all layers)."""
        cfg = self.cfg
        # bf16 pool: 2 bytes/element, x2 for K and V
        return 2 * 2 * cfg.n_layers * self.block_size * cfg.n_kv_heads * cfg.head_dim

    @property
    def prefix_block_refs(self) -> int:
        """Cumulative shared-prefix block references handed to admitted
        requests — each one is a whole block of prefix K/V served with ZERO
        device copies (the metric that replaced insert_prefix dispatches)."""
        return self._prefix_block_refs

    @property
    def prefix_copy_dispatches(self) -> int:
        """Whole-prefix device-copy dispatches at admission. Structurally
        zero since the paged pool: admitted requests REFERENCE prefix
        blocks through their tables instead of copying them into slot rows
        (the round-7 jitted insert_prefix path is deleted). Kept as an
        explicit counter so the bench/smoke contract 'zero prefix
        device-copy dispatches' is asserted, not assumed."""
        return 0

    @property
    def kv_cow_copies(self) -> int:
        """Copy-on-write duplications of a partially-filled shared prefix
        tail block (ONE block each — not a prefix copy)."""
        return self._kv_cow_copies

    # -- paged-attention accounting --------------------------------------
    def _gather_view_bytes(self, rows: int, length: int) -> int:
        """Bytes of contiguous KV working set the gather programs would
        materialize for one program call over ``rows`` block tables of
        ``length`` gathered positions (K + V, all layers)."""
        cfg = self.cfg
        itemsize = 2 if self._pool_k is None else self._pool_k.dtype.itemsize
        return 2 * cfg.n_layers * rows * length * cfg.n_kv_heads * cfg.head_dim * itemsize

    @property
    def paged_kernel_steps(self) -> int:
        """Decode steps served by the paged-attention programs — attention
        read the pool through the block table; NO contiguous working-set
        copy was built or scattered back. Structurally zero under
        ``paged_attention="gather"``; > 0 is the smoke contract that the
        kernel path was actually taken."""
        return self._paged_kernel_steps

    @property
    def kv_gather_bytes_avoided(self) -> int:
        """Cumulative bytes of per-call contiguous KV working set the
        gather programs would have materialized (and scattered back) for
        the prefill/decode calls the paged path served instead."""
        return self._kv_gather_bytes_avoided

    @property
    def decode_attention_s(self) -> float:
        """Tight wall time of decode program calls + host sync, identical
        measurement site for the paged and gather paths — the
        kernel-vs-gather comparison the bench caption_attention section
        reports. (Also contained in phase decode_s, which this mirrors at
        the program-call granularity.)"""
        return self._decode_attn_time

    @property
    def mesh_geometry(self) -> tuple:
        """Hashable (axis, extent) view of the serving mesh (empty when
        unsharded) — part of the SharedCaptionEngine key so differently
        sharded engines never collide."""
        if self.mesh is None:
            return ()
        return tuple(
            (str(name), int(self.mesh.shape[name])) for name in self.mesh.axis_names
        )

    def stats(self) -> dict:
        """One-call snapshot of the serving counters (bench row / smoke
        surface). Includes both sides of the block-size fallback: the
        constructor-requested size and the gcd-shrunk divisor actually
        used, so cross-run bench comparisons can detect a silent shrink."""
        with self._stats_lock:
            return {
                "paged_attention": self.paged_attention,
                "mesh_geometry": self.mesh_geometry,
                "kv_block_size": self.block_size,
                "kv_block_size_requested": self.block_size_requested,
                "paged_kernel_steps": self._paged_kernel_steps,
                "kv_gather_bytes_avoided": self._kv_gather_bytes_avoided,
                "decode_attention_s": self._decode_attn_time,
                "decode_tokens": self._decode_tokens,
                "decode_s": self._decode_time,
                "prefill_tokens": self._prefill_tokens,
                "prefill_s": self._prefill_time,
                "kv_blocks_total": self._allocator.capacity,
                "kv_blocks_used": self._allocator.used_blocks,
                "kv_blocks_used_peak": self._kv_blocks_used_peak,
            }

    @property
    def requests_admitted(self) -> int:
        return self._requests_admitted

    @property
    def kv_bytes_reserved_per_request(self) -> float:
        """Mean KV bytes reserved per admitted request (shared references
        counted at full block size — still strictly below the old
        worst-case row whenever prompt + max_new undershoots the lane)."""
        if not self._requests_admitted:
            return 0.0
        return self._kv_blocks_reserved * self.kv_block_bytes / self._requests_admitted

    @property
    def kv_bytes_worstcase_per_request(self) -> float:
        """What the slot-row engine reserved for the same admissions: each
        routed lane's FULL row, regardless of actual request length."""
        if not self._requests_admitted:
            return 0.0
        token_bytes = self.kv_block_bytes / self.block_size
        return self._kv_worstcase_tokens * token_bytes / self._requests_admitted

    @property
    def interleaved_decode_steps(self) -> int:
        """Steps whose active slots spanned 2+ owners — the cross-job
        continuous-batching signal (two pipelines decoding in ONE batch)."""
        return self._interleaved_steps

    @property
    def owner_decode_tokens(self) -> dict:
        with self._stats_lock:
            return dict(self._owner_decode_tokens)

    def owner_stats(self) -> dict:
        """Per-owner queue/in-flight/served gauges, keyed by str(owner) —
        the cross-job accounting surface (metrics exporter + run report)."""
        with self._lock:
            out: dict[str, dict] = {}

            def bucket(owner):
                return out.setdefault(
                    str(owner),
                    {"waiting": 0, "ready": 0, "inflight": 0,
                     "decode_tokens": 0, "requests": 0},
                )

            for r in self.waiting:
                bucket(r.owner)["waiting"] += 1
            if self._prep_inflight is not None:
                bucket(self._prep_inflight.owner)["waiting"] += 1
            for p in self._ready:
                bucket(p.request.owner)["ready"] += 1
            for lane in self.lanes:
                for s in lane.slots.values():
                    bucket(s.request.owner)["inflight"] += 1
                for p in lane.pending.values():
                    bucket(p.request.owner)["inflight"] += 1
            with self._stats_lock:
                for owner, n in self._owner_decode_tokens.items():
                    bucket(owner)["decode_tokens"] = n
                for owner, n in self._owner_requests.items():
                    bucket(owner)["requests"] = n
            return out

    @property
    def phase_seconds(self) -> dict[str, float]:
        """Cumulative per-phase seconds: ``prep`` (host prep incl. the
        vision share), ``vision_encode`` (vision-tower subset of prep),
        ``prefill`` (prefill programs + host sync), ``decode`` (decode
        steps + host sync). Wall minus (prefill + decode) over a drive
        window is the engine's idle/stall time."""
        return {
            "prep_s": self._prep_time,
            "vision_encode_s": self._vision_time,
            "prefill_s": self._prefill_time,
            "decode_s": self._decode_time,
        }

    def reset_stats(self) -> None:
        """Zero the throughput counters (e.g. after benchmark warmup) —
        the counter set and its reset stay in one place. Shared-prefix
        cache CONTENTS survive (only the hit/miss counters reset)."""
        with self._stats_lock:
            self._decode_tokens = 0
            self._decode_time = 0.0
            self._decode_rows = 0
            self._prep_time = 0.0
            self._vision_time = 0.0
            self._prefill_time = 0.0
            self._prefill_tokens = 0
            self._vision_encodes = 0
            self._vision_reuses = 0
            self._prefix_hits = 0
            self._prefix_misses = 0
            self._prefix_evictions = 0
            self._prefix_tokens_saved = 0
            self._requests_admitted = 0
            self._kv_blocks_reserved = 0
            self._kv_private_blocks = 0
            self._kv_worstcase_tokens = 0
            self._prefix_block_refs = 0
            self._kv_cow_copies = 0
            self._paged_kernel_steps = 0
            self._kv_gather_bytes_avoided = 0
            self._decode_attn_time = 0.0
            self._kv_blocks_used_peak = self._allocator.used_blocks
            self._interleaved_steps = 0
            self._owner_decode_tokens.clear()
            self._owner_requests.clear()

    def clear_prefix_cache(self) -> None:
        """Drop every cached prefix and release the LRU's block references.
        Blocks still mapped by in-flight slots stay allocated until those
        slots release (deferred free); after a full drain the pool reads
        fully free."""
        with self._lock, self._prefix_lock:
            for entry in self._prefix_cache.values():
                self._allocator.decref(entry.blocks)
            self._prefix_cache.clear()

    def shutdown(self) -> None:
        """Stop the background prep thread and release the prefix cache's
        block references (tests assert the pool is fully free after a
        drained shutdown; long-lived engines just let the daemon thread die
        with the process)."""
        self.clear_prefix_cache()
        with self._work_cv:
            self._prep_stop = True
            self._work_cv.notify_all()
        t = self._prep_thread
        if t is not None:
            t.join(timeout=5)
            if t.is_alive():
                # mid-encode and past the grace: leave the stop flag SET so
                # the thread exits at its next loop check instead of
                # resuming work beside a future replacement thread
                logger.warning("caption prep thread still running after 5s grace")
                return
            self._prep_thread = None
        self._prep_stop = False

    @property
    def decode_slot_utilization(self) -> float:
        """Fraction of executed decode rows that produced a token (the
        static-batch dead-work measure; lanes raise it by keeping batches
        near their occupancy)."""
        return self._decode_tokens / self._decode_rows if self._decode_rows else 0.0

    # -- engine internals ----------------------------------------------
    def step(self) -> None:
        """Admit ready requests, advance chunked prefills, then one decode
        step per active lane — so a long prompt never blocks the in-flight
        batch's decode for more than a chunk's latency.

        Chunk admission is tuned against decode occupancy (the live signal
        behind ``decode_slot_utilization``): chunking exists to protect
        in-flight decode from a long prefill stall, so while NO lane is
        decoding, pending chunks run back to back instead of one per step —
        an idle engine prefills at full speed."""
        if not self._built:
            raise RuntimeError("call setup() first")
        with self._work_cv:
            self._admit()
            # cross-job signal: this step's active slots span 2+ owners —
            # several jobs are decoding in ONE continuous batch
            step_owners = {
                s.request.owner for l in self.lanes for s in l.slots.values()
            }
            if len(step_owners) > 1:
                with self._stats_lock:
                    self._interleaved_steps += 1
            for lane in self.lanes:
                if lane.pending:
                    self._prefill_chunk_step(lane)
                    while lane.pending and not any(l.slots for l in self.lanes):
                        self._prefill_chunk_step(lane)
                if lane.slots:
                    self._decode_once(lane)
            self._work_cv.notify_all()  # ready-queue space may have freed

    # -- request prep (sync inline, or the background overlap thread) ---
    def _start_prep_thread(self) -> None:
        if self._prep_thread is not None and self._prep_thread.is_alive():
            return
        # a shutdown() whose join grace expired leaves _prep_stop latched;
        # a fresh thread must not read the stale flag and die instantly
        self._prep_stop = False
        self._prep_thread = threading.Thread(
            target=self._prep_loop, name="caption-prep", daemon=True
        )
        self._prep_thread.start()

    def _prep_ahead_limit(self) -> int:
        # bound host memory for prepared-but-unadmitted embeds: enough to
        # keep every slot fed one wave ahead, no more
        return max(2, 2 * sum(l.n_slots for l in self.lanes))

    def _prep_loop(self) -> None:
        """Background prep: vision encode + token embedding for waiting
        requests, FIFO, overlapping the caller's decode loop. Device
        compute runs OUTSIDE the engine lock — the lock only guards queue
        hops, so a decode step never waits on a vision encode and vice
        versa (device-side serialization is the hardware's business)."""
        while True:
            with self._work_cv:
                while not self._prep_stop and (
                    not self.waiting or len(self._ready) >= self._prep_ahead_limit()
                ):
                    self._work_cv.wait(0.1)
                if self._prep_stop:
                    return
                req = self._pop_waiting_fair()
                self._prep_inflight = req
            prep = self._safe_prepare(req)  # no lock: overlaps decode
            with self._work_cv:
                self._prep_inflight = None
                if prep is not None:
                    self._ready.append(prep)
                self._work_cv.notify_all()

    # every stage instance mints a fresh owner tag, so a long-lived shared
    # engine would otherwise accumulate owner-keyed state forever (and mint
    # unbounded per-owner metric series)
    _OWNER_STATE_CAP = 256

    # holds-lock: _lock
    def _prune_owner_state(self, keep: Any = None) -> None:
        """Bound the owner-keyed maps: once past the cap, drop entries for
        owners with no live work. ``keep`` protects the owner whose drive
        just completed — its stage reads the accounting deltas right after
        (pruning it first would hand the stage a zero/negative delta).
        Lock held by caller."""
        maps = (
            self._owner_last_admit,
            self._owner_last_prep,
            self._owner_decode_tokens,
            self._owner_requests,
        )
        if all(len(m) <= self._OWNER_STATE_CAP for m in maps):
            return
        live = {r.owner for r in self.waiting}
        live.update(p.request.owner for p in self._ready)
        if self._prep_inflight is not None:
            live.add(self._prep_inflight.owner)
        for lane in self.lanes:
            live.update(s.request.owner for s in lane.slots.values())
            live.update(p.request.owner for p in lane.pending.values())
        live.update(r.owner for r in self.completed)
        if keep is not None:
            live.add(keep)
        with self._stats_lock:
            for m in maps:
                if len(m) > self._OWNER_STATE_CAP:
                    for owner in [o for o in m if o not in live]:
                        del m[owner]

    @staticmethod
    def _fair_head(owners_in_order, last_map: dict, inflight: dict, cap: float):
        """(owner, index) of the next fair pick: FIFO within an owner,
        least-recently-served owner first, owners at ``cap`` in-flight
        skipped. ``owners_in_order`` yields each queue item's owner in
        queue order. Returns None when every queued owner is capped."""
        heads: "OrderedDict[Any, int]" = OrderedDict()
        for i, owner in enumerate(owners_in_order):
            if owner not in heads:
                heads[owner] = i
        eligible = [(o, i) for o, i in heads.items() if inflight.get(o, 0) < cap]
        if not eligible:
            return None
        return min(eligible, key=lambda kv: (last_map.get(kv[0], -1), kv[1]))

    def _pop_waiting_fair(self) -> CaptionRequest:
        """Next waiting request: one pipeline's burst cannot push another
        pipeline's requests out of the prep pipeline (cross-job fairness
        starts at prep, since only prepped requests can be admitted).
        Single-owner queues reduce to plain FIFO. Lock held by caller."""
        owner, idx = self._fair_head(
            (r.owner for r in self.waiting), self._owner_last_prep, {}, float("inf")
        )
        self._owner_last_prep[owner] = self._prep_seq
        self._prep_seq += 1
        return self.waiting.pop(idx)

    def _safe_prepare(self, req: CaptionRequest) -> "_Prepared | None":
        t0 = time.monotonic()
        try:
            return self._prepare(req)
        except Exception:
            logger.exception("prefill prep failed for %s; dropping", req.request_id)
            return None
        finally:
            with self._stats_lock:
                self._prep_time += time.monotonic() - t0

    def _should_linger(self) -> bool:
        """True while admission should hold for the in-flight burst's prep:
        every lane idle, ready requests waiting, more of the burst still
        prepping, and the linger deadline not yet blown. Lock held by
        caller."""
        if not self.async_prep or self.admission_linger_s <= 0:
            return False
        if not self._ready or any(l.slots or l.pending for l in self.lanes):
            self._linger_until = None
            return False
        incoming = len(self.waiting) + (1 if self._prep_inflight is not None else 0)
        free = sum(l.n_slots for l in self.lanes)
        if not incoming or len(self._ready) >= free:
            self._linger_until = None
            return False
        now = time.monotonic()
        if self._linger_until is None:
            self._linger_until = now + self.admission_linger_s
        return now < self._linger_until

    def _owner_cap(self, inflight: dict) -> int:
        """Per-owner in-flight slot cap: an explicit ``owner_inflight_cap``,
        or the fair share of the slot budget across owners that currently
        have work. A single owner gets the whole engine (admission-order
        parity with the single-job engine)."""
        if self.owner_inflight_cap is not None:
            return max(1, self.owner_inflight_cap)
        owners = set(inflight)
        owners.update(r.owner for r in self.waiting)
        owners.update(p.request.owner for p in self._ready)
        if self._prep_inflight is not None:
            owners.add(self._prep_inflight.owner)
        total = sum(l.n_slots for l in self.lanes)
        if len(owners) <= 1:
            return total
        return max(1, -(-total // len(owners)))

    # holds-lock: _lock
    def _next_prepared(self, inflight: dict) -> "_Prepared | None":
        """Next admission candidate: FIFO within an owner, least-recently-
        admitted owner first, owners at their in-flight cap skipped — the
        cross-job interleave. Single-owner queues reduce to plain FIFO. In
        sync mode fall through to inline prep of the waiting queue (same
        owner rotation)."""
        cap = self._owner_cap(inflight)
        if self._ready:
            pick = self._fair_head(
                (p.request.owner for p in self._ready),
                self._owner_last_admit,
                inflight,
                cap,
            )
            if pick is None:
                return None  # every queued owner is at its fair share
            prep = self._ready[pick[1]]
            del self._ready[pick[1]]
            return prep
        if not self.async_prep:
            while self.waiting:
                pick = self._fair_head(
                    (r.owner for r in self.waiting),
                    self._owner_last_prep,
                    inflight,
                    cap,
                )
                if pick is None:
                    return None
                owner, idx = pick
                self._owner_last_prep[owner] = self._prep_seq
                self._prep_seq += 1
                prep = self._safe_prepare(self.waiting.pop(idx))
                if prep is not None:
                    return prep
        return None

    def _route(self, need: int) -> _Lane | None:
        """Pick the lane for a request needing ``need`` positions.

        Utilization-aware admission: every decode step runs a lane's FULL
        slot batch (static shapes), so joining a lane that is already
        decoding adds a token to rows that execute anyway — pure win —
        while opening an idle lane pays its whole batch for one request.
        Among lanes that fit and have a free slot, prefer the smallest
        ACTIVE lane; fall back to the smallest idle one. Exception: a
        request that a SHORTER idle lane could serve must not consume the
        LAST free slot of a longer active lane — long-lane slots are
        scarce (e.g. 2 at 4096 for the 7B default) and burning the last
        one on a short request head-of-line-blocks the next long prompt."""
        first_idle = None
        active = None
        active_free = 0
        for lane in self.lanes:  # sorted by length
            occupied = len(lane.slots) + len(lane.pending) + len(lane.reserved)
            if lane.length < need or occupied >= lane.n_slots:
                continue
            if occupied and active is None:
                active = lane
                active_free = lane.n_slots - occupied
            elif not occupied and first_idle is None:
                first_idle = lane
        if active is not None:
            if (
                first_idle is not None
                and first_idle.length < active.length
                and active_free <= 1
            ):
                return first_idle
            return active
        return first_idle

    def _prompt_len_estimate(self, req: CaptionRequest) -> int:
        """Prompt length WITHOUT running the encoders. Routing now sees the
        prepared request's ACTUAL total (prep precedes admission), so this
        is a planning utility: callers sizing a request against the lanes
        (fit_max_new_tokens, capacity tooling) without paying an encode."""
        n = len(req.prefix_ids) + len(req.prompt_ids)
        if req.frames is not None:
            n += self._vision_token_count(req.frames.shape[0])
        return min(n, self._max_len - req.sampling.max_new_tokens - 1)

    # holds-lock: _lock
    def _admit(self) -> None:
        if self._should_linger():
            return
        # per-owner in-flight counts for the fairness cap (updated as this
        # pass admits, so one pass cannot blow past the cap either)
        inflight: dict[Any, int] = {}
        for l in self.lanes:
            for s in l.slots.values():
                inflight[s.request.owner] = inflight.get(s.request.owner, 0) + 1
            for p in l.pending.values():
                inflight[p.request.owner] = inflight.get(p.request.owner, 0) + 1
        groups: dict[tuple[int, int], list[tuple]] = {}
        while True:
            prep = self._next_prepared(inflight)
            if prep is None:
                break
            req = prep.request
            need = prep.total + req.sampling.max_new_tokens + 1
            lane = self._route(min(need, self._max_len))
            if lane is None:
                # head-of-line waits for a slot to free (FIFO); the prep
                # work is kept, not redone
                self._ready.appendleft(prep)
                break
            lane_budget = lane.length - req.sampling.max_new_tokens - 1
            if prep.total > lane_budget:  # routed lane too short after all
                if req.frames is not None:
                    # never slice a vision block (see _fit_frames_to_budget):
                    # re-route on the ACTUAL token count — _prepare
                    # guarantees the total fits the longest lane, so a lane
                    # exists; None only means it is busy, so requeue at the
                    # head and wait instead of dropping a servable request
                    lane2 = self._route(prep.total + req.sampling.max_new_tokens + 1)
                    if lane2 is None:
                        self._ready.appendleft(prep)
                        break
                    logger.info(
                        "%s: multimodal prompt re-routed %d -> %d lane "
                        "(estimate %d, actual %d tokens)",
                        req.request_id, lane.length, lane2.length,
                        lane_budget, prep.total,
                    )
                    lane = lane2
                    lane_budget = lane.length - req.sampling.max_new_tokens - 1
                else:
                    if prep.base:
                        # tail-keep truncation may cut into the prefix
                        # region: fold the prefix back in first
                        prep = self._materialize_full(prep)
                    prep.embeds = prep.embeds[-lane_budget:]
                    prep.rope = prep.rope[-lane_budget:]
                    if prep.ds is not None:
                        prep.ds = prep.ds[:, -lane_budget:]
                    prep.t_suffix = lane_budget
            # The prefix entry must be resident BEFORE placement decisions:
            # when the pool cannot host it (exhausted with nothing
            # evictable), fold the prefix back into the host embeds and
            # admit uncached — recompute beats waiting on cache memory.
            if prep.base:
                entry, _ = self._ensure_prefix(prep.prefix_key, count=False)
                if entry is None:
                    prep = self._materialize_full(prep)
            # Shared-prefix placement feasibility in THIS lane: a bucketed
            # group prefill writes a [bucket]-length chunk at offset base,
            # which must stay inside the lane. Chunked prefill places
            # exactly (its final chunk shifts back), so prefer it when the
            # suffix is chunkable; otherwise fold the prefix back in.
            group_ok = (
                prep.base + min(next_pow2(prep.t_suffix), lane.length) <= lane.length
            )
            if not group_ok and prep.t_suffix <= self.prefill_chunk:
                prep = self._materialize_full(prep)
                group_ok = True
            # Chunk admission tuned against decode occupancy (the live
            # signal behind decode_slot_utilization): chunking protects
            # in-flight decode from a long prefill stall — with no lane
            # decoding there is nothing to protect, so admit the whole
            # prompt as one bucketed prefill and skip the per-step drip.
            decode_active = any(l.slots for l in self.lanes)
            chunked = prep.t_suffix > self.prefill_chunk and (
                decode_active or not group_ok
            )
            slot_idx = next(
                i
                for i in range(lane.n_slots)
                if i not in lane.slots
                and i not in lane.pending
                and i not in lane.reserved
            )
            try:
                self._claim_kv(lane, slot_idx, prep, req)
            except PoolExhausted:
                if prep.base and not any(l.claims for l in self.lanes):
                    # nothing in flight will free blocks and eviction
                    # spares the entry this claim references — the
                    # request's OWN prefix entry may be hoarding an idle
                    # pool. Fold the prefix back in and retry uncached: a
                    # lone worst-case request always fits an empty pool
                    # (kv_pool_blocks is floored at the lane sum).
                    self._ready.appendleft(self._materialize_full(prep))
                    continue
                # occupancy-based admission: the BLOCK POOL, not slot
                # count, is the limit — wait for in-flight requests to
                # free blocks (prep kept, not redone)
                self._ready.appendleft(prep)
                break
            except Exception:
                logger.exception(
                    "KV block claim failed for %s; dropping", req.request_id
                )
                continue
            inflight[req.owner] = inflight.get(req.owner, 0) + 1
            self._owner_last_admit[req.owner] = self._admit_seq
            self._admit_seq += 1
            if chunked:
                # long prompt: prefill in chunks interleaved with decode
                lane.pending[slot_idx] = _PendingPrefill(
                    request=req,
                    embeds=prep.embeds,
                    t_valid=prep.t_suffix,
                    rope_pos=prep.rope,
                    next_rope=prep.next_rope,
                    ds=prep.ds,
                    base=prep.base,
                )
                continue
            bucket = min(next_pow2(prep.t_suffix), lane.length)
            groups.setdefault((self.lanes.index(lane), bucket), []).append(
                (
                    slot_idx,
                    req,
                    prep.embeds,
                    prep.t_suffix,
                    prep.rope,
                    prep.next_rope,
                    prep.ds,
                    prep.base,
                )
            )
            # reserve the slot so this loop's later iterations see it taken
            lane.reserved.add(slot_idx)
        for (lane_i, bucket), items in sorted(groups.items()):
            lane = self.lanes[lane_i]
            for slot_idx, *_ in items:  # release the reservations
                lane.reserved.discard(slot_idx)
            try:
                self._prefill_group(lane, bucket, items)
            except Exception:
                if len(items) == 1:
                    logger.exception(
                        "prefill failed for %s; dropping", items[0][1].request_id
                    )
                    self._release_claim(lane, items[0][0])
                    continue
                # isolate the offender: retry each request as its own group
                logger.exception(
                    "batched prefill failed for %d requests; retrying singly",
                    len(items),
                )
                for item in items:
                    try:
                        self._prefill_group(lane, bucket, [item])
                    except Exception:
                        logger.exception(
                            "prefill failed for %s; dropping", item[1].request_id
                        )
                        self._release_claim(lane, item[0])

    def _prepare(self, req: CaptionRequest, allow_prefix: bool = True) -> _Prepared:
        """Vision encode + token embed for one request.

        When the request's text prefix is shareable (``share_prefix``, long
        enough, cache enabled, no truncation needed), only the SUFFIX
        (vision + prompt text) is embedded — the prefix's K/V come from the
        shared-prefix cache and ``base`` marks where suffix prefill starts.
        Rope positions stay absolute over the full [prefix][vision][prompt]
        layout either way, so cached and uncached prefills write identical
        cache contents (greedy parity). Under m-rope the positions come
        from build_mrope_positions; otherwise they are arange."""
        from cosmos_curate_tpu.models.vlm.model import build_mrope_positions

        budget = self._max_len - req.sampling.max_new_tokens - 1
        n_pre = len(req.prefix_ids)
        vis_embeds = None
        ds_vis = None
        grid_merged = None
        eff_fps = None
        if req.frames is not None:
            vf = req.vision_features
            n_text = n_pre + len(req.prompt_ids)
            if vf is not None and n_text + vf.n_tokens <= budget:
                # refinement pass over the SAME frames: reuse the encoded
                # vision features instead of re-running the tower
                vis_embeds, ds_vis = vf.embeds, vf.ds
                grid_merged, eff_fps = vf.grid, vf.eff_fps
                with self._stats_lock:
                    self._vision_reuses += 1
            else:
                frames, eff_fps = self._fit_frames_to_budget(req)
                t0 = time.monotonic()
                vis = self._encode_images(self.params, jnp.asarray(frames)[None])
                if isinstance(vis, tuple):  # qwen3: (embeds, deepstack levels)
                    vis, ds_levels = vis
                    ds_vis = np.asarray(ds_levels[:, 0], np.float32)  # [L_ds, T_vis, D]
                vis_embeds = vis[0]
                jax.block_until_ready(vis_embeds)
                with self._stats_lock:
                    self._vision_time += time.monotonic() - t0
                    self._vision_encodes += 1
                if self.cfg.vision_variant in ("qwen2", "qwen3"):
                    grid_merged = self.cfg.qwen_vision.merged_grid(frames.shape[0])
                req.vision_features = _VisionFeatures(
                    embeds=vis_embeds,
                    ds=ds_vis,
                    grid=grid_merged,
                    eff_fps=eff_fps,
                    n_tokens=int(vis_embeds.shape[0]),
                )
        n_vis = 0 if vis_embeds is None else int(vis_embeds.shape[0])
        total = n_pre + n_vis + len(req.prompt_ids)
        use_prefix = (
            allow_prefix
            and self.enable_prefix_cache
            and req.share_prefix
            and n_pre >= self.min_prefix_len
            and n_vis + len(req.prompt_ids) > 0  # suffix must be non-empty
            and total <= budget  # tail-keep truncation cuts into the prefix
        )
        parts = []
        if n_pre and not use_prefix:
            pre = jnp.asarray(req.prefix_ids, jnp.int32)
            parts.append(self._embed_tokens(self.params, pre[None])[0])
        if vis_embeds is not None:
            parts.append(vis_embeds)
        if req.prompt_ids:
            ids = jnp.asarray(req.prompt_ids, jnp.int32)
            parts.append(self._embed_tokens(self.params, ids[None])[0])
        embeds = jnp.concatenate(parts, axis=0)
        if self.cfg.mrope_section is not None:
            if grid_merged is None and n_vis:
                # vit-variant vision tokens: treat as a 1 x 1 x n_vis row
                grid_merged = (1, 1, n_vis)
            # Qwen2.5-VL temporal scaling: t_scale = second_per_grid_t *
            # tokens_per_second, second_per_grid_t = temporal_patch_size /
            # sampled fps (HF get_rope_index); Qwen2-VL (tokens_per_second
            # None) keeps the unscaled arange.
            t_scale = 1.0
            qv = self.cfg.qwen_vision
            if (
                qv is not None
                and qv.tokens_per_second
                and eff_fps
                and grid_merged is not None
            ):
                t_scale = qv.tokens_per_second * qv.temporal_patch_size / eff_fps
            rope_pos, next_rope = build_mrope_positions(
                n_pre, grid_merged, len(req.prompt_ids), t_scale
            )
        else:
            rope_pos = np.arange(total, dtype=np.int32)
            next_rope = total
        ds = None
        if ds_vis is not None and self._ds_levels:
            # deepstack buffer: zeros at text positions, the merger levels
            # at the vision span (text-only requests carry ds=None — the
            # prefill buffers read as zeros); suffix-aligned when the
            # prefix is cached
            off = 0 if use_prefix else n_pre
            t_len = (total - n_pre) if use_prefix else total
            ds = np.zeros((self._ds_levels, t_len, embeds.shape[-1]), np.float32)
            ds[:, off : off + ds_vis.shape[1]] = ds_vis
        if use_prefix:
            key = tuple(req.prefix_ids)
            _entry, hit = self._ensure_prefix(key)
            if hit:
                with self._stats_lock:
                    self._prefix_tokens_saved += n_pre
            return _Prepared(
                request=req,
                embeds=np.asarray(embeds, np.float32),
                t_suffix=total - n_pre,
                rope=np.asarray(rope_pos)[n_pre:],
                next_rope=next_rope,
                ds=ds,
                base=n_pre,
                prefix_key=key,
            )
        t_valid = total
        rope_pos = np.asarray(rope_pos)
        if t_valid > budget:
            if req.frames is not None:
                # _fit_frames_to_budget guarantees multimodal prompts fit;
                # slicing here would cut the vision block mid-grid and
                # corrupt the prompt silently
                raise ValueError(
                    f"{req.request_id}: multimodal prompt still over budget "
                    f"after frame reduction ({t_valid} > {budget})"
                )
            # text-only: keep the tail (task instructions usually come
            # last); rope positions stay absolute for the kept tokens
            embeds = embeds[-budget:]
            rope_pos = rope_pos[-budget:]
            if ds is not None:
                ds = ds[:, -budget:]
            t_valid = budget
        return _Prepared(
            request=req,
            embeds=np.asarray(embeds, np.float32),
            t_suffix=t_valid,
            rope=rope_pos,
            next_rope=next_rope,
            ds=ds,
        )

    def _prepare_embeds(self, req: CaptionRequest):
        """Legacy full-layout prep view (no prefix cache): ([T, D] embeds,
        t_valid, [T(,3)] rope positions, next_rope, ds)."""
        p = self._prepare(req, allow_prefix=False)
        return p.embeds, p.t_suffix, p.rope, p.next_rope, p.ds

    def _materialize_full(self, prep: _Prepared) -> _Prepared:
        """Fold the cached prefix back into a prepared request (host-side):
        the fallback when a routed lane cannot place a bucketed suffix at
        offset ``base``, or when tail-keep truncation must see the whole
        layout. Produces the exact uncached prefill inputs."""
        req = prep.request
        n_pre = len(req.prefix_ids)
        pre = jnp.asarray(req.prefix_ids, jnp.int32)
        pre_emb = np.asarray(self._embed_tokens(self.params, pre[None])[0], np.float32)
        t = np.arange(n_pre, dtype=np.int32)
        pre_rope = np.stack([t, t, t], axis=-1) if prep.rope.ndim == 2 else t
        ds = prep.ds
        if ds is not None:
            ds = np.concatenate(
                [np.zeros((ds.shape[0], n_pre, ds.shape[-1]), np.float32), ds], axis=1
            )
        return _Prepared(
            request=req,
            embeds=np.concatenate([pre_emb, prep.embeds], axis=0),
            t_suffix=n_pre + prep.t_suffix,
            rope=np.concatenate([pre_rope, prep.rope], axis=0),
            next_rope=prep.next_rope,
            ds=ds,
        )

    def _ensure_prefix(
        self, key: tuple, count: bool = True
    ) -> "tuple[_PrefixEntry | None, bool]":
        """(entry, was_hit) for one shared text prefix, prefilling it into
        POOL BLOCKS on first use and LRU-inserting the entry. The scratch
        prefill compute runs without the engine lock (it touches no pool
        state, so the prep thread can build a prefix while the decode loop
        runs); only the final block allocation + pool write takes the
        engine lock — lock order is always engine lock -> prefix lock.
        Returns (None, False) when the pool cannot host the entry even
        after evicting idle prefixes: callers serve the prefix uncached.
        ``count=False`` skips the hit counter (the admission-time re-lookup
        must not double-count the prep-time hit); rebuild misses always
        count — an eviction-rebuild is real recompute."""
        with self._prefix_lock:
            entry = self._prefix_cache.get(key)
            if entry is not None:
                self._prefix_cache.move_to_end(key)
                if count:
                    with self._stats_lock:
                        self._prefix_hits += 1
                return entry, True
        if not self.enable_prefix_cache:
            return None, False
        with self._stats_lock:
            self._prefix_misses += 1
        tp = len(key)
        sp = next_pow2(tp)
        emb = np.zeros((1, sp, self.cfg.dim), np.float32)
        emb[0, :tp] = np.asarray(
            self._embed_tokens(self.params, jnp.asarray(key, jnp.int32)[None])[0],
            np.float32,
        )
        pos = np.zeros((1, sp), np.int32)
        pos[0, :tp] = np.arange(tp, dtype=np.int32)
        if self.cfg.mrope_section is not None:
            # text prefix: all three m-rope components equal
            pos = np.broadcast_to(pos[..., None], (1, sp, 3))
        t0 = time.monotonic()
        k, v = self._prefix_prefill(
            self.params,
            jnp.asarray(emb),
            jnp.asarray(pos),
            jnp.asarray(tp, jnp.int32),
        )
        k, v = k[:, :tp], v[:, :tp]
        jax.block_until_ready(v)
        with self._stats_lock:
            self._prefill_time += time.monotonic() - t0
            self._prefill_tokens += tp
        bs = self.block_size
        nb = -(-tp // bs)
        with self._lock:
            with self._prefix_lock:
                raced = self._prefix_cache.get(key)
                if raced is not None:  # a concurrent build won: adopt it
                    self._prefix_cache.move_to_end(key)
                    with self._stats_lock:
                        # the outcome is a HIT (the winner's build is
                        # served); reclassify the miss counted up front so
                        # hit-rate stats stay exact under concurrency
                        self._prefix_misses -= 1
                        self._prefix_hits += 1
                    return raced, True
                if not self._allocator.can_alloc(nb):
                    self._evict_prefixes_for(nb)
                if not self._allocator.can_alloc(nb):
                    logger.warning(
                        "prefix cache: pool exhausted; serving %d-token "
                        "prefix uncached", tp,
                    )
                    return None, False
                ids = self._allocator.alloc(nb)
                self._pool_k, self._pool_v = self._write_prefix_blocks(
                    self._pool_k,
                    self._pool_v,
                    k,
                    v,
                    jnp.asarray(ids, jnp.int32),
                )
                entry = _PrefixEntry(
                    blocks=ids,
                    n_full=tp // bs,
                    tail_block=ids[-1] if tp % bs else None,
                    length=tp,
                )
                self._prefix_cache[key] = entry
                while len(self._prefix_cache) > self.prefix_cache_size:
                    _k2, evicted = self._prefix_cache.popitem(last=False)
                    # referenced blocks defer their free to the last slot
                    self._allocator.decref(evicted.blocks)
                    with self._stats_lock:
                        self._prefix_evictions += 1
                return entry, False

    # holds-lock: _lock, _prefix_lock
    def _evict_prefixes_for(self, n_blocks: int, exclude: tuple | None = None) -> None:
        """Evict idle LRU prefixes until ``n_blocks`` are allocatable (or
        the cache is empty — referenced blocks free only when their last
        slot releases). ``exclude`` protects the entry a claim in progress
        is about to reference. Engine + prefix locks held by caller."""
        for key in list(self._prefix_cache):
            if self._allocator.can_alloc(n_blocks):
                return
            if key == exclude:
                continue
            evicted = self._prefix_cache.pop(key)
            self._allocator.decref(evicted.blocks)
            with self._stats_lock:
                self._prefix_evictions += 1

    # holds-lock: _lock
    def _claim_kv(
        self, lane: _Lane, slot_idx: int, prep: _Prepared, req: CaptionRequest
    ) -> _BlockClaim:
        """Reserve a request's KV blocks and build its block-table row.

        Shared-prefix full blocks are REFERENCED (incref — zero device
        copies, the successor of the deleted insert_prefix path); a
        partially-filled shared tail block is copy-on-write duplicated into
        the request's first private block; the rest of
        ``ceil(need / block_size)`` blocks are fresh private allocations.
        Raises PoolExhausted when the pool cannot supply the private blocks
        (admission backpressure, not an error). Engine lock held by
        caller."""
        bs = self.block_size
        need = min(prep.total + req.sampling.max_new_tokens + 1, lane.length)
        view_blocks = -(-need // bs)
        shared: list[int] = []
        cow_src: int | None = None
        if prep.base:
            with self._prefix_lock:
                entry = self._prefix_cache.get(prep.prefix_key)
            if entry is None:
                # _admit ensured the entry earlier THIS iteration and holds
                # the engine lock inserts/evictions need — it cannot vanish
                raise RuntimeError(f"prefix entry vanished for {req.request_id}")
            shared = list(entry.blocks[: entry.n_full])
            cow_src = entry.tail_block
        private_needed = view_blocks - len(shared)
        if not self._allocator.can_alloc(private_needed):
            if not any(l.claims for l in self.lanes):
                # nothing in flight will ever free blocks — the pool is
                # held by idle prefix entries. Evict them (sparing the one
                # this claim references) instead of deadlocking admission.
                with self._prefix_lock:
                    self._evict_prefixes_for(
                        private_needed,
                        exclude=prep.prefix_key if prep.base else None,
                    )
            if not self._allocator.can_alloc(private_needed):
                raise PoolExhausted(
                    f"{private_needed} KV blocks needed, "
                    f"{self._allocator.free_blocks} free of {self._allocator.capacity}"
                )
        self._allocator.incref(shared)
        private = self._allocator.alloc(private_needed)
        try:
            if cow_src is not None:
                # the suffix extends INTO the partially-filled shared tail
                # block: copy-on-write one block — the only device copy on
                # the whole admission path
                self._pool_k, self._pool_v = self._copy_blocks(
                    self._pool_k,
                    self._pool_v,
                    jnp.asarray([cow_src], jnp.int32),
                    jnp.asarray([private[0]], jnp.int32),
                )
        except BaseException:
            # a failed CoW dispatch must hand the references back, or the
            # shared pool shrinks permanently on every transient error
            self._allocator.decref(shared + private)
            raise
        row = lane.table[slot_idx]
        row[:] = 0
        row[: len(shared)] = shared
        row[len(shared) : view_blocks] = private
        claim = _BlockClaim(shared=shared, private=private)
        lane.claims[slot_idx] = claim
        with self._stats_lock:
            self._requests_admitted += 1
            self._kv_blocks_reserved += view_blocks
            self._kv_private_blocks += len(private)
            self._kv_worstcase_tokens += lane.length
            self._prefix_block_refs += len(shared)
            if cow_src is not None:
                self._kv_cow_copies += 1
            self._kv_blocks_used_peak = max(
                self._kv_blocks_used_peak, self._allocator.used_blocks
            )
            self._owner_requests[req.owner] = (
                self._owner_requests.get(req.owner, 0) + 1
            )
        return claim

    def _release_claim(self, lane: _Lane, slot_idx: int) -> None:
        """Return a slot's block references to the pool. Private blocks
        free immediately; shared prefix blocks free only when the LAST
        reference (including the LRU's own) drops — an evicted-but-still-
        referenced prefix frees here, deferred. Engine lock held by
        caller."""
        claim = lane.claims.pop(slot_idx, None)
        if claim is None:
            return
        self._allocator.decref(claim.all_blocks)
        lane.table[slot_idx, :] = 0
        self._work_cv.notify_all()  # pool-blocked admissions may now fit

    def fit_max_new_tokens(
        self,
        requested: int,
        prompt_ids: list[int],
        prefix_ids: list[int] = (),
        n_frames: int = 0,
    ) -> int:
        """The largest ``max_new_tokens`` (≤ requested, ≥ 1) that leaves
        this prompt inside the longest KV lane — callers with fixed prompts
        clamp generation instead of having the vision block rejected."""
        n = len(prefix_ids) + len(prompt_ids)
        if n_frames:
            n += self._vision_token_count(n_frames)
        return max(1, min(requested, self._max_len - n - 1))

    def _vision_token_count(self, n_frames: int) -> int:
        if self.cfg.vision_variant in ("qwen2", "qwen3"):
            return self.cfg.qwen_vision.tokens_out(n_frames)
        return self.cfg.vision_tokens

    def _fit_frames_to_budget(
        self, req: CaptionRequest
    ) -> tuple[np.ndarray | None, float | None]:
        """An over-budget multimodal prompt re-samples FEWER frames instead
        of silently slicing the vision block (VERDICT r3: tail-keep on a
        frames-heavy request dropped leading vision tokens mid-grid,
        producing a grammatically-valid but semantically-corrupt prompt;
        the reference's windowing guarantees prompts fit,
        windowing_utils.py:53). Raises when even one frame cannot fit —
        the caller's text leaves no room for vision.

        Returns (frames, effective_fps): re-sampling spreads fewer frames
        over the SAME source span, so the temporal m-rope scale must use
        the reduced rate, not the request's original frame_fps."""
        frames = req.frames
        if frames is None:
            return None, None
        budget = self._max_len - req.sampling.max_new_tokens - 1
        n_text = len(req.prefix_ids) + len(req.prompt_ids)
        n = frames.shape[0]
        if n_text + self._vision_token_count(n) <= budget:
            return frames, req.frame_fps
        for n2 in range(n - 1, 0, -1):
            if n_text + self._vision_token_count(n2) <= budget:
                idx = np.linspace(0, n - 1, n2).round().astype(int)
                logger.warning(
                    "%s: prompt over budget; re-sampled %d -> %d frames",
                    req.request_id,
                    n,
                    n2,
                )
                eff = req.frame_fps * (n2 / n) if req.frame_fps else None
                return frames[idx], eff
        raise ValueError(
            f"{req.request_id}: text prompt ({n_text} tokens) leaves no room "
            f"for any vision tokens within budget {budget}"
        )

    # holds-lock: _lock
    def _prefill_group(self, lane: _Lane, bucket: int, items: list) -> None:
        """One batched prefill for all requests sharing a length bucket.

        The row count is padded to a power of two by duplicating row 0
        (same table + same content → the duplicate scatter writes identical
        values), so compiled program count stays O(log max_batch x
        log max_seq). Bucket padding may write past a row's reserved
        blocks ([base, base + bucket) can overshoot need): those positions
        map to garbage-block table entries, whose contents are never read
        unmasked."""
        n = len(items)
        n_pad = next_pow2(n)  # bounded by next_pow2(lane.n_slots)
        dim = items[0][2].shape[-1]
        embeds = np.zeros((n_pad, bucket, dim), np.float32)
        slots_arr = np.zeros(n_pad, np.int32)
        t_valids = np.ones(n_pad, np.int32)
        bases = np.zeros(n_pad, np.int32)
        mrope = self.cfg.mrope_section is not None
        rope_shape = (n_pad, bucket, 3) if mrope else (n_pad, bucket)
        rope_buf = np.zeros(rope_shape, np.int32)
        ds_buf = (
            np.zeros((self._ds_levels, n_pad, bucket, dim), np.float32)
            if self._ds_levels
            else None
        )
        for j, (slot_idx, _req, emb, t_valid, rope_pos, _next, ds, base) in enumerate(
            items
        ):
            embeds[j, :t_valid] = np.asarray(emb, np.float32)[:t_valid]
            slots_arr[j] = slot_idx
            t_valids[j] = t_valid
            bases[j] = base  # shared-prefix rows start past their cached K/V
            rope_buf[j, :t_valid] = rope_pos[:t_valid]
            if ds_buf is not None and ds is not None:
                ds_buf[:, j, :t_valid] = ds[:, :t_valid]
        for j in range(n, n_pad):  # duplicate row 0 into padding
            embeds[j] = embeds[0]
            slots_arr[j] = slots_arr[0]
            t_valids[j] = t_valids[0]
            bases[j] = bases[0]
            rope_buf[j] = rope_buf[0]
            if ds_buf is not None:
                ds_buf[:, j] = ds_buf[:, 0]
        t0 = time.monotonic()
        tables = lane.table[slots_arr]  # [n_pad, nbl]; padding rows = row 0
        logits, self._pool_k, self._pool_v = self._prefill_batch(
            self.params,
            self._pool_k,
            self._pool_v,
            jnp.asarray(tables),
            jnp.asarray(embeds),
            jnp.asarray(bases),
            jnp.asarray(t_valids),
            jnp.asarray(rope_buf),
            None if ds_buf is None else jnp.asarray(ds_buf),
        )
        logits_np = np.asarray(logits)  # one host sync for the whole group
        with self._stats_lock:
            self._prefill_time += time.monotonic() - t0
            self._prefill_tokens += int(sum(it[3] for it in items))
            if self._use_paged:
                self._kv_gather_bytes_avoided += self._gather_view_bytes(
                    len(tables), lane.length
                )
        for j, (slot_idx, req, _emb, t_valid, _rope, next_rope, _ds, base) in enumerate(
            items
        ):
            self._start_slot(lane, slot_idx, req, base + t_valid, next_rope, logits_np[j])

    def _start_slot(
        self,
        lane: _Lane,
        slot_idx: int,
        req: CaptionRequest,
        t_valid: int,
        next_rope: int,
        logits_row: np.ndarray,
    ) -> None:
        """Sample the first token from the last-prompt-position logits and
        enter the slot into the continuous decode batch."""
        # seed=None is the unseeded sentinel; any int (incl. 0) pins
        rng = (
            np.random.default_rng(req.sampling.seed)
            if req.sampling.seed is not None
            else None
        )
        counts: dict[int, int] | None = None
        s = req.sampling
        if (
            s.repetition_penalty != 1.0
            or s.presence_penalty != 0.0
            or s.frequency_penalty != 0.0
        ):
            # penalty history covers prompt tokens too (vLLM
            # semantics); maintained incrementally from here on
            counts = {}
            for t in [*req.prefix_ids, *req.prompt_ids]:
                counts[t] = counts.get(t, 0) + 1
        first = sample_token(
            logits_row,
            req.sampling,
            generated=counts,
            num_generated=0,
            eos_id=self.tokenizer.eos_id,
            rng=rng if rng is not None else self._host_rng,
        )
        slot = _Slot(
            request=req,
            position=t_valid,
            rope_position=next_rope,
            generated=[first],
            rng=rng,
            penalty_counts=counts,
        )
        if counts is not None:
            counts[first] = counts.get(first, 0) + 1
        if req.sampling.stop:
            slot.raw += self.tokenizer.decode_bytes([first])
        lane.slots[slot_idx] = slot
        self._maybe_finish(lane, slot_idx, slot)

    # holds-lock: _lock
    def _prefill_chunk_step(self, lane: _Lane) -> None:
        """Advance every pending chunked prefill by one chunk (one batched
        program call); rows finishing their prompt enter the decode batch."""
        C = self.prefill_chunk
        items = list(lane.pending.items())
        if not items:
            return
        n = len(items)
        n_pad = next_pow2(n)  # bounded by next_pow2(lane.n_slots)
        dim = items[0][1].embeds.shape[-1]
        mrope = self.cfg.mrope_section is not None
        embeds = np.zeros((n_pad, C, dim), np.float32)
        slots_arr = np.zeros(n_pad, np.int32)
        write_idx = np.zeros(n_pad, np.int32)
        chunk_valid = np.ones(n_pad, np.int32)
        rope_buf = np.zeros((n_pad, C, 3) if mrope else (n_pad, C), np.int32)
        ds_buf = (
            np.zeros((self._ds_levels, n_pad, C, dim), np.float32)
            if self._ds_levels
            else None
        )
        new_tokens = 0
        for j, (slot_idx, p) in enumerate(items):
            take = min(C, p.t_valid - p.progress)
            start = p.progress
            if take < C:
                # final partial chunk: shift back so the C-length buffer
                # ends exactly at the prompt end. The overlapped rows
                # rewrite identical K/V (same embeds, same rope, correct
                # causal mask), and dynamic_update_slice stays in bounds
                # for shared-prefix bases > 0 and for lane lengths that are
                # not a multiple of the chunk size.
                start = p.t_valid - C
            new_tokens += take
            embeds[j] = p.embeds[start : start + C]
            slots_arr[j] = slot_idx
            write_idx[j] = p.base + start
            chunk_valid[j] = C if start < p.progress else take
            rope_buf[j] = p.rope_pos[start : start + C]
            if ds_buf is not None and p.ds is not None:
                ds_buf[:, j] = p.ds[:, start : start + C]
        for j in range(n, n_pad):  # duplicate row 0 (identical writes: safe)
            embeds[j] = embeds[0]
            slots_arr[j] = slots_arr[0]
            write_idx[j] = write_idx[0]
            chunk_valid[j] = chunk_valid[0]
            rope_buf[j] = rope_buf[0]
            if ds_buf is not None:
                ds_buf[:, j] = ds_buf[:, 0]
        t0 = time.monotonic()
        tables = lane.table[slots_arr]  # [n_pad, nbl]; padding rows = row 0
        logits, self._pool_k, self._pool_v = self._prefill_batch(
            self.params,
            self._pool_k,
            self._pool_v,
            jnp.asarray(tables),
            jnp.asarray(embeds),
            jnp.asarray(write_idx),
            jnp.asarray(chunk_valid),
            jnp.asarray(rope_buf),
            None if ds_buf is None else jnp.asarray(ds_buf),
        )
        finished = []
        for j, (slot_idx, p) in enumerate(items):
            p.progress += min(C, p.t_valid - p.progress)
            if p.progress >= p.t_valid:
                finished.append((j, slot_idx, p))
        if finished:
            logits_np = np.asarray(logits)
            for j, slot_idx, p in finished:
                del lane.pending[slot_idx]
                self._start_slot(
                    lane, slot_idx, p.request, p.base + p.t_valid, p.next_rope,
                    logits_np[j],
                )
        with self._stats_lock:
            self._prefill_time += time.monotonic() - t0
            self._prefill_tokens += new_tokens
            if self._use_paged:
                self._kv_gather_bytes_avoided += self._gather_view_bytes(
                    len(tables), lane.length
                )

    # holds-lock: _lock
    def _decode_once(self, lane: _Lane) -> None:
        tokens = np.full(lane.n_slots, self.tokenizer.pad_id, np.int32)
        positions = np.zeros(lane.n_slots, np.int32)
        rope_positions = np.zeros(lane.n_slots, np.int32)
        # The decode program scatters K/V for EVERY row (static shapes, no
        # write mask), so idle rows' write positions must be harmless.
        # Fully-free rows carry an all-garbage block table — their write
        # lands in the reserved garbage block — but a row mid-chunked-
        # prefill holds real prompt K/V: point its write at base +
        # progress, a cell the NEXT chunk overwrites anyway (the shifted
        # final chunk covers [t_valid - C, t_valid), which contains it), so
        # the pad-token garbage can never survive into attention reads.
        for i, p in lane.pending.items():
            positions[i] = p.base + p.progress
        for i, slot in lane.slots.items():
            tokens[i] = slot.generated[-1]
            positions[i] = slot.position
            rope_positions[i] = slot.rope_position
        t0 = time.monotonic()
        greedy, logits, self._pool_k, self._pool_v = self._decode(
            self.params,
            self._pool_k,
            self._pool_v,
            jnp.asarray(lane.table),
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(rope_positions),
        )
        greedy_np = np.asarray(greedy)  # ONE host sync for the whole batch
        dt = time.monotonic() - t0  # program call + sync: same site, both paths
        with self._stats_lock:
            self._decode_time += dt
            self._decode_attn_time += dt
            self._decode_tokens += len(lane.slots)
            self._decode_rows += lane.n_slots
            if self._use_paged:
                self._paged_kernel_steps += 1
                self._kv_gather_bytes_avoided += self._gather_view_bytes(
                    lane.n_slots, lane.length
                )
            for slot in lane.slots.values():
                owner = slot.request.owner
                self._owner_decode_tokens[owner] = (
                    self._owner_decode_tokens.get(owner, 0) + 1
                )
        # the device argmax suffices only for pure-greedy rows with no
        # penalties and min_tokens already satisfied
        needs_logits = any(
            s.request.sampling.needs_logits(len(s.generated))
            for s in lane.slots.values()
        )
        logits_np = np.asarray(logits) if needs_logits else None
        for i in list(lane.slots):
            slot = lane.slots[i]
            if slot.request.sampling.needs_logits(len(slot.generated)):
                nxt = sample_token(
                    logits_np[i],
                    slot.request.sampling,
                    # incrementally maintained prompt+output counts; the
                    # decode loop must not re-unique the history per token
                    generated=slot.penalty_counts,
                    num_generated=len(slot.generated),
                    eos_id=self.tokenizer.eos_id,
                    rng=slot.rng if slot.rng is not None else self._host_rng,
                )
            else:
                nxt = int(greedy_np[i])
            slot.generated.append(nxt)
            if slot.penalty_counts is not None:
                slot.penalty_counts[nxt] = slot.penalty_counts.get(nxt, 0) + 1
            if slot.request.sampling.stop:
                slot.raw += self.tokenizer.decode_bytes([nxt])
            slot.position += 1
            slot.rope_position += 1
            self._maybe_finish(lane, i, slot)

    def _maybe_finish(self, lane: _Lane, slot_idx: int, slot: _Slot) -> None:
        req = slot.request
        done = (
            slot.generated[-1] == self.tokenizer.eos_id
            or len(slot.generated) >= req.sampling.max_new_tokens
            or slot.position + 1 >= lane.length
        )
        stop_text: str | None = None
        if not done and req.sampling.stop:
            # stop strings match on decoded text (vLLM `stop`); the match
            # and everything after it is dropped. The hot path scans only a
            # bounded tail of the incrementally maintained byte buffer
            # (slot.raw — exact regardless of zero-byte special tokens);
            # the full decode runs once, on a hit.
            longest = max(len(s) for s in req.sampling.stop)
            tail = bytes(slot.raw[-(4 * longest + 8) :]).decode("utf-8", errors="replace")
            if any(s in tail for s in req.sampling.stop):
                stop_text = _truncate_at_stop(
                    bytes(slot.raw).decode("utf-8", errors="replace"), req.sampling.stop
                )
                done = stop_text is not None
        if not done:
            return
        del lane.slots[slot_idx]
        self._release_claim(lane, slot_idx)
        out_ids = [t for t in slot.generated if t != self.tokenizer.eos_id]
        text = stop_text if stop_text is not None else self.tokenizer.decode(out_ids)
        if stop_text is None and req.sampling.stop:
            # a stop string may land in the same step that hit eos/max
            truncated = _truncate_at_stop(text, req.sampling.stop)
            if truncated is not None:
                text = truncated
        result = CaptionResult(
            request_id=req.request_id,
            text=text,
            num_prompt_tokens=len(req.prefix_ids) + len(req.prompt_ids),
            num_output_tokens=len(slot.generated),
            metadata=req.metadata,
            owner=req.owner,
        )
        if req.on_complete is not None:
            follow_up = req.on_complete(text)
            if follow_up is not None:
                if follow_up.owner is None:
                    follow_up.owner = req.owner
                if (
                    follow_up.frames is not None
                    and follow_up.frames is req.frames
                    and follow_up.vision_features is None
                ):
                    # refinement over the SAME frames array: hand the
                    # already-encoded vision features to the follow-up so
                    # the tower doesn't run twice per window
                    follow_up.vision_features = req.vision_features
                self.waiting.append(follow_up)
                self._work_cv.notify_all()  # wake the prep thread
                return  # result superseded by the refinement pass
        self.completed.append(result)
