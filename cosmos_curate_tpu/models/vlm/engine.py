"""Continuous-batching caption engine.

Equivalent capability of the reference's vLLM engine driver
(cosmos_curate/models/vllm_interface.py:390-703 — ``add_request``/``step``
in-flight batching with two-stage caption refinement; async variant
vllm_async_stage.py). TPU-first re-design:

- **slot-based KV cache**: a static ``[L, max_batch, max_seq, Hkv, Dh]``
  cache; a request claims a free slot, prefills at a power-of-two bucket
  length, then joins the batched one-token decode step. All jitted programs
  have static shapes — XLA compiles O(log max_seq) prefill buckets plus one
  decode program, nothing per-request.
- **continuous batching**: slots join/leave between decode steps; the decode
  step always runs the full slot batch with an active mask (idle rows write
  into masked cache cells — dead work, bounded by max_batch, in exchange
  for zero recompiles).
- **tokens/s** is tracked per engine — THE caption-throughput metric
  (reference docs/curator/design/SPEED_OF_LIGHT.md).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.models.batching import next_pow2
from cosmos_curate_tpu.models.tokenizer import ByteTokenizer, default_caption_tokenizer
from cosmos_curate_tpu.models.vlm.model import VLM, VLMConfig, init_cache

# full sampling surface (top_p/min_p/penalties/min_tokens) lives in
# models/vlm/sampling.py; re-exported here for the existing import paths
from cosmos_curate_tpu.models.vlm.sampling import SamplingConfig, sample_token
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CaptionRequest:
    request_id: str
    prompt_ids: list[int]
    frames: np.ndarray | None = None  # uint8 [N, H, W, 3]
    sampling: SamplingConfig = field(default_factory=SamplingConfig)
    # called with the finished text; may return a follow-up request
    # (two-stage caption refinement, reference vllm_interface.py:543)
    on_complete: Callable[[str], "CaptionRequest | None"] | None = None
    metadata: dict[str, Any] = field(default_factory=dict)
    # set by add_request: which caller's run_until_complete owns this request
    # (several caption-family stages share one engine; see run_until_complete)
    owner: Any = None


@dataclass
class _Slot:
    request: CaptionRequest
    position: int  # next cache position to write (== current length)
    generated: list[int] = field(default_factory=list)
    # per-request generator when sampling.seed is set (reproducible
    # captions regardless of batch interleaving); None = engine-shared rng
    rng: np.random.Generator | None = None
    # incrementally decoded output bytes (exact: decode is per-token byte
    # concatenation) — stop-string checks scan a bounded tail of this
    raw: bytearray = field(default_factory=bytearray)
    # prompt+output token counts maintained incrementally for penalties
    # (None when no penalty is configured)
    penalty_counts: dict[int, int] | None = None


def _truncate_at_stop(text: str, stops: tuple[str, ...]) -> str | None:
    """Text before the EARLIEST stop-string match (tuple order must not
    matter), or None when nothing matches."""
    idx = min((i for i in (text.find(s) for s in stops) if i >= 0), default=-1)
    return text[:idx] if idx >= 0 else None


@dataclass
class CaptionResult:
    request_id: str
    text: str
    num_prompt_tokens: int
    num_output_tokens: int
    metadata: dict[str, Any] = field(default_factory=dict)
    owner: Any = None


class CaptionEngine:
    def __init__(
        self,
        cfg: VLMConfig,
        *,
        max_batch: int = 8,
        params: Any = None,
        tokenizer: ByteTokenizer | None = None,
    ) -> None:
        self.cfg = cfg
        self.max_batch = max_batch
        self.tokenizer = tokenizer or default_caption_tokenizer()
        self.model = VLM(cfg)
        self.params = params
        self.waiting: list[CaptionRequest] = []
        self.slots: dict[int, _Slot] = {}
        self.completed: list[CaptionResult] = []
        self._decode_tokens = 0
        self._decode_time = 0.0
        self._built = False
        # One engine is shared by every caption-family stage in a pipeline
        # (weights + KV cache are too big to duplicate). Stages run in
        # separate pool threads, and the jitted prefill/decode donate the
        # cache buffers — concurrent steps would be use-after-donate. This
        # lock serializes all engine mutation; completions are owner-tagged
        # so one stage's run cannot steal another stage's results.
        self._lock = threading.RLock()

    # -- setup ----------------------------------------------------------
    def setup(self, seed: int = 0) -> None:
        cfg = self.cfg
        if self.params is None:
            size = cfg.vision.image_size
            frames = jnp.zeros((1, 1, size, size, 3), jnp.uint8)
            ids = jnp.zeros((1, 4), jnp.int32)
            ck, cv = init_cache(cfg, 1)
            self.params = self.model.init(
                jax.random.PRNGKey(seed),
                frames,
                ids,
                ck,
                cv,
                method=self.model.init_everything,
            )
        self.cache_k, self.cache_v = init_cache(cfg, self.max_batch)

        model = self.model

        @jax.jit
        def encode_images(params, frames_u8):
            return model.apply(params, frames_u8, method=model.encode_images)

        @jax.jit
        def embed_tokens(params, ids):
            return model.apply(params, ids, method=model.embed_tokens)

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_batch(params, cache_k, cache_v, embeds, slots, t_valid):
            """Batched bucket prefill (replaces the round-1 one-request-at-a-
            time admission — the reference leans on vLLM's batched prefill,
            vllm_interface.py:543). embeds: [N, Tb, D] (bucket-padded);
            slots/t_valid: [N]. Writes every request's cache rows in one
            program and returns each row's logits at its last valid
            position: [N, V]."""
            ck = cache_k[:, slots]  # [L, N, S, Hkv, Dh]
            cv = cache_v[:, slots]
            n, t, _ = embeds.shape
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (n, t))
            logits, nk, nv = model.apply(
                params,
                embeds,
                ck,
                cv,
                positions,
                jnp.zeros((n,), jnp.int32),
                t_valid,
            )
            cache_k = cache_k.at[:, slots].set(nk)
            cache_v = cache_v.at[:, slots].set(nv)
            last = jnp.take_along_axis(
                logits, (t_valid - 1)[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
            return last, cache_k, cache_v

        @partial(jax.jit, donate_argnums=(1, 2))
        def decode_step(params, cache_k, cache_v, tokens, positions):
            """tokens/positions: [max_batch]; one token for every slot.

            Greedy argmax happens ON DEVICE for the whole batch — per-slot
            host argmaxes were the decode loop's bottleneck (one device
            sync per slot per token)."""
            embeds = model.apply(params, tokens[:, None], method=model.embed_tokens)
            logits, ck, cv = model.apply(
                params,
                embeds,
                cache_k,
                cache_v,
                positions[:, None],
                positions,
                positions + 1,
            )
            step_logits = logits[:, 0]
            greedy = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            return greedy, step_logits, ck, cv

        self._host_rng = np.random.default_rng(seed)
        self._encode_images = encode_images
        self._embed_tokens = embed_tokens
        self._prefill_batch = prefill_batch
        self._decode = decode_step
        self._built = True

    # -- public API -----------------------------------------------------
    def add_request(self, request: CaptionRequest, owner: Any = None) -> None:
        budget = self.cfg.max_seq - request.sampling.max_new_tokens - 1
        if budget <= 0:
            raise ValueError(
                f"max_new_tokens={request.sampling.max_new_tokens} leaves no "
                f"prompt budget in max_seq={self.cfg.max_seq}"
            )
        if request.owner is None:
            request.owner = owner if owner is not None else threading.get_ident()
        with self._lock:
            self.waiting.append(request)

    def has_work(self, owner: Any = None) -> bool:
        with self._lock:
            if owner is None:
                return bool(self.waiting or self.slots)
            return any(r.owner == owner for r in self.waiting) or any(
                s.request.owner == owner for s in self.slots.values()
            )

    def run_until_complete(self, owner: Any = None) -> list[CaptionResult]:
        """Drive the engine until this caller's requests are done.

        ``owner`` defaults to the calling thread's ident — the same default
        ``add_request`` tags requests with — so the existing
        add-then-run-in-one-thread usage is unchanged. Requests queued by
        other owners still ride along in the continuous batch (free
        throughput), but their completions stay queued for *their*
        ``run_until_complete``.
        """
        if owner is None:
            owner = threading.get_ident()
        while True:
            # Lock per step, not across the drain: another stage's
            # add_request must be able to slip in between decode steps so
            # its requests actually join the continuous batch.
            with self._lock:
                if not self.has_work(owner):
                    mine = [r for r in self.completed if r.owner == owner]
                    self.completed = [r for r in self.completed if r.owner != owner]
                    return mine
                self.step()

    @property
    def tokens_per_second(self) -> float:
        return self._decode_tokens / self._decode_time if self._decode_time > 0 else 0.0

    # -- engine internals ----------------------------------------------
    def step(self) -> None:
        """Admit waiting requests into free slots, then one decode step."""
        if not self._built:
            raise RuntimeError("call setup() first")
        with self._lock:
            self._admit()
            if self.slots:
                self._decode_once()

    def _admit(self) -> None:
        free = [i for i in range(self.max_batch) if i not in self.slots]
        prepared: list[tuple[int, CaptionRequest, Any, int]] = []
        while free and self.waiting:
            slot_idx = free.pop(0)
            req = self.waiting.pop(0)
            try:
                embeds, t_valid = self._prepare_embeds(req)
            except Exception:
                logger.exception("prefill prep failed for %s; dropping", req.request_id)
                continue
            prepared.append((slot_idx, req, embeds, t_valid))
        # group by prefill bucket; each group runs ONE batched prefill
        groups: dict[int, list[tuple[int, CaptionRequest, Any, int]]] = {}
        for item in prepared:
            bucket = min(next_pow2(item[3]), self.cfg.max_seq)
            groups.setdefault(bucket, []).append(item)
        for bucket, items in sorted(groups.items()):
            try:
                self._prefill_group(bucket, items)
            except Exception:
                if len(items) == 1:
                    logger.exception(
                        "prefill failed for %s; dropping", items[0][1].request_id
                    )
                    continue
                # isolate the offender: retry each request as its own group
                logger.exception(
                    "batched prefill failed for %d requests; retrying singly",
                    len(items),
                )
                for item in items:
                    try:
                        self._prefill_group(bucket, [item])
                    except Exception:
                        logger.exception(
                            "prefill failed for %s; dropping", item[1].request_id
                        )

    def _prepare_embeds(self, req: CaptionRequest):
        """Vision encode + token embed for one request -> ([T, D], t_valid)."""
        parts = []
        if req.frames is not None:
            vis = self._encode_images(self.params, jnp.asarray(req.frames)[None])
            parts.append(vis[0])
        ids = jnp.asarray(req.prompt_ids, jnp.int32)
        parts.append(self._embed_tokens(self.params, ids[None])[0])
        embeds = jnp.concatenate(parts, axis=0)
        t_valid = embeds.shape[0]
        budget = self.cfg.max_seq - req.sampling.max_new_tokens - 1
        if t_valid > budget:
            # keep the tail (task instructions usually come last)
            embeds = embeds[-budget:]
            t_valid = budget
        return embeds, t_valid

    def _prefill_group(self, bucket: int, items: list) -> None:
        """One batched prefill for all requests sharing a length bucket.

        The row count is padded to a power of two by duplicating row 0
        (same slot + same content → the duplicate scatter writes identical
        values), so compiled program count stays O(log max_batch x
        log max_seq)."""
        n = len(items)
        n_pad = min(next_pow2(n), self.max_batch)
        dim = items[0][2].shape[-1]
        embeds = np.zeros((n_pad, bucket, dim), np.float32)
        slots_arr = np.zeros(n_pad, np.int32)
        t_valids = np.ones(n_pad, np.int32)
        for j, (slot_idx, _req, emb, t_valid) in enumerate(items):
            embeds[j, :t_valid] = np.asarray(emb, np.float32)[:t_valid]
            slots_arr[j] = slot_idx
            t_valids[j] = t_valid
        for j in range(n, n_pad):  # duplicate row 0 into padding
            embeds[j] = embeds[0]
            slots_arr[j] = slots_arr[0]
            t_valids[j] = t_valids[0]
        logits, self.cache_k, self.cache_v = self._prefill_batch(
            self.params,
            self.cache_k,
            self.cache_v,
            jnp.asarray(embeds),
            jnp.asarray(slots_arr),
            jnp.asarray(t_valids),
        )
        logits_np = np.asarray(logits)  # one host sync for the whole group
        for j, (slot_idx, req, _emb, t_valid) in enumerate(items):
            # seed=None is the unseeded sentinel; any int (incl. 0) pins
            rng = (
                np.random.default_rng(req.sampling.seed)
                if req.sampling.seed is not None
                else None
            )
            counts: dict[int, int] | None = None
            s = req.sampling
            if (
                s.repetition_penalty != 1.0
                or s.presence_penalty != 0.0
                or s.frequency_penalty != 0.0
            ):
                # penalty history covers prompt tokens too (vLLM
                # semantics); maintained incrementally from here on
                counts = {}
                for t in req.prompt_ids:
                    counts[t] = counts.get(t, 0) + 1
            first = sample_token(
                logits_np[j],
                req.sampling,
                generated=counts,
                num_generated=0,
                eos_id=self.tokenizer.eos_id,
                rng=rng if rng is not None else self._host_rng,
            )
            slot = _Slot(
                request=req,
                position=t_valid,
                generated=[first],
                rng=rng,
                penalty_counts=counts,
            )
            if counts is not None:
                counts[first] = counts.get(first, 0) + 1
            if req.sampling.stop:
                slot.raw += self.tokenizer.decode_bytes([first])
            self.slots[slot_idx] = slot
            self._maybe_finish(slot_idx, slot)

    def _decode_once(self) -> None:
        tokens = np.full(self.max_batch, self.tokenizer.pad_id, np.int32)
        positions = np.zeros(self.max_batch, np.int32)
        for i, slot in self.slots.items():
            tokens[i] = slot.generated[-1]
            positions[i] = slot.position
        t0 = time.monotonic()
        greedy, logits, self.cache_k, self.cache_v = self._decode(
            self.params, self.cache_k, self.cache_v, jnp.asarray(tokens), jnp.asarray(positions)
        )
        greedy_np = np.asarray(greedy)  # ONE host sync for the whole batch
        self._decode_time += time.monotonic() - t0
        self._decode_tokens += len(self.slots)
        # the device argmax suffices only for pure-greedy rows with no
        # penalties and min_tokens already satisfied
        needs_logits = any(
            s.request.sampling.needs_logits(len(s.generated))
            for s in self.slots.values()
        )
        logits_np = np.asarray(logits) if needs_logits else None
        for i in list(self.slots):
            slot = self.slots[i]
            if slot.request.sampling.needs_logits(len(slot.generated)):
                nxt = sample_token(
                    logits_np[i],
                    slot.request.sampling,
                    # incrementally maintained prompt+output counts; the
                    # decode loop must not re-unique the history per token
                    generated=slot.penalty_counts,
                    num_generated=len(slot.generated),
                    eos_id=self.tokenizer.eos_id,
                    rng=slot.rng if slot.rng is not None else self._host_rng,
                )
            else:
                nxt = int(greedy_np[i])
            slot.generated.append(nxt)
            if slot.penalty_counts is not None:
                slot.penalty_counts[nxt] = slot.penalty_counts.get(nxt, 0) + 1
            if slot.request.sampling.stop:
                slot.raw += self.tokenizer.decode_bytes([nxt])
            slot.position += 1
            self._maybe_finish(i, slot)

    def _maybe_finish(self, slot_idx: int, slot: _Slot) -> None:
        req = slot.request
        done = (
            slot.generated[-1] == self.tokenizer.eos_id
            or len(slot.generated) >= req.sampling.max_new_tokens
            or slot.position + 1 >= self.cfg.max_seq
        )
        stop_text: str | None = None
        if not done and req.sampling.stop:
            # stop strings match on decoded text (vLLM `stop`); the match
            # and everything after it is dropped. The hot path scans only a
            # bounded tail of the incrementally maintained byte buffer
            # (slot.raw — exact regardless of zero-byte special tokens);
            # the full decode runs once, on a hit.
            longest = max(len(s) for s in req.sampling.stop)
            tail = bytes(slot.raw[-(4 * longest + 8) :]).decode("utf-8", errors="replace")
            if any(s in tail for s in req.sampling.stop):
                stop_text = _truncate_at_stop(
                    bytes(slot.raw).decode("utf-8", errors="replace"), req.sampling.stop
                )
                done = stop_text is not None
        if not done:
            return
        del self.slots[slot_idx]
        out_ids = [t for t in slot.generated if t != self.tokenizer.eos_id]
        text = stop_text if stop_text is not None else self.tokenizer.decode(out_ids)
        if stop_text is None and req.sampling.stop:
            # a stop string may land in the same step that hit eos/max
            truncated = _truncate_at_stop(text, req.sampling.stop)
            if truncated is not None:
                text = truncated
        result = CaptionResult(
            request_id=req.request_id,
            text=text,
            num_prompt_tokens=len(req.prompt_ids),
            num_output_tokens=len(slot.generated),
            metadata=req.metadata,
            owner=req.owner,
        )
        if req.on_complete is not None:
            follow_up = req.on_complete(text)
            if follow_up is not None:
                if follow_up.owner is None:
                    follow_up.owner = req.owner
                self.waiting.append(follow_up)
                return  # result superseded by the refinement pass
        self.completed.append(result)
