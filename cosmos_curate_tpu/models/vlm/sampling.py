"""Host-side token sampling with the reference's full parameter surface.

Equivalent capability of the reference's VllmSamplingConfig
(pipelines/video/utils/data_model.py:900-931: presence/frequency/repetition
penalties, temperature, top_p, top_k, min_p, min_tokens, max_tokens) —
applied on host to one slot's logits row. Device work stays greedy-argmax
for the pure-greedy fast path; any non-default knob routes the row through
here (one numpy pass, no device round-trips).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class SamplingConfig:
    max_new_tokens: int = 256
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # 1.0 = disabled (nucleus)
    min_p: float = 0.0  # 0.0 = disabled
    repetition_penalty: float = 1.0  # 1.0 = disabled
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    min_tokens: int = 0  # suppress EOS until this many tokens generated
    # generation stops when any of these strings appears in the decoded
    # text; the match and everything after it is dropped (vLLM `stop`)
    stop: tuple[str, ...] = ()
    # None = unseeded (engine-shared rng); any int — including 0 — pins
    # this request's draws to a dedicated generator
    seed: int | None = None

    @property
    def needs_host_sampling(self) -> bool:
        """True when the device greedy-argmax result is insufficient."""
        return (
            self.temperature > 0.0
            or self.repetition_penalty != 1.0
            or self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
        )

    def needs_logits(self, num_generated: int) -> bool:
        return self.needs_host_sampling or num_generated < self.min_tokens


def apply_penalties(
    logits: np.ndarray,
    generated: list[int] | dict[int, int],
    cfg: SamplingConfig,
) -> np.ndarray:
    """Repetition / presence / frequency penalties over generated history
    (vLLM semantics: repetition divides positive logits and multiplies
    negative ones; presence subtracts once per seen token; frequency
    subtracts per occurrence).

    ``generated`` may be a token list or a precomputed ``{token: count}``
    map — hot loops maintain the map incrementally instead of re-uniquing
    the full prompt+output history every token."""
    if not generated or (
        cfg.repetition_penalty == 1.0
        and cfg.presence_penalty == 0.0
        and cfg.frequency_penalty == 0.0
    ):
        return logits
    logits = logits.astype(np.float64).copy()
    if isinstance(generated, dict):
        seen = np.fromiter(generated.keys(), np.int64, len(generated))
        counts = np.fromiter(generated.values(), np.int64, len(generated))
    else:
        seen, counts = np.unique(np.asarray(generated, np.int64), return_counts=True)
    in_range = (seen >= 0) & (seen < logits.shape[-1])
    seen = seen[in_range]
    counts = counts[in_range]
    if cfg.repetition_penalty != 1.0:
        vals = logits[seen]
        logits[seen] = np.where(
            vals > 0, vals / cfg.repetition_penalty, vals * cfg.repetition_penalty
        )
    if cfg.presence_penalty:
        logits[seen] -= cfg.presence_penalty
    if cfg.frequency_penalty:
        logits[seen] -= cfg.frequency_penalty * counts
    return logits


def sample_token(
    logits_row: np.ndarray,
    cfg: SamplingConfig,
    *,
    generated: list[int] | dict[int, int] | None = None,
    num_generated: int | None = None,
    eos_id: int | None = None,
    rng: np.random.Generator | None = None,
) -> int:
    """One token from one logits row under the full sampling config.

    ``generated`` is the penalty history (list or ``{token: count}`` map) —
    vLLM's repetition penalty covers prompt AND output tokens, so callers
    pass both. ``num_generated`` is the OUTPUT-token count governing
    min_tokens (defaults to len(generated) for standalone list use).
    ``eos_id`` is masked out while num_generated < min_tokens. Greedy
    (temperature<=0) still applies penalties and the EOS mask."""
    generated = generated or []
    if num_generated is None:
        num_generated = (
            int(sum(generated.values())) if isinstance(generated, dict) else len(generated)
        )
    logits = apply_penalties(np.asarray(logits_row), generated, cfg)
    if eos_id is not None and num_generated < cfg.min_tokens:
        logits = logits.astype(np.float64).copy()
        logits[eos_id] = -np.inf
    if cfg.temperature <= 0.0:
        return int(np.argmax(logits))
    scaled = logits.astype(np.float64) / cfg.temperature
    k = min(cfg.top_k, scaled.shape[-1]) if cfg.top_k > 0 else 0
    if 0 < k < scaled.shape[-1]:
        kth = np.partition(scaled, -k)[-k]
        scaled = np.where(scaled < kth, -np.inf, scaled)
    scaled = scaled - scaled.max()
    probs = np.exp(scaled)
    probs /= probs.sum()
    # vLLM filter order: top_p over the raw distribution, THEN min_p —
    # reversing it computes the nucleus over renormalized (inflated) probs
    if cfg.top_p < 1.0:
        order = np.argsort(probs)[::-1]
        csum = np.cumsum(probs[order])
        # smallest prefix with mass >= top_p
        cutoff = int(np.searchsorted(csum, cfg.top_p)) + 1
        mask = np.zeros_like(probs, bool)
        mask[order[:cutoff]] = True
        probs = np.where(mask, probs, 0.0)
    if cfg.min_p > 0.0:
        keep = probs >= cfg.min_p * probs.max()
        probs = np.where(keep, probs, 0.0)
    probs /= probs.sum()
    if rng is None:
        rng = _fallback_rng(cfg.seed)
    return int(rng.choice(len(probs), p=probs))


_FALLBACK_RNGS: dict[int, np.random.Generator] = {}


def _fallback_rng(seed: int) -> np.random.Generator:
    """Per-seed generator whose state ADVANCES across calls — a fresh
    default_rng(seed) per token would repeat the same draw every step."""
    rng = _FALLBACK_RNGS.get(seed)
    if rng is None:
        rng = _FALLBACK_RNGS[seed] = np.random.default_rng(seed)
    return rng
