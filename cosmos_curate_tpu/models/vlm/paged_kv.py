"""Paged KV-cache primitives for the caption engine.

vLLM's PagedAttention block-table design (Kwon et al. 2023 — PAPERS.md)
re-shaped for XLA's static-shape compilation: KV memory is ONE block pool
``[L, n_blocks, block_size, Hkv, Dh]`` and every slot owns a block *table*
instead of a worst-case-length cache row, so a request's KV footprint is
``ceil(len / block_size)`` blocks. Rather than a dynamic per-read gather
inside the attention kernel (hostile to XLA), the engine's prefill/decode
programs gather each slot's blocks into a contiguous ``[lane_length]`` view
— the exact shapes the slot-row engine compiled, so greedy outputs stay
byte-identical — run the unchanged model, and scatter the written blocks
back.

Why duplicate scatter indices are safe: shared-prefix blocks appear in MANY
slots' tables at once (that is the point — zero device copies at
admission). The scatter that writes views back therefore writes the same
block several times, and XLA leaves the winning order undefined. The
engine's invariant makes every such write identical: a slot's own K/V
writes always start at the prefix boundary (copy-on-write gives it a
private copy of any partially-filled shared tail block first), so shared
blocks are only ever written back with their unchanged gathered contents.
Block 0 is a reserved garbage block: free table entries point at it and
the decode program's unconditional writes for idle rows land there — its
contents are never read unmasked.

The allocator is host-side and refcounted: the shared-prefix LRU holds one
reference per block it caches, every admitted slot holds one per shared
block it maps, and a block returns to the free list only when the last
reference drops — evicting a prefix whose blocks are still mapped by
in-flight slots defers the free instead of corrupting them.
"""

from __future__ import annotations

import jax.numpy as jnp


class PoolExhausted(RuntimeError):
    """The block pool cannot supply the requested allocation right now.

    Admission treats this as backpressure (the request waits for in-flight
    slots to free their blocks), not as an error."""


class BlockAllocator:
    """Refcounted free-list allocator over pool block ids.

    Block 0 is the reserved garbage block (never handed out): free table
    entries point at it so the static-shape decode program has a harmless
    write target for idle rows. All mutation runs under the engine lock —
    the allocator itself is deliberately lock-free.
    """

    def __init__(self, n_blocks: int) -> None:
        if n_blocks < 2:
            raise ValueError(f"block pool needs >= 2 blocks, got {n_blocks}")
        self.n_blocks = n_blocks
        self._refs = [0] * n_blocks
        # LIFO free list: recently freed blocks are re-used first (their
        # pool pages are the warmest)
        self._free = list(range(n_blocks - 1, 0, -1))

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the garbage block is not)."""
        return self.n_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        """n fresh blocks with refcount 1; raises PoolExhausted when the
        free list cannot supply them (callers requeue and wait)."""
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} KV blocks, {len(self._free)} free of {self.capacity}"
            )
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._refs[b] = 1
        return ids

    def incref(self, ids) -> None:
        for b in ids:
            if self._refs[b] <= 0:
                raise ValueError(f"incref on free block {b}")
            self._refs[b] += 1

    def decref(self, ids) -> list[int]:
        """Drop one reference per id; blocks reaching zero return to the
        free list. Returns the freed ids."""
        freed: list[int] = []
        for b in ids:
            r = self._refs[b]
            if r <= 0:
                raise ValueError(f"decref on free block {b}")
            self._refs[b] = r - 1
            if r == 1:
                self._free.append(b)
                freed.append(b)
        return freed

    def ref(self, block_id: int) -> int:
        return self._refs[block_id]


def init_block_pool(cfg, n_blocks: int, block_size: int, dtype=jnp.bfloat16):
    """The K and V block pools: ``[L, n_blocks, block_size, Hkv, Dh]``."""
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def gather_block_views(pool_k, pool_v, tables):
    """Per-slot contiguous KV views through the block tables.

    pool_k/v: ``[L, NB, bs, Hkv, Dh]``; tables: ``[N, nbl]`` int32 block
    ids. Returns ``[L, N, nbl * bs, Hkv, Dh]`` views — the same shape the
    slot-row engine's cache rows had, so the model and its compiled
    programs are unchanged."""
    l = pool_k.shape[0]
    bs = pool_k.shape[2]
    n, nbl = tables.shape
    vk = pool_k[:, tables].reshape(l, n, nbl * bs, *pool_k.shape[3:])
    vv = pool_v[:, tables].reshape(l, n, nbl * bs, *pool_v.shape[3:])
    return vk, vv


def scatter_block_views(pool_k, pool_v, tables, view_k, view_v):
    """Write updated per-slot views back into the pool blocks.

    Duplicate table entries (shared prefix blocks, garbage padding) write
    identical values by the engine's copy-on-write invariant — see the
    module docstring — so the scatter's undefined duplicate-write order
    cannot change pool contents."""
    l = pool_k.shape[0]
    bs = pool_k.shape[2]
    n, nbl = tables.shape
    bk = view_k.reshape(l, n, nbl, bs, *view_k.shape[3:])
    bv = view_v.reshape(l, n, nbl, bs, *view_v.shape[3:])
    return pool_k.at[:, tables].set(bk), pool_v.at[:, tables].set(bv)


def paged_head_update(mesh, pool_k, pool_v, k, v, tables, write_index, *, layer_index=0):
    """Head-parallel scatter of a chunk's K/V into the pool over the model
    mesh axis: the pools and the chunk shard on their ``Hkv`` dimension
    (each shard writes its own head plane), block tables and positions
    replicate. The positional math is identical to the unsharded layer
    scatter, so an extent-1 model axis is bit-equal to it. Accepts an
    ``AbstractMesh`` so shardcheck's ``vlm-paged-head-scatter`` contract
    traces this call site device-free (analysis/shard_check.py).

    pool_k/v: ``[L, NB, bs, Hkv, Dh]``; k/v: ``[B, T, Hkv, Dh]`` (the
    chunk, rope already applied); tables: ``[B, nbl]``; write_index:
    ``[B]``. Returns the updated pools."""
    import jax.numpy as _jnp
    from jax.sharding import PartitionSpec as P

    from cosmos_curate_tpu.parallel.axes import MODEL
    from cosmos_curate_tpu.parallel.sharding import shard_map

    axis = MODEL if MODEL in mesh.axis_names else None
    pspec = P(None, None, None, axis, None)
    kspec = P(None, None, axis, None)

    def _update(pk, pv, k_, v_, tbl, wi):
        bs = pk.shape[2]
        t = k_.shape[1]
        pos = wi[:, None] + _jnp.arange(t)[None, :]  # [B, T]
        blk = _jnp.take_along_axis(tbl, pos // bs, axis=1)
        off = pos % bs
        npk = pk.at[layer_index, blk, off].set(k_.astype(pk.dtype))
        npv = pv.at[layer_index, blk, off].set(v_.astype(pv.dtype))
        return npk, npv

    return shard_map(
        _update,
        mesh=mesh,
        in_specs=(pspec, pspec, kspec, kspec, P(None, None), P(None)),
        out_specs=(pspec, pspec),
    )(pool_k, pool_v, k, v, tables, write_index)


def paged_gather(mesh, pool_k, pool_v, tables):
    """Data-parallel block-table gather: slot rows (tables) shard over the
    mesh's batch axes while the pool is replicated — the fan-out shape for
    data-parallel engine replicas served from one pool snapshot. Accepts an
    ``AbstractMesh`` too, so shardcheck's ``vlm-paged-gather`` contract
    traces this exact call site device-free (analysis/shard_check.py)."""
    from jax.sharding import PartitionSpec as P

    from cosmos_curate_tpu.parallel.axes import BATCH_AXES
    from cosmos_curate_tpu.parallel.sharding import shard_map

    axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    tspec = P(axes) if axes else P(None)
    vspec = P(None, axes) if axes else P(None, None)
    return shard_map(
        gather_block_views,
        mesh=mesh,
        in_specs=(P(), P(), tspec),
        out_specs=(vspec, vspec),
    )(pool_k, pool_v, tables)
