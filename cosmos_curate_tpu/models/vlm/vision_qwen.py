"""Qwen2-VL vision tower in Flax — 3D-conv patchify, 2D rope, patch merger.

Equivalent capability of the vision encoder the reference serves through
vLLM for its Qwen-VL captioners (cosmos_curate/models/vllm_qwen.py:122-260;
HF `Qwen2VisionTransformerPretrainedModel`): tensor-for-tensor the same
architecture, so `convert_qwen.convert_qwen2_vision` can load a real
Qwen2-VL checkpoint's ``visual.*`` weights and multimodal captions see the
trained tower, not a random-init stand-in.

TPU-first differences from the HF implementation (behavior-preserving):

- **Static shapes.** HF flattens all images of a request into one ragged
  sequence partitioned by ``cu_seqlens``; here a batch is a dense
  ``[B, S, patch_dim]`` array with one static ``(t, h, w)`` grid per
  compiled program (the caption engine buckets by shape anyway), so
  attention is one big batched MXU matmul instead of per-image splits.
- **Patchify as a matmul.** The Conv3d with kernel == stride over
  pre-extracted patches is exactly a dense layer on the flattened patch
  vector — one ``[B*S, patch_dim] @ [patch_dim, embed]`` MXU call.
- The 2D rotary tables and the merge-window patch ordering are computed
  host-side once per grid (static) and closed over by the jitted program.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.models.layers import dense, quick_gelu


@dataclass(frozen=True)
class QwenVisionConfig:
    depth: int = 32
    embed_dim: int = 1280
    num_heads: int = 16
    hidden_size: int = 1536  # LM dim the merger projects into
    mlp_ratio: float = 4.0
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    in_channels: int = 3
    image_size: int = 224  # our fixed inference resolution
    # "qwen2" = LayerNorm blocks + quick_gelu MLP, full per-frame attention;
    # "qwen2_5" = RMSNorm blocks + SwiGLU MLP, windowed attention with
    # full-attention blocks at fullatt_block_indexes (also CosmosReason's
    # vision architecture)
    variant: str = "qwen2"
    intermediate_size: int | None = None  # qwen2_5 sets this explicitly
    window_size: int = 112  # pixels; qwen2_5 only
    fullatt_block_indexes: tuple[int, ...] = ()
    # Qwen2.5-VL scales the temporal m-rope component to absolute time:
    # t_index = floor(grid_t_idx * second_per_grid_t * tokens_per_second)
    # (HF get_rope_index). None = unscaled (Qwen2-VL behavior).
    tokens_per_second: float | None = None
    # "qwen3" (deepstack) only: side length of the learned pos-embed grid
    # (HF num_position_embeddings = side²), bilinearly interpolated to the
    # actual patch grid; and the block indexes whose hidden states feed
    # the deepstack mergers (injected into the LM's first layers).
    pos_embed_side: int = 0
    deepstack_indexes: tuple[int, ...] = ()

    @property
    def mlp_hidden(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        return int(self.embed_dim * self.mlp_ratio)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    def grid(self, n_frames: int) -> tuple[int, int, int]:
        """Static (t, h, w) patch grid for n_frames at image_size."""
        t = -(-n_frames // self.temporal_patch_size)
        hw = self.image_size // self.patch_size
        return t, hw, hw

    def tokens_out(self, n_frames: int) -> int:
        t, h, w = self.grid(n_frames)
        return t * h * w // self.spatial_merge_size**2

    def merged_grid(self, n_frames: int) -> tuple[int, int, int]:
        """Grid of MERGED tokens (what the LM sees; m-rope position space)."""
        t, h, w = self.grid(n_frames)
        m = self.spatial_merge_size
        return t, h // m, w // m


# Qwen2-VL-2B-Instruct's visual config (depth 32 / 1280 / 16 heads,
# merger → 1536). hidden_size must match the LM dim.
QWEN2_VL_2B_VISION = QwenVisionConfig()
# Qwen2.5-VL-7B-Instruct's visual config (windowed attention; also the
# CosmosReason family's tower): depth 32 / 1280 / 16 heads, SwiGLU 3420,
# window 112px, full attention at blocks 7/15/23/31, merger → 3584.
QWEN25_VL_7B_VISION = QwenVisionConfig(
    depth=32,
    embed_dim=1280,
    num_heads=16,
    hidden_size=3584,
    intermediate_size=3420,
    variant="qwen2_5",
    window_size=112,
    fullatt_block_indexes=(7, 15, 23, 31),
    tokens_per_second=2.0,  # HF Qwen2.5-VL vision_config.tokens_per_second
)
# Qwen3-VL(-MoE) deepstack vision tower (SigLIP-shaped: 27 deep / 1152 /
# 16 heads / gelu-tanh MLP 4304), learned 48x48 pos-embed grid, deepstack
# taps at blocks 8/16/24; merger projects into the LM dim per checkpoint.
QWEN3_VL_MOE_VISION = QwenVisionConfig(
    depth=27,
    embed_dim=1152,
    num_heads=16,
    hidden_size=2048,  # 30B-A3B text hidden; conversion derives from config
    intermediate_size=4304,
    patch_size=16,
    variant="qwen3",
    pos_embed_side=48,
    deepstack_indexes=(8, 16, 24),
)
QWEN3_VISION_TINY_TEST = QwenVisionConfig(
    depth=3,
    embed_dim=32,
    num_heads=4,
    hidden_size=64,
    intermediate_size=64,
    patch_size=8,
    image_size=32,
    variant="qwen3",
    pos_embed_side=4,
    deepstack_indexes=(0, 1),
)
QWEN_VISION_TINY_TEST = QwenVisionConfig(
    depth=2,
    embed_dim=64,
    num_heads=4,
    hidden_size=64,
    mlp_ratio=2.0,
    patch_size=8,
    image_size=32,
)


def pos_embed_interp_matrix(cfg: QwenVisionConfig, grid: tuple[int, int, int]) -> np.ndarray:
    """Host-side [h*w, side²] bilinear interpolation matrix mapping the
    learned pos-embed table onto ONE temporal slice of the (t, h, w) patch
    grid in merge-window order (HF ``fast_pos_embed_interpolate``
    semantics: linspace over the side, 4-neighbor weights, merge
    permutation; the caller broadcasts the interpolated product over t —
    tiling the matrix itself would bake a t× larger constant into the
    jitted program)."""
    _t, h, w = grid
    side = cfg.pos_embed_side
    msz = cfg.spatial_merge_size
    h_idx = np.linspace(0, side - 1, h)
    w_idx = np.linspace(0, side - 1, w)
    h0 = h_idx.astype(np.int64)
    w0 = w_idx.astype(np.int64)
    h1 = np.clip(h0 + 1, None, side - 1)
    w1 = np.clip(w0 + 1, None, side - 1)
    dh = (h_idx - h0)[:, None]
    dw = (w_idx - w0)[None, :]
    mat = np.zeros((h * w, side * side), np.float32)
    rows = np.arange(h * w).reshape(h, w)
    for hi, wi, wgt in (
        (h0, w0, (1 - dh) * (1 - dw)),
        (h0, w1, (1 - dh) * dw),
        (h1, w0, dh * (1 - dw)),
        (h1, w1, dh * dw),
    ):
        cols = hi[:, None] * side + wi[None, :]
        # accumulate: clipped edge neighbors can collide on the same cell
        np.add.at(mat, (rows.reshape(-1), cols.reshape(-1)), wgt.reshape(-1))
    perm = (
        np.arange(h * w)
        .reshape(h // msz, msz, w // msz, msz)
        .transpose(0, 2, 1, 3)
        .reshape(-1)
    )
    return mat[perm]  # [h*w, side²] in merge-window order


def rotary_tables(cfg: QwenVisionConfig, grid: tuple[int, int, int]) -> np.ndarray:
    """Host-side [S, head_dim] rope angles in merge-window patch order.

    Matches HF ``rot_pos_emb`` (modeling_qwen2_vl.py): h/w position ids are
    permuted so each spatial_merge_size² window is contiguous, each position
    indexes a 1D table of ``outer(pos, inv_freq(head_dim//2))``, the (h, w)
    halves concatenate to head_dim//2, then the whole thing doubles for
    rotate-half cos/sin.
    """
    t, h, w = grid
    msz = cfg.spatial_merge_size
    hpos = np.arange(h)[:, None].repeat(w, axis=1)
    wpos = np.arange(w)[None, :].repeat(h, axis=0)

    def merge_order(pos):
        return (
            pos.reshape(h // msz, msz, w // msz, msz).transpose(0, 2, 1, 3).reshape(-1)
        )

    hpos, wpos = merge_order(hpos), merge_order(wpos)  # [h*w]
    dim = cfg.head_dim // 2
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    table = np.arange(max(h, w), dtype=np.float64)[:, None] * inv_freq[None, :]
    angles = np.concatenate([table[hpos], table[wpos]], axis=-1)  # [h*w, dim]
    angles = np.tile(angles, (t, 1))  # temporal repeat: same 2D pos every t
    return np.concatenate([angles, angles], axis=-1).astype(np.float32)  # [S, head_dim]


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def window_partition(cfg: QwenVisionConfig, grid: tuple[int, int, int]):
    """Host-side window permutation for the qwen2_5 variant.

    HF ``get_window_index`` semantics for one static grid: merge units
    (spatial_merge_size² consecutive tokens) are regrouped into
    window-major order; returns (token_perm [S], window segment id per
    permuted token [S]) — static arrays the jitted program closes over.
    Frame (t) boundaries are preserved by the permutation, so the per-frame
    full-attention mask formula is unchanged.
    """
    t, h, w = grid
    msz = cfg.spatial_merge_size
    unit = msz * msz
    lh, lw = h // msz, w // msz
    vws = max(1, cfg.window_size // msz // cfg.patch_size)
    index = np.arange(t * lh * lw).reshape(t, lh, lw)
    pad_h = (-lh) % vws
    pad_w = (-lw) % vws
    nh, nw = (lh + pad_h) // vws, (lw + pad_w) // vws
    padded = np.full((t, lh + pad_h, lw + pad_w), -100, dtype=np.int64)
    padded[:, :lh, :lw] = index
    padded = (
        padded.reshape(t, nh, vws, nw, vws)
        .transpose(0, 1, 3, 2, 4)
        .reshape(t, nh * nw, vws, vws)
    )
    seqlens = (padded != -100).sum(axis=(2, 3)).reshape(-1)  # merge units/window
    flat = padded.reshape(-1)
    unit_perm = flat[flat != -100]  # [S/unit] merge-unit permutation
    token_perm = (unit_perm[:, None] * unit + np.arange(unit)).reshape(-1)
    # window segment id per permuted TOKEN (empty windows contribute none)
    seg = np.repeat(np.arange(len(seqlens)), seqlens * unit)
    return token_perm.astype(np.int64), seg.astype(np.int64), unit_perm.astype(np.int64)


class _VisionRMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (scale * normed).astype(x.dtype)


class QwenVisionBlock(nn.Module):
    cfg: QwenVisionConfig
    dtype: jnp.dtype = jnp.bfloat16

    def _norm(self, name: str):
        if self.cfg.variant == "qwen2_5":
            return _VisionRMSNorm(name=name)
        return nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name=name)

    @nn.compact
    def __call__(self, x, cos, sin, block_mask):
        """x: [B, S, E]; cos/sin: [S, head_dim] rope tables; block_mask:
        [S, S] bool — HF splits attention at cu_seqlens boundaries (per
        temporal frame, or per window for qwen2_5's windowed blocks), which
        for our static grid is a block-diagonal mask."""
        cfg = self.cfg
        b, s, _ = x.shape
        h, dh = cfg.num_heads, cfg.head_dim

        y = self._norm("ln1")(x)
        # fused qkv (one MXU matmul), as in the checkpoint layout
        qkv = dense(3 * cfg.embed_dim, "out", name="qkv", use_bias=True, dtype=self.dtype)(y)
        q, k, v = jnp.split(qkv.reshape(b, s, 3, h, dh), 3, axis=2)
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]  # [B, S, H, Dh]
        cos_ = cos[None, :, None, :]
        sin_ = sin[None, :, None, :]
        qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
        q = (qf * cos_ + _rotate_half(qf) * sin_).astype(self.dtype)
        k = (kf * cos_ + _rotate_half(kf) * sin_).astype(self.dtype)

        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32) * dh**-0.5, k.astype(jnp.float32)
        )
        logits = jnp.where(block_mask[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(self.dtype), v)
        attn = attn.reshape(b, s, h * dh)
        x = x + dense(cfg.embed_dim, "in", name="proj", use_bias=True, dtype=self.dtype)(attn)

        y = self._norm("ln2")(x)
        hdim = cfg.mlp_hidden
        if cfg.variant == "qwen2_5":  # SwiGLU (with biases, HF Qwen2_5_VLMLP)
            gate = dense(hdim, "out", name="gate", use_bias=True, dtype=self.dtype)(y)
            up = dense(hdim, "out", name="up", use_bias=True, dtype=self.dtype)(y)
            y = nn.silu(gate) * up
            return x + dense(cfg.embed_dim, "in", name="down", use_bias=True, dtype=self.dtype)(y)
        y = dense(hdim, "out", name="fc1", use_bias=True, dtype=self.dtype)(y)
        if cfg.variant == "qwen3":  # HF hidden_act gelu_pytorch_tanh
            y = nn.gelu(y, approximate=True)
        else:
            y = quick_gelu(y)
        return x + dense(cfg.embed_dim, "in", name="fc2", use_bias=True, dtype=self.dtype)(y)


class QwenVisionTower(nn.Module):
    """[B, S, patch_dim] pixel patches -> [B, S/merge², hidden_size]."""

    cfg: QwenVisionConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, patches, grid: tuple[int, int, int]):
        cfg = self.cfg
        b, s, _ = patches.shape
        assert s == grid[0] * grid[1] * grid[2], (s, grid)
        x = dense(
            cfg.embed_dim,
            None,
            name="patch_embed",
            use_bias=cfg.variant == "qwen3",  # Qwen3's Conv3d carries a bias
            dtype=self.dtype,
        )(patches.astype(self.dtype))
        if cfg.variant == "qwen3":
            # learned pos-embed table, bilinearly interpolated to the grid
            # (host-precomputed static matrix; HF fast_pos_embed_interpolate)
            table = self.param(
                "pos_embed",
                nn.initializers.normal(0.02),
                (cfg.pos_embed_side**2, cfg.embed_dim),
                jnp.float32,
            )
            interp = jnp.asarray(pos_embed_interp_matrix(cfg, grid))
            pos = jnp.tile(interp @ table, (grid[0], 1))  # temporal repeat
            x = (x.astype(jnp.float32) + pos).astype(self.dtype)
        angles = rotary_tables(cfg, grid)
        # per-frame full attention (HF cu_seqlens semantics)
        frame = np.arange(s) // (grid[1] * grid[2])
        full_mask = jnp.asarray(frame[:, None] == frame[None, :])
        windowed_mask = None
        inverse_unit_perm = None
        if cfg.variant == "qwen2_5":
            # static window permutation: tokens regroup window-major; all
            # blocks except fullatt_block_indexes attend within windows
            token_perm, seg, unit_perm = window_partition(cfg, grid)
            x = x[:, token_perm]
            angles = angles[token_perm]
            windowed_mask = jnp.asarray(seg[:, None] == seg[None, :])
            inverse_unit_perm = np.argsort(unit_perm)
        cos, sin = jnp.cos(jnp.asarray(angles)), jnp.sin(jnp.asarray(angles))
        msz2 = cfg.spatial_merge_size**2
        deepstack = []
        for i in range(cfg.depth):
            if cfg.variant == "qwen2_5" and i not in cfg.fullatt_block_indexes:
                mask = windowed_mask
            else:
                mask = full_mask
            x = QwenVisionBlock(cfg, dtype=self.dtype, name=f"block_{i}")(x, cos, sin, mask)
            if cfg.variant == "qwen3" and i in cfg.deepstack_indexes:
                # deepstack merger (postshuffle norm): merge-window group
                # FIRST, LayerNorm over the grouped features, then the MLP
                level = cfg.deepstack_indexes.index(i)
                d = x.reshape(b, s // msz2, msz2 * cfg.embed_dim)
                d = nn.LayerNorm(
                    epsilon=1e-6, dtype=jnp.float32, name=f"ds{level}_norm"
                )(d)
                d = dense(
                    msz2 * cfg.embed_dim, "out", name=f"ds{level}_fc1",
                    use_bias=True, dtype=self.dtype,
                )(d)
                d = nn.gelu(d, approximate=False)
                d = dense(
                    cfg.hidden_size, "in", name=f"ds{level}_fc2",
                    use_bias=True, dtype=self.dtype,
                )(d)
                deepstack.append(d)
        # merger: group each merge-window's msz² consecutive tokens
        if cfg.variant == "qwen2_5":
            x = _VisionRMSNorm(name="ln_q")(x)
        else:
            x = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name="ln_q")(x)
        x = x.reshape(b, s // msz2, msz2 * cfg.embed_dim)
        x = dense(msz2 * cfg.embed_dim, "out", name="merger_fc1", use_bias=True, dtype=self.dtype)(x)
        x = nn.gelu(x, approximate=False)
        x = dense(cfg.hidden_size, "in", name="merger_fc2", use_bias=True, dtype=self.dtype)(x)
        if inverse_unit_perm is not None:
            # undo the window permutation so outputs are t-major row-major
            # (what build_mrope_positions and the engine assume)
            x = x[:, inverse_unit_perm]
        if cfg.variant == "qwen3":
            return x, jnp.stack(deepstack) if deepstack else jnp.zeros((0, *x.shape))
        return x


def frames_to_patches(frames_u8, cfg: QwenVisionConfig):
    """uint8 [B, N, H, W, 3] -> ([B, S, patch_dim], grid), HF processor order.

    Device-side equivalent of Qwen2VLImageProcessor._preprocess: CLIP
    mean/std normalization at image_size, last frame repeated to a multiple
    of temporal_patch_size, then the
    (t, tps, C, h/m, m, ps, w/m, m, ps) → (t, h/m, w/m, m, m, C, tps, ps, ps)
    transpose that puts each merge window's patches contiguous.
    """
    from cosmos_curate_tpu.models.vit import preprocess_frames

    b, n = frames_u8.shape[:2]
    tps, ps, msz = cfg.temporal_patch_size, cfg.patch_size, cfg.spatial_merge_size
    x = preprocess_frames(frames_u8, image_size=cfg.image_size, mode="clip")
    if n % tps:
        pad = tps - n % tps
        x = jnp.concatenate([x, jnp.repeat(x[:, -1:], pad, axis=1)], axis=1)
        n += pad
    t, gh, gw = cfg.grid(n)
    # [B, N, H, W, C] -> channel-first patch blocks
    x = x.transpose(0, 1, 4, 2, 3)  # [B, N, C, H, W]
    x = x.reshape(b, t, tps, cfg.in_channels, gh // msz, msz, ps, gw // msz, msz, ps)
    x = x.transpose(0, 1, 4, 7, 5, 8, 3, 2, 6, 9)
    patches = x.reshape(b, t * gh * gw, cfg.patch_dim)
    return patches, (t, gh, gw)
