"""Qwen2-VL vision tower in Flax — 3D-conv patchify, 2D rope, patch merger.

Equivalent capability of the vision encoder the reference serves through
vLLM for its Qwen-VL captioners (cosmos_curate/models/vllm_qwen.py:122-260;
HF `Qwen2VisionTransformerPretrainedModel`): tensor-for-tensor the same
architecture, so `convert_qwen.convert_qwen2_vision` can load a real
Qwen2-VL checkpoint's ``visual.*`` weights and multimodal captions see the
trained tower, not a random-init stand-in.

TPU-first differences from the HF implementation (behavior-preserving):

- **Static shapes.** HF flattens all images of a request into one ragged
  sequence partitioned by ``cu_seqlens``; here a batch is a dense
  ``[B, S, patch_dim]`` array with one static ``(t, h, w)`` grid per
  compiled program (the caption engine buckets by shape anyway), so
  attention is one big batched MXU matmul instead of per-image splits.
- **Patchify as a matmul.** The Conv3d with kernel == stride over
  pre-extracted patches is exactly a dense layer on the flattened patch
  vector — one ``[B*S, patch_dim] @ [patch_dim, embed]`` MXU call.
- The 2D rotary tables and the merge-window patch ordering are computed
  host-side once per grid (static) and closed over by the jitted program.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.models.layers import dense, quick_gelu


@dataclass(frozen=True)
class QwenVisionConfig:
    depth: int = 32
    embed_dim: int = 1280
    num_heads: int = 16
    hidden_size: int = 1536  # LM dim the merger projects into
    mlp_ratio: float = 4.0
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    in_channels: int = 3
    image_size: int = 224  # our fixed inference resolution

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    def grid(self, n_frames: int) -> tuple[int, int, int]:
        """Static (t, h, w) patch grid for n_frames at image_size."""
        t = -(-n_frames // self.temporal_patch_size)
        hw = self.image_size // self.patch_size
        return t, hw, hw

    def tokens_out(self, n_frames: int) -> int:
        t, h, w = self.grid(n_frames)
        return t * h * w // self.spatial_merge_size**2

    def merged_grid(self, n_frames: int) -> tuple[int, int, int]:
        """Grid of MERGED tokens (what the LM sees; m-rope position space)."""
        t, h, w = self.grid(n_frames)
        m = self.spatial_merge_size
        return t, h // m, w // m


# Qwen2-VL-2B-Instruct's visual config (depth 32 / 1280 / 16 heads,
# merger → 1536). hidden_size must match the LM dim.
QWEN2_VL_2B_VISION = QwenVisionConfig()
QWEN_VISION_TINY_TEST = QwenVisionConfig(
    depth=2,
    embed_dim=64,
    num_heads=4,
    hidden_size=64,
    mlp_ratio=2.0,
    patch_size=8,
    image_size=32,
)


def rotary_tables(cfg: QwenVisionConfig, grid: tuple[int, int, int]) -> np.ndarray:
    """Host-side [S, head_dim] rope angles in merge-window patch order.

    Matches HF ``rot_pos_emb`` (modeling_qwen2_vl.py): h/w position ids are
    permuted so each spatial_merge_size² window is contiguous, each position
    indexes a 1D table of ``outer(pos, inv_freq(head_dim//2))``, the (h, w)
    halves concatenate to head_dim//2, then the whole thing doubles for
    rotate-half cos/sin.
    """
    t, h, w = grid
    msz = cfg.spatial_merge_size
    hpos = np.arange(h)[:, None].repeat(w, axis=1)
    wpos = np.arange(w)[None, :].repeat(h, axis=0)

    def merge_order(pos):
        return (
            pos.reshape(h // msz, msz, w // msz, msz).transpose(0, 2, 1, 3).reshape(-1)
        )

    hpos, wpos = merge_order(hpos), merge_order(wpos)  # [h*w]
    dim = cfg.head_dim // 2
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    table = np.arange(max(h, w), dtype=np.float64)[:, None] * inv_freq[None, :]
    angles = np.concatenate([table[hpos], table[wpos]], axis=-1)  # [h*w, dim]
    angles = np.tile(angles, (t, 1))  # temporal repeat: same 2D pos every t
    return np.concatenate([angles, angles], axis=-1).astype(np.float32)  # [S, head_dim]


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


class QwenVisionBlock(nn.Module):
    cfg: QwenVisionConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, cos, sin, block_mask):
        """x: [B, S, E]; cos/sin: [S, head_dim] rope tables; block_mask:
        [S, S] bool — HF splits attention at cu_seqlens boundaries (each
        temporal frame's h·w patches attend only among themselves), which
        for our static grid is a block-diagonal mask."""
        cfg = self.cfg
        b, s, _ = x.shape
        h, dh = cfg.num_heads, cfg.head_dim

        y = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name="ln1")(x)
        # fused qkv (one MXU matmul), as in the checkpoint layout
        qkv = dense(3 * cfg.embed_dim, "out", name="qkv", use_bias=True, dtype=self.dtype)(y)
        q, k, v = jnp.split(qkv.reshape(b, s, 3, h, dh), 3, axis=2)
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]  # [B, S, H, Dh]
        cos_ = cos[None, :, None, :]
        sin_ = sin[None, :, None, :]
        qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
        q = (qf * cos_ + _rotate_half(qf) * sin_).astype(self.dtype)
        k = (kf * cos_ + _rotate_half(kf) * sin_).astype(self.dtype)

        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32) * dh**-0.5, k.astype(jnp.float32)
        )
        logits = jnp.where(block_mask[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(self.dtype), v)
        attn = attn.reshape(b, s, h * dh)
        x = x + dense(cfg.embed_dim, "in", name="proj", use_bias=True, dtype=self.dtype)(attn)

        y = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name="ln2")(x)
        hdim = int(cfg.embed_dim * cfg.mlp_ratio)
        y = dense(hdim, "out", name="fc1", use_bias=True, dtype=self.dtype)(y)
        y = quick_gelu(y)
        return x + dense(cfg.embed_dim, "in", name="fc2", use_bias=True, dtype=self.dtype)(y)


class QwenVisionTower(nn.Module):
    """[B, S, patch_dim] pixel patches -> [B, S/merge², hidden_size]."""

    cfg: QwenVisionConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, patches, grid: tuple[int, int, int]):
        cfg = self.cfg
        b, s, _ = patches.shape
        assert s == grid[0] * grid[1] * grid[2], (s, grid)
        x = dense(cfg.embed_dim, None, name="patch_embed", use_bias=False, dtype=self.dtype)(
            patches.astype(self.dtype)
        )
        angles = rotary_tables(cfg, grid)
        cos, sin = jnp.cos(jnp.asarray(angles)), jnp.sin(jnp.asarray(angles))
        # attention never crosses temporal frames (HF cu_seqlens semantics)
        frame = np.arange(s) // (grid[1] * grid[2])
        block_mask = jnp.asarray(frame[:, None] == frame[None, :])
        for i in range(cfg.depth):
            x = QwenVisionBlock(cfg, dtype=self.dtype, name=f"block_{i}")(x, cos, sin, block_mask)
        # merger: group each merge-window's msz² consecutive tokens
        msz2 = cfg.spatial_merge_size**2
        x = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name="ln_q")(x)
        x = x.reshape(b, s // msz2, msz2 * cfg.embed_dim)
        x = dense(msz2 * cfg.embed_dim, "out", name="merger_fc1", use_bias=True, dtype=self.dtype)(x)
        x = nn.gelu(x, approximate=False)
        return dense(cfg.hidden_size, "in", name="merger_fc2", use_bias=True, dtype=self.dtype)(x)


def frames_to_patches(frames_u8, cfg: QwenVisionConfig):
    """uint8 [B, N, H, W, 3] -> ([B, S, patch_dim], grid), HF processor order.

    Device-side equivalent of Qwen2VLImageProcessor._preprocess: CLIP
    mean/std normalization at image_size, last frame repeated to a multiple
    of temporal_patch_size, then the
    (t, tps, C, h/m, m, ps, w/m, m, ps) → (t, h/m, w/m, m, m, C, tps, ps, ps)
    transpose that puts each merge window's patches contiguous.
    """
    from cosmos_curate_tpu.models.vit import preprocess_frames

    b, n = frames_u8.shape[:2]
    tps, ps, msz = cfg.temporal_patch_size, cfg.patch_size, cfg.spatial_merge_size
    x = preprocess_frames(frames_u8, image_size=cfg.image_size, mode="clip")
    if n % tps:
        pad = tps - n % tps
        x = jnp.concatenate([x, jnp.repeat(x[:, -1:], pad, axis=1)], axis=1)
        n += pad
    t, gh, gw = cfg.grid(n)
    # [B, N, H, W, C] -> channel-first patch blocks
    x = x.transpose(0, 1, 4, 2, 3)  # [B, N, C, H, W]
    x = x.reshape(b, t, tps, cfg.in_channels, gh // msz, msz, ps, gw // msz, msz, ps)
    x = x.transpose(0, 1, 4, 7, 5, 8, 3, 2, 6, 9)
    patches = x.reshape(b, t * gh * gw, cfg.patch_dim)
    return patches, (t, gh, gw)
