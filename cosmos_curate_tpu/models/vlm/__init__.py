from cosmos_curate_tpu.models.vlm.model import VLM, VLMConfig, VLM_BASE, VLM_TINY_TEST
from cosmos_curate_tpu.models.vlm.engine import CaptionEngine, CaptionRequest, SamplingConfig

__all__ = [
    "VLM",
    "VLMConfig",
    "VLM_BASE",
    "VLM_TINY_TEST",
    "CaptionEngine",
    "CaptionRequest",
    "SamplingConfig",
]
