from cosmos_curate_tpu.models.vlm.model import VLM, VLMConfig, VLM_BASE, VLM_TINY_TEST
from cosmos_curate_tpu.models.vlm.engine import CaptionEngine, CaptionRequest, SamplingConfig
from cosmos_curate_tpu.models.vlm.paged_kv import BlockAllocator, PoolExhausted
from cosmos_curate_tpu.models.vlm.shared_engine import SharedCaptionEngine

__all__ = [
    "VLM",
    "VLMConfig",
    "VLM_BASE",
    "VLM_TINY_TEST",
    "BlockAllocator",
    "CaptionEngine",
    "CaptionRequest",
    "PoolExhausted",
    "SamplingConfig",
    "SharedCaptionEngine",
]
