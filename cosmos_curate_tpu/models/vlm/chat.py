"""Qwen chat-template prompt construction for real checkpoints.

Equivalent capability of the reference's vLLM chat handling for its Qwen
captioners (cosmos_curate/models/vllm_qwen.py builds
``<|im_start|>...<|im_end|>`` turns with ``<|vision_start|>`` image
placeholders via the HF processor): produces the engine's
``(prefix_ids, prompt_ids)`` pair so vision embeddings splice exactly
where the template puts the image — matching what the checkpoint saw in
training. Use with :class:`~cosmos_curate_tpu.models.tokenizer.
HFVocabTokenizer` (exact HF ids) and a converted Qwen2/2.5-VL checkpoint.
"""

from __future__ import annotations

from cosmos_curate_tpu.models.tokenizer import QWEN2_SPECIAL_TOKENS

DEFAULT_SYSTEM = "You are a helpful assistant."


def build_qwen_vl_chat(
    tokenizer,
    user_text: str,
    *,
    system: str = DEFAULT_SYSTEM,
    has_vision: bool = True,
    specials: dict[str, int] | None = None,
) -> tuple[list[int], list[int]]:
    """Token ids for one captioning turn in Qwen2(-VL)'s chat template.

    Returns ``(prefix_ids, prompt_ids)`` for ``CaptionRequest``: the vision
    embeddings splice between them, standing in for the template's
    ``<|image_pad|>`` run (the engine inserts real embeddings instead of
    placeholder tokens, so no pad-token count is needed)::

        <|im_start|>system\\n{system}<|im_end|>\\n
        <|im_start|>user\\n<|vision_start|>[VISION]<|vision_end|>{text}<|im_end|>\\n
        <|im_start|>assistant\\n

    Generation naturally stops at ``<|im_end|>`` — make it the engine
    tokenizer's ``eos_id`` (HFVocabTokenizer's default).
    """
    sp = specials or QWEN2_SPECIAL_TOKENS
    im_start, im_end = sp["<|im_start|>"], sp["<|im_end|>"]
    nl = tokenizer.encode("\n")
    prefix = (
        [im_start]
        + tokenizer.encode("system\n" + system)
        + [im_end]
        + nl
        + [im_start]
        + tokenizer.encode("user\n")
    )
    if has_vision:
        prefix = prefix + [sp["<|vision_start|>"]]
        suffix = [sp["<|vision_end|>"]]
    else:
        suffix = []
    suffix = (
        suffix
        + tokenizer.encode(user_text)
        + [im_end]
        + nl
        + [im_start]
        + tokenizer.encode("assistant\n")
    )
    return prefix, suffix
