"""CurateVLM: the vision-language captioning model.

Equivalent capability of the reference's vLLM-served VLM captioners
(cosmos_curate/models/vllm_qwen.py, vllm_interface.py — Qwen-VL-class
models behind the plugin ABC). This is our own Flax architecture, TPU-first:

- vision tower = the shared ViT backbone (models/vit.py), whose patch
  tokens are projected into the LM embedding space (one image/frame-group →
  ``vision_tokens`` embeddings);
- language model = decoder-only transformer with RoPE and grouped-query
  attention, TP-sharded via the Megatron-style annotations in
  models/layers.py (replaces vLLM's NCCL TP with pjit sharding);
- inference is cache-centric: ``apply`` consumes and returns a static-shape
  slot-based KV cache ``[L, B, S, Hkv, Dh]``, so prefill (T=bucket) and
  decode (T=1) are the same compiled family of programs. No dynamic shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.models.layers import MODEL_AXIS, dense
from cosmos_curate_tpu.models.vit import VIT_B_16, VIT_TINY_TEST, ViT, ViTConfig, preprocess_frames
from cosmos_curate_tpu.models.vlm.vision_qwen import (
    QWEN2_VL_2B_VISION,
    QWEN25_VL_7B_VISION,
    QWEN3_VL_MOE_VISION,
    QWEN3_VISION_TINY_TEST,
    QWEN_VISION_TINY_TEST,
    QwenVisionConfig,
    QwenVisionTower,
    frames_to_patches,
)


@dataclass(frozen=True)
class MoEConfig:
    """Sparse mixture-of-experts FFN (the Qwen3-VL-MoE captioner class,
    reference models/vllm_qwen.py:313-349 serves Qwen3-VL-30B/235B via
    vLLM expert parallelism). Router semantics match HF Qwen3MoE: softmax
    over ALL experts in fp32, THEN top-k, renormalized."""

    n_experts: int = 8
    top_k: int = 2
    hidden: int = 512  # per-expert intermediate (HF moe_intermediate_size)
    # expert-queue capacity = ceil(top_k * tokens / n_experts * factor);
    # None = no-drop (capacity = token count) — exact HF equivalence, used
    # by tests and small decode batches
    capacity_factor: float | None = None


@dataclass(frozen=True)
class VLMConfig:
    vocab: int = 512
    dim: int = 1024
    n_layers: int = 12
    n_heads: int = 16
    n_kv_heads: int = 8
    head_dim: int = 64
    hidden_mult: float = 4.0
    max_seq: int = 1024
    rope_theta: float = 10000.0
    # Qwen2-family checkpoints put biases on q/k/v (not o); ours default off.
    qkv_bias: bool = False
    vision: ViTConfig = VIT_B_16
    vision_tokens: int = 64  # LM embeddings per image after pooling
    # "vit" = our shared ViT backbone + projector; "qwen2" = the Qwen2-VL
    # vision tower (vision_qwen.py), whose merger IS the projector
    vision_variant: str = "vit"
    qwen_vision: QwenVisionConfig | None = None
    # Qwen2-VL multimodal rope: freq dims split into (t, h, w) sections
    # (HF `rope_scaling.mrope_section`); None = standard 1D rope
    mrope_section: tuple[int, int, int] | None = None
    rms_eps: float = 1e-6
    # tied = logits via embed.attend (Qwen2-VL-2B); untied checkpoints
    # (Qwen2.5-VL-7B) carry a separate lm_head matrix
    tied_embeddings: bool = True
    # Qwen3 family: per-head-dim RMSNorm on q/k before rope
    qk_norm: bool = False
    # sparse MoE FFN replaces the dense SwiGLU on every layer when set
    moe: MoEConfig | None = None
    # Qwen3-VL interleaves the (t, h, w) m-rope components across frequency
    # dims ([THW THW ... TT], preserving frequency continuity) instead of
    # Qwen2-VL's chunked [TTT HHH WWW] sections
    mrope_interleaved: bool = False


VLM_BASE = VLMConfig()
# Qwen2-VL-2B-class shapes (reference serves Qwen2/2.5-VL via vLLM,
# cosmos_curate/models/vllm_qwen.py:122-260): both halves match
# Qwen2-VL-2B-Instruct tensor-for-tensor — the LM stack (GQA 12/2 heads,
# SwiGLU 8960, tied embeddings, rope 1e6, m-rope 16/24/24) via
# convert_qwen.convert_qwen2_lm, and the vision tower (32-deep 1280-wide
# windowless ViT with 3D-conv patchify, 2D rope, patch merger) via
# convert_qwen.convert_qwen2_vision — so a real checkpoint loads completely.
VLM_QWEN2_2B = VLMConfig(
    vocab=151936,
    dim=1536,
    n_layers=28,
    n_heads=12,
    n_kv_heads=2,
    head_dim=128,
    hidden_mult=8960 / 1536,
    max_seq=4096,
    rope_theta=1_000_000.0,
    qkv_bias=True,
    vision=VIT_B_16,
    vision_tokens=64,
    vision_variant="qwen2",
    qwen_vision=QWEN2_VL_2B_VISION,
    mrope_section=(16, 24, 24),
)
# Qwen2.5-VL-7B-Instruct — the family the reference actually serves for
# captions (vllm_qwen.py; CosmosReason shares this architecture): GQA
# 28/4 heads, SwiGLU 18944, untied head, m-rope 16/24/24, windowed vision.
VLM_QWEN25_7B = VLMConfig(
    vocab=152064,
    dim=3584,
    n_layers=28,
    n_heads=28,
    n_kv_heads=4,
    head_dim=128,
    hidden_mult=18944 / 3584,
    max_seq=4096,
    rope_theta=1_000_000.0,
    qkv_bias=True,
    vision=VIT_B_16,
    vision_tokens=64,
    vision_variant="qwen2",
    qwen_vision=QWEN25_VL_7B_VISION,
    mrope_section=(16, 24, 24),
    tied_embeddings=False,
)
VLM_TINY_TEST = VLMConfig(
    vocab=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    max_seq=128,
    vision=VIT_TINY_TEST,
    vision_tokens=8,
)
# Qwen3-VL-30B-A3B-class sparse captioner LM (reference roster:
# models/vllm_qwen.py:313-349 serves the Qwen3-VL MoE family via vLLM
# expert parallelism). Nominal checkpoint shapes; at conversion time
# `convert_qwen.qwen3_moe_lm_config(hf_config)` derived from the actual
# checkpoint is authoritative. The Qwen3-VL DEEPSTACK vision tower is not
# implemented yet — this flavor serves the text/chat-LM paths (caption
# enhancement) and the EP-sharded serving plumbing; see PARITY.md.
VLM_QWEN3_MOE_A3B = VLMConfig(
    vocab=151936,
    dim=2048,
    n_layers=48,
    n_heads=32,
    n_kv_heads=4,
    head_dim=128,
    hidden_mult=6144 / 2048,
    max_seq=4096,
    rope_theta=1_000_000.0,
    qkv_bias=False,
    qk_norm=True,
    vision=VIT_TINY_TEST,
    vision_tokens=8,
    mrope_section=(24, 20, 20),
    mrope_interleaved=True,
    tied_embeddings=False,
    moe=MoEConfig(n_experts=128, top_k=8, hidden=768, capacity_factor=2.0),
)
# Full Qwen3-VL-MoE: the deepstack vision tower + sparse LM (reference's
# newest captioner roster, vllm_qwen.py:313-349). Nominal 30B-A3B shapes;
# conversion derives exact configs from the checkpoint
# (qwen3_moe_lm_config + qwen3_vision_config).
VLM_QWEN3_VL_MOE_A3B = VLMConfig(
    vocab=151936,
    dim=2048,
    n_layers=48,
    n_heads=32,
    n_kv_heads=4,
    head_dim=128,
    hidden_mult=6144 / 2048,
    max_seq=4096,
    rope_theta=1_000_000.0,
    qkv_bias=False,
    qk_norm=True,
    vision=VIT_TINY_TEST,
    vision_variant="qwen3",
    qwen_vision=QWEN3_VL_MOE_VISION,
    mrope_section=(24, 20, 20),
    mrope_interleaved=True,
    tied_embeddings=False,
    moe=MoEConfig(n_experts=128, top_k=8, hidden=768, capacity_factor=2.0),
)
VLM_QWEN3VL_TINY_TEST = VLMConfig(
    vocab=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    max_seq=128,
    vision=VIT_TINY_TEST,
    vision_variant="qwen3",
    qwen_vision=QWEN3_VISION_TINY_TEST,
    mrope_section=(2, 3, 3),
    mrope_interleaved=True,
    qk_norm=True,
    moe=MoEConfig(n_experts=4, top_k=2, hidden=32),
)
VLM_MOE_TINY_TEST = VLMConfig(
    vocab=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    max_seq=128,
    vision=VIT_TINY_TEST,
    vision_tokens=8,
    qk_norm=True,
    moe=MoEConfig(n_experts=4, top_k=2, hidden=32),
)
# Named caption-model flavors selectable from pipeline args (CLI
# --caption-model); each pairs an architecture with its weight-registry id
# plus the serving knobs that must travel with the checkpoint choice.
@dataclass(frozen=True)
class FlavorSpec:
    cfg: "VLMConfig"
    model_id: str
    # Converted-HF-checkpoint flavors index embeddings by the checkpoint's
    # EXACT token ids and were trained on its chat template: serving them
    # requires HFVocabTokenizer (staged vocab.json/merges.txt) + the
    # Qwen chat layout (vlm/chat.py). Repo-native flavors use the local
    # byte/BPE tokenizer and raw prompts.
    hf_chat: bool = False
    # Flavors naming a real checkpoint must refuse to run random-init
    # (a user asking for qwen25vl-7b must not silently get gibberish).
    require_weights: bool = True
    # hf_chat special-token table override (None = Qwen2 defaults); tuple
    # of (token, id) pairs so the spec stays hashable.
    specials: tuple[tuple[str, int], ...] | None = None
    # The flavor serves TEXT ONLY (no trained vision tower): frame-bearing
    # requests must be refused loudly, never encoded through a placeholder
    # tower into silent gibberish.
    text_only: bool = False
    # Default KV lane layout ((length, n_slots), ...) for the caption
    # engine — memory-bounding by actual request lengths (None = one
    # worst-case-length pool). Chosen per checkpoint size so the
    # production caption stage runs laned by default.
    kv_lanes: tuple[tuple[int, int], ...] | None = None


VLM_FLAVORS: dict[str, FlavorSpec] = {}


def vlm_flavor(name: str) -> FlavorSpec:
    """The full serving spec for a named caption flavor."""
    try:
        return VLM_FLAVORS[name]
    except KeyError:
        raise ValueError(
            f"unknown caption model {name!r}; choose from {sorted(VLM_FLAVORS)}"
        ) from None


VLM_QWEN2VL_TINY_TEST = VLMConfig(
    vocab=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    max_seq=128,
    vision=VIT_TINY_TEST,
    vision_variant="qwen2",
    qwen_vision=QWEN_VISION_TINY_TEST,
    mrope_section=(2, 3, 3),
)
# chat-template prompts in byte-level test tokens run ~170 ids — the
# hf_chat test flavor needs the extra context
VLM_QWEN_CHAT_TINY_TEST = VLMConfig(
    vocab=512,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    max_seq=256,
    vision=VIT_TINY_TEST,
    vision_variant="qwen2",
    qwen_vision=QWEN_VISION_TINY_TEST,
    mrope_section=(2, 3, 3),
)

# Special-token ids small enough for the tiny test config's 512-row
# embedding table; layout mirrors QWEN2_SPECIAL_TOKENS.
_TINY_CHAT_SPECIALS = (
    ("<|endoftext|>", 500),
    ("<|im_start|>", 501),
    ("<|im_end|>", 502),
    ("<|vision_start|>", 503),
    ("<|vision_end|>", 504),
    ("<|vision_pad|>", 505),
    ("<|image_pad|>", 506),
    ("<|video_pad|>", 507),
)

VLM_FLAVORS.update(
    {
        "base": FlavorSpec(VLM_BASE, "caption-vlm-tpu", require_weights=False),
        "qwen2vl-2b": FlavorSpec(
            VLM_QWEN2_2B,
            "caption-qwen2vl-2b-tpu",
            hf_chat=True,
            # 2B-class KV is cheap (2 kv-heads): plenty of short-lane slots
            # for caption windows, a few full-context rows for long prompts
            kv_lanes=((1024, 8), (4096, 4)),
        ),
        "qwen25vl-7b": FlavorSpec(
            VLM_QWEN25_7B,
            "caption-qwen25vl-7b-tpu",
            hf_chat=True,
            # 7B KV rows are 4x the 2B's — halve the lane budget
            kv_lanes=((1024, 4), (4096, 2)),
        ),
        "tiny-test": FlavorSpec(VLM_TINY_TEST, "caption-vlm-tpu", require_weights=False),
        # MoE chat-LM slot for LM-ONLY converted checkpoints (enhancement
        # and other text paths); the full-VL flavor below serves frames
        "qwen3moe-a3b-lm": FlavorSpec(
            VLM_QWEN3_MOE_A3B,
            "caption-qwen3moe-a3b-tpu",
            hf_chat=True,
            text_only=True,  # this slot's checkpoints carry no vision params
            kv_lanes=((1024, 4), (4096, 2)),
        ),
        # full Qwen3-VL-MoE: deepstack vision + EP-sharded sparse LM
        "qwen3vl-moe-a3b": FlavorSpec(
            VLM_QWEN3_VL_MOE_A3B,
            "caption-qwen3vl-moe-a3b-tpu",
            hf_chat=True,
            kv_lanes=((1024, 4), (4096, 2)),
        ),
        "qwen3moe-tiny-test": FlavorSpec(
            VLM_MOE_TINY_TEST, "caption-vlm-tpu", require_weights=False
        ),
        # hf_chat plumbing under test shapes: exercises HFVocabTokenizer +
        # chat-template request building without a real checkpoint
        "qwen-chat-tiny-test": FlavorSpec(
            VLM_QWEN_CHAT_TINY_TEST,
            "caption-vlm-tpu",
            hf_chat=True,
            require_weights=False,
            specials=_TINY_CHAT_SPECIALS,
            kv_lanes=((192, 4), (256, 2)),
        ),
    }
)


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))


def mrope_component_map(
    mrope_section: tuple[int, int, int], interleaved: bool
) -> np.ndarray:
    """Which (t=0, h=1, w=2) position component drives each of the D/2
    rotary frequency dims.

    Chunked (Qwen2-VL): [T]*s0 + [H]*s1 + [W]*s2. Interleaved (Qwen3-VL,
    HF ``apply_interleaved_mrope``): start all-T, then dims 1,4,7,..
    (< 3*s1) become H and dims 2,5,8,.. (< 3*s2) become W."""
    if not interleaved:
        return np.repeat(np.arange(3), np.asarray(mrope_section))
    d2 = int(sum(mrope_section))
    comp = np.zeros(d2, np.int64)
    comp[1 : 3 * mrope_section[1] : 3] = 1
    comp[2 : 3 * mrope_section[2] : 3] = 2
    return comp


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    theta: float,
    mrope_section: tuple[int, int, int] | None = None,
    mrope_interleaved: bool = False,
) -> jnp.ndarray:
    """x: [B, T, H, D]; positions: [B, T] absolute positions, or [B, T, 3]
    (t, h, w) multimodal positions under m-rope.

    M-rope (HF apply_multimodal_rotary_pos_emb semantics): each of the D/2
    rotary frequency dims takes its angle from one position component,
    assigned by ``mrope_component_map`` (chunked sections for Qwen2-VL,
    interleaved for Qwen3-VL). With all three components equal (any
    pure-text span) both layouts reduce exactly to standard 1D rope.
    """
    freqs = rope_frequencies(x.shape[-1], theta)  # [D/2]
    if positions.ndim == 3:
        if mrope_section is None:
            raise ValueError("3-component positions require mrope_section")
        comp = mrope_component_map(mrope_section, mrope_interleaved)
        pos_sel = positions[..., comp].astype(jnp.float32)  # [B, T, D/2]
        angles = pos_sel * freqs
    else:
        angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def build_mrope_positions(
    n_text_before: int,
    grid_merged: tuple[int, int, int] | None,
    n_text_after: int,
    t_scale: float = 1.0,
) -> tuple[np.ndarray, int]:
    """(t, h, w) position ids for a [text][vision][text] prompt layout.

    HF ``Qwen2VLModel.get_rope_index`` semantics: text tokens carry equal
    components; a vision block starting at offset ``st`` gets
    ``st + (t_idx, h_idx, w_idx)`` over the MERGED token grid in t-major
    row-major order (exactly the merger's output order); text resumes at
    ``st + max(vision indices) + 1``. Returns ([T, 3] int32, next_position).

    ``t_scale`` is Qwen2.5-VL's absolute-time temporal component
    (HF ``Qwen2_5_VLModel.get_rope_index``):
    ``t_index = floor(grid_t_idx * second_per_grid_t * tokens_per_second)``
    with ``t_scale = second_per_grid_t * tokens_per_second``. The default
    1.0 reproduces Qwen2-VL's unscaled ``arange`` exactly.
    """
    parts = []
    if n_text_before:
        t = np.arange(n_text_before, dtype=np.int32)
        parts.append(np.stack([t, t, t], axis=-1))
    offset = n_text_before
    if grid_merged is not None:
        gt, gh, gw = grid_merged
        t_idx = np.floor(
            np.repeat(np.arange(gt, dtype=np.float64), gh * gw) * t_scale
        ).astype(np.int32)
        h_idx = np.tile(np.repeat(np.arange(gh, dtype=np.int32), gw), gt)
        w_idx = np.tile(np.tile(np.arange(gw, dtype=np.int32), gh), gt)
        parts.append(offset + np.stack([t_idx, h_idx, w_idx], axis=-1))
        offset += max(int(t_idx[-1]) + 1 if gt else 0, gh, gw)
    if n_text_after:
        t = offset + np.arange(n_text_after, dtype=np.int32)
        parts.append(np.stack([t, t, t], axis=-1))
        offset += n_text_after
    if not parts:
        return np.zeros((0, 3), np.int32), offset
    return np.concatenate(parts, axis=0).astype(np.int32), offset


def _flash_gate(env_var: str, cache_len: int, min_len: int) -> bool:
    """Shared Pallas-kernel gate: the env var forces 1/0 (tests use 1 with
    the interpreter off-TPU); otherwise on-TPU above the length where
    streaming beats XLA's materialized path."""
    import os

    env = os.environ.get(env_var)
    if env is not None:
        return env == "1"
    return jax.devices()[0].platform == "tpu" and cache_len >= min_len


def _use_flash_decode(cache_len: int) -> bool:
    return _flash_gate("CURATE_FLASH_DECODE", cache_len, 512)


def _use_flash_prefill(cache_len: int) -> bool:
    # the XLA prefill materializes fp32 [B, Hkv, G, T, S] logits — the HBM
    # hot spot of long-prompt prefill (ops/prefill_attention.py)
    return _flash_gate("CURATE_FLASH_PREFILL", cache_len, 1024)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


class MoEFFN(nn.Module):
    """Expert-parallel sparse FFN, GShard-style static dispatch.

    TPU-first formulation: routing becomes one-hot einsum dispatch into a
    fixed per-expert queue of ``capacity`` slots, the expert SwiGLU runs
    as ONE batched [E, C, D] x [E, D, 2H] einsum (expert axis sharded over
    the ``model`` mesh axis = expert parallelism under pjit — each device
    holds E/ep experts and XLA all-to-alls the queues), and the combine is
    the transpose einsum weighted by the router. No dynamic shapes, no
    per-expert Python loops; compiled once per (tokens, capacity) bucket.

    Numerics match HF Qwen3MoE (softmax-then-topk in fp32, renormalized;
    fused gate_up chunked into gate|up; silu(gate)*up) exactly when no
    token overflows its expert queue (``capacity_factor=None`` guarantees
    this; a finite factor trades exactness at overflow for memory, the
    standard GShard drop semantics)."""

    cfg: VLMConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        moe = self.cfg.moe
        b, t, d = x.shape
        n = b * t
        e, k, h = moe.n_experts, moe.top_k, moe.hidden
        tokens = x.reshape(n, d)
        logits = dense(e, None, name="router", use_bias=False, dtype=jnp.float32)(
            tokens.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)
        top_w, top_i = jax.lax.top_k(probs, k)  # [N, k]
        top_w = top_w / top_w.sum(axis=-1, keepdims=True)
        if moe.capacity_factor is None:
            cap = n
        else:
            cap = max(1, min(n, int(np.ceil(k * n / e * moe.capacity_factor))))
        # assignment axis A = N*k, token-major; queue position = number of
        # earlier assignments to the same expert. Dispatch/combine are
        # scatter/gather over queue-slot ids — O(A·D) data movement —
        # instead of one-hot einsums whose [A, E, cap] contraction costs
        # as much FLOPs as the expert matmuls themselves.
        a_ids = top_i.reshape(-1)  # [A] expert id per assignment
        e_onehot32 = jax.nn.one_hot(a_ids, e, dtype=jnp.float32)  # [A, E]
        prior = jnp.cumsum(e_onehot32, axis=0) - e_onehot32
        pos = jnp.sum(prior * e_onehot32, axis=-1).astype(jnp.int32)  # [A]
        a = a_ids.shape[0]
        # destination queue slot per assignment; overflow (pos >= cap)
        # lands out of range and is DROPPED by the scatter
        dest = jnp.where(pos < cap, a_ids * cap + pos, e * cap)
        gather = jnp.full((e * cap,), a, jnp.int32)  # sentinel -> zero fill
        gather = gather.at[dest].set(jnp.arange(a, dtype=jnp.int32), mode="drop")
        x_a = jnp.repeat(tokens, k, axis=0).astype(self.dtype)  # [A, D]
        # OOB sentinel reads fill with zeros — no padded-copy of x_a needed
        expert_in = jnp.take(x_a, gather, axis=0, mode="fill", fill_value=0).reshape(
            e, cap, d
        )
        gate_up = self.param(
            "gate_up",
            nn.with_partitioning(
                nn.initializers.normal(0.02), (MODEL_AXIS, None, None)
            ),
            (e, d, 2 * h),
            jnp.float32,
        )
        down = self.param(
            "down",
            nn.with_partitioning(
                nn.initializers.normal(0.02), (MODEL_AXIS, None, None)
            ),
            (e, h, d),
            jnp.float32,
        )
        z = jnp.einsum("ecd,edh->ech", expert_in, gate_up.astype(self.dtype))
        gate, up = jnp.split(z, 2, axis=-1)
        out = jnp.einsum(
            "ech,ehd->ecd", nn.silu(gate) * up, down.astype(self.dtype)
        )  # [E, C, D]
        # combine: each assignment reads back its queue slot (overflow
        # dest is already out of range -> zero fill), weighted by the
        # renormalized router prob
        out_a = jnp.take(
            out.reshape(e * cap, d), dest, axis=0, mode="fill", fill_value=0
        ).astype(jnp.float32)
        y = (out_a * top_w.reshape(-1)[:, None]).reshape(n, k, d).sum(axis=1)
        return y.reshape(b, t, d).astype(x.dtype)


class DecoderLayer(nn.Module):
    cfg: VLMConfig
    dtype: jnp.dtype = jnp.bfloat16
    # optional device mesh: when set and it names the model axis, the paged
    # path runs head-parallel (shard_map over Hkv) — see paged_head_attention
    mesh: object = None

    @nn.compact
    def __call__(
        self, x, cache_k, cache_v, positions, write_index, kv_len,
        block_tables=None, layer_index=0,
    ):
        """One decoder layer with slot KV cache.

        x: [B, T, D]; cache_k/v: [B, S, Hkv, Dh]; positions: [B, T] rope
        positions (or [B, T, 3] m-rope components — under m-rope, rope
        position ≠ cache index, so causality derives from write_index, not
        positions); write_index: [B] offset where this chunk's K/V land;
        kv_len: [B] valid cache length AFTER writing (= write_index + T for
        active rows). Returns (y, new_cache_k, new_cache_v).

        Paged mode (``block_tables`` set): cache_k/v are the FULL block
        pools ``[L, NB, bs, Hkv, Dh]`` and block_tables is ``[B, nbl]``.
        K/V scatter through the table and attention reads the pool in place
        (ops/paged_attention.py) — no contiguous working-set view exists.
        Returns the updated pools in place of cache rows.
        """
        cfg = self.cfg
        b, t, _ = x.shape
        s = cache_k.shape[1] if block_tables is None else None
        h, hk, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

        y = RMSNorm(eps=cfg.rms_eps, name="ln1")(x)
        q = dense(h * dh, "out", name="q", use_bias=cfg.qkv_bias, dtype=self.dtype)(y)
        k = dense(hk * dh, "out", name="k", use_bias=cfg.qkv_bias, dtype=self.dtype)(y)
        v = dense(hk * dh, "out", name="v", use_bias=cfg.qkv_bias, dtype=self.dtype)(y)
        q = q.reshape(b, t, h, dh)
        k = k.reshape(b, t, hk, dh)
        if cfg.qk_norm:  # Qwen3 family: per-HEAD-DIM RMSNorm before rope
            q = RMSNorm(eps=cfg.rms_eps, name="q_norm")(q)
            k = RMSNorm(eps=cfg.rms_eps, name="k_norm")(k)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.mrope_section, cfg.mrope_interleaved)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.mrope_section, cfg.mrope_interleaved)
        v = v.reshape(b, t, hk, dh)

        group = h // hk
        if block_tables is not None:
            # paged path: scatter this chunk's K/V through the block table
            # (the same full-window write the gather path's scatter-back
            # performs — positions past t_valid land in-table and carry
            # identical garbage both ways), then attend straight out of the
            # pool. No gathered view, no scatter-back.
            from cosmos_curate_tpu.models.vlm.paged_kv import paged_head_update
            from cosmos_curate_tpu.ops.paged_attention import (
                paged_attention,
                paged_head_attention,
            )
            from cosmos_curate_tpu.parallel.axes import MODEL

            head_parallel = self.mesh is not None and MODEL in self.mesh.axis_names
            if head_parallel:
                new_k, new_v = paged_head_update(
                    self.mesh, cache_k, cache_v, k, v, block_tables, write_index,
                    layer_index=layer_index,
                )
            else:
                bs_blk = cache_k.shape[2]
                pos = write_index[:, None] + jnp.arange(t)[None, :]  # [B, T]
                blk = jnp.take_along_axis(block_tables, pos // bs_blk, axis=1)
                off = pos % bs_blk
                new_k = cache_k.at[layer_index, blk, off].set(k.astype(cache_k.dtype))
                new_v = cache_v.at[layer_index, blk, off].set(v.astype(cache_v.dtype))
            qk = q.reshape(b, t, hk, group, dh)
            if head_parallel:
                attn = paged_head_attention(
                    self.mesh, qk, new_k, new_v, block_tables, write_index, kv_len,
                    layer_index=layer_index,
                )
            else:
                attn = paged_attention(
                    qk, new_k, new_v, block_tables, write_index, kv_len,
                    layer_index=layer_index,
                )
            attn = attn.astype(self.dtype)
        else:
            # scatter this chunk into the cache at each row's write_index
            def write_row(cache, chunk, idx):
                return jax.lax.dynamic_update_slice(cache, chunk, (idx, 0, 0))

            new_k = jax.vmap(write_row)(cache_k, k.astype(cache_k.dtype), write_index)
            new_v = jax.vmap(write_row)(cache_v, v.astype(cache_v.dtype), write_index)

            # GQA attention of q against the whole (masked) cache. Heads stay
            # grouped ([B, T, Hkv, G, Dh] vs the KV's [B, S, Hkv, Dh]) — no
            # jnp.repeat materialization, so HBM traffic is the true KV size
            # (the decode step is KV-bandwidth-bound; for 12/2 GQA a repeat
            # would read 6x the bytes).
            if t == 1 and _use_flash_decode(s):
                from cosmos_curate_tpu.ops.decode_attention import decode_attention

                out = decode_attention(
                    q[:, 0].reshape(b, hk, group, dh), new_k, new_v, kv_len
                )
                attn = out.astype(self.dtype)[:, None]  # [B, 1, Hkv, G, Dh]
            elif t > 1 and _use_flash_prefill(s):
                from cosmos_curate_tpu.ops.prefill_attention import prefill_attention

                attn = prefill_attention(
                    q.reshape(b, t, hk, group, dh), new_k, new_v, write_index, kv_len
                ).astype(self.dtype)
            else:
                qg = (q * (dh**-0.5)).reshape(b, t, hk, group, dh)
                logits = jnp.einsum(
                    "btkgd,bskd->bkgts", qg.astype(jnp.float32), new_k.astype(jnp.float32)
                )
                k_pos = jnp.arange(s)[None, None, None, None, :]  # cache slot index
                # causality is over cache order (write_index + chunk offset) —
                # under m-rope the rope positions are NOT monotone in it
                q_seq = write_index[:, None] + jnp.arange(t)[None, :]  # [B, T]
                causal = k_pos <= q_seq[:, None, None, :, None]
                written = k_pos < kv_len[:, None, None, None, None]
                logits = jnp.where(causal & written, logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                attn = jnp.einsum("bkgts,bskd->btkgd", probs.astype(self.dtype), new_v)
        attn = attn.reshape(b, t, h * dh)
        x = x + dense(cfg.dim, "in", name="o", use_bias=False, dtype=self.dtype)(attn)

        y = RMSNorm(eps=cfg.rms_eps, name="ln2")(x)
        if cfg.moe is not None:
            return x + MoEFFN(cfg, dtype=self.dtype, name="moe")(y), new_k, new_v
        up = dense(int(cfg.dim * cfg.hidden_mult), "out", name="up", use_bias=False, dtype=self.dtype)(y)
        gate = dense(int(cfg.dim * cfg.hidden_mult), "out", name="gate", use_bias=False, dtype=self.dtype)(y)
        down = dense(cfg.dim, "in", name="down", use_bias=False, dtype=self.dtype)(
            nn.silu(gate) * up
        )
        return x + down, new_k, new_v


class VLM(nn.Module):
    cfg: VLMConfig
    dtype: jnp.dtype = jnp.bfloat16
    # optional device mesh threaded to every DecoderLayer: enables the
    # head-parallel paged-attention path (tensor parallelism over Hkv)
    mesh: object = None

    def setup(self) -> None:
        cfg = self.cfg
        self.embed = nn.Embed(
            cfg.vocab,
            cfg.dim,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            embedding_init=nn.with_partitioning(nn.initializers.normal(0.02), (None, MODEL_AXIS)),
        )
        self.layers = [
            DecoderLayer(cfg, dtype=self.dtype, mesh=self.mesh, name=f"layer_{i}")
            for i in range(cfg.n_layers)
        ]
        self.ln_f = RMSNorm(eps=cfg.rms_eps, name="ln_f")
        self.lm_head = (
            None
            if cfg.tied_embeddings
            else dense(cfg.vocab, "out", name="lm_head", use_bias=False, dtype=jnp.float32)
        )
        if cfg.vision_variant in ("qwen2", "qwen3"):
            self.vision_tower = QwenVisionTower(cfg.qwen_vision, dtype=self.dtype, name="vision")
            self.projector = None  # the Qwen merger already maps to LM dim
        else:
            self.vision_tower = ViT(cfg.vision, dtype=self.dtype, name="vision")
            self.projector = nn.Sequential(
                [
                    dense(cfg.dim * 2, None, use_bias=True, dtype=self.dtype),
                    nn.gelu,
                    dense(cfg.dim, None, use_bias=True, dtype=self.dtype),
                ],
                name="projector",
            )

    def encode_images(self, frames_u8):
        """uint8 [B, N, Hp, Wp, 3] -> [B, T_vis, dim] LM embeddings.

        ``vit`` variant: frames through the ViT, patch tokens mean-pooled
        over frames, strided to ``vision_tokens``, projected.
        ``qwen2`` variant: frames → 3D patches → QwenVisionTower; the merged
        token grid (t·h·w/merge²) IS the LM embedding sequence, ordered
        t-major row-major (what build_mrope_positions assumes).
        ``qwen3`` variant: same, but returns (embeds, deepstack) — the
        deepstack levels [L_ds, B, T_vis, dim] inject into the first LM
        layers (HF Qwen3VLTextModel._deepstack_process).
        """
        cfg = self.cfg
        if cfg.vision_variant in ("qwen2", "qwen3"):
            patches, grid = frames_to_patches(frames_u8, cfg.qwen_vision)
            return self.vision_tower(patches, grid)
        b, n = frames_u8.shape[:2]
        pixels = preprocess_frames(
            frames_u8, image_size=cfg.vision.image_size, mode=cfg.vision.preprocess
        )
        _, tokens = self.vision_tower(pixels.reshape((b * n, *pixels.shape[2:])))
        tokens = tokens[:, 1:]  # drop cls
        tokens = tokens.reshape(b, n, tokens.shape[1], tokens.shape[2]).mean(axis=1)
        # stride-pool the patch grid down to vision_tokens
        stride = max(1, tokens.shape[1] // cfg.vision_tokens)
        tokens = tokens[:, :: stride][:, : cfg.vision_tokens]
        return self.projector(tokens)

    def embed_tokens(self, token_ids):
        return self.embed(token_ids)

    def init_everything(self, frames_u8, token_ids, cache_k, cache_v):
        """Init-only method touching every submodule (flax only creates
        params for modules traced during init)."""
        vis = self.encode_images(frames_u8)
        txt = self.embed_tokens(token_ids)
        deepstack = None
        if isinstance(vis, tuple):  # qwen3: (embeds, deepstack levels)
            vis, ds = vis
            pad = jnp.zeros((ds.shape[0], ds.shape[1], txt.shape[1], ds.shape[-1]), ds.dtype)
            deepstack = jnp.concatenate([ds, pad], axis=2)
        embeds = jnp.concatenate([vis, txt], axis=1)
        t = embeds.shape[1]
        positions = jnp.broadcast_to(jnp.arange(t), (embeds.shape[0], t))
        return self(
            embeds,
            cache_k,
            cache_v,
            positions,
            jnp.zeros((embeds.shape[0],), jnp.int32),
            jnp.full((embeds.shape[0],), t, jnp.int32),
            deepstack=deepstack,
        )

    def __call__(
        self, embeds, cache_k, cache_v, positions, write_index, kv_len, deepstack=None
    ):
        """Forward over input *embeddings* (text and vision already spliced).

        embeds: [B, T, D]; cache_k/v: [L, B, S, Hkv, Dh]; deepstack:
        optional [L_ds, B, T, D] visual features added to the hidden states
        AFTER each of the first L_ds layers (zeros at text positions — HF
        Qwen3VL deepstack semantics; prefill-only, decode passes None).
        Returns (logits [B, T, vocab], new_cache_k, new_cache_v).
        """
        x = embeds.astype(self.dtype)
        n_ds = 0 if deepstack is None else deepstack.shape[0]
        new_ks, new_vs = [], []
        for i, layer in enumerate(self.layers):
            x, nk, nv = layer(x, cache_k[i], cache_v[i], positions, write_index, kv_len)
            if i < n_ds:
                x = x + deepstack[i].astype(x.dtype)
            new_ks.append(nk)
            new_vs.append(nv)
        x = self.ln_f(x)
        if self.lm_head is not None:  # untied checkpoints (Qwen2.5-VL-7B)
            logits = self.lm_head(x.astype(jnp.float32))
        else:
            logits = self.embed.attend(x.astype(jnp.float32))
        return logits, jnp.stack(new_ks), jnp.stack(new_vs)

    def paged_forward(
        self, embeds, pool_k, pool_v, positions, write_index, kv_len, block_tables,
        deepstack=None,
    ):
        """Forward straight against the paged KV pool — no working-set view.

        embeds: [B, T, D]; pool_k/pool_v: the FULL block pools
        ``[L, NB, bs, Hkv, Dh]`` threaded through every layer (each layer
        scatters its chunk through ``block_tables`` [B, nbl] and attends in
        place via ops/paged_attention.py); write_index/kv_len as in
        ``__call__``. Returns (logits [B, T, vocab], pool_k, pool_v) — the
        updated pools, never a ``jnp.stack`` of per-layer copies, so XLA
        donation keeps the scatters in-place.
        """
        x = embeds.astype(self.dtype)
        n_ds = 0 if deepstack is None else deepstack.shape[0]
        for i, layer in enumerate(self.layers):
            x, pool_k, pool_v = layer(
                x, pool_k, pool_v, positions, write_index, kv_len,
                block_tables=block_tables, layer_index=i,
            )
            if i < n_ds:
                x = x + deepstack[i].astype(x.dtype)
        x = self.ln_f(x)
        if self.lm_head is not None:  # untied checkpoints (Qwen2.5-VL-7B)
            logits = self.lm_head(x.astype(jnp.float32))
        else:
            logits = self.embed.attend(x.astype(jnp.float32))
        return logits, pool_k, pool_v


def init_cache(cfg: VLMConfig, batch: int, dtype=jnp.bfloat16, length: int | None = None):
    shape = (cfg.n_layers, batch, length or cfg.max_seq, cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
