"""Shot-transition detector: dilated 3D-CNN over frame windows.

Equivalent capability of the reference's TransNetV2
(cosmos_curate/models/transnetv2.py:39-580, a torch DDCNN): per-frame shot
transition probabilities over overlap-averaged sliding windows (``WINDOW``
frames — 32 here; the reference uses 100) on 48x27 inputs.
This is our own Flax implementation of the DDCNN idea (Soucek & Lokoc,
TransNet V2, public architecture): blocks of parallel 3D convs with
exponential temporal dilations, spatial pooling between stages, per-frame
head.

TPU-first: the whole sliding-window batch is one conv3d-heavy jit (convs map
to MXU); windows are batched, not looped.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

INPUT_H, INPUT_W = 27, 48
# Inference windows MUST match the training window (transnet_train.train
# enforces it):
# the dilated temporal convs' SAME-padding gives every in-window position
# an edge signature, so a model trained at one window length does not
# transfer to another (observed: window-16 training produced positional,
# content-free outputs under 100-frame windows). 32 keeps CPU training
# affordable while overlap-averaging (stride = half) smooths edges exactly
# as in training-time geometry.
WINDOW = 32
STRIDE = 16  # overlap-averaged halves, like the published model


@dataclass(frozen=True)
class TransNetConfig:
    # (8, 16, 32) is capacity-sufficient for hard-cut detection and trains
    # ~4x faster than (16, 32, 64). Checkpoints produced by
    # models/transnet_train.py use these defaults; a checkpoint staged with
    # other shapes falls back to random init with a warning (registry).
    # ARCH REVISION (round 5): LayerNorm between block pairs — any
    # checkpoint trained before it has a different tree and is rejected by
    # the registry's shape validation (clear stale $CURATE_MODEL_WEIGHTS_DIR
    # staging dirs; no pre-revision checkpoint was ever committed).
    filters: tuple[int, ...] = (8, 16, 32)
    dilations: tuple[int, ...] = (1, 2, 4, 8)
    head_dim: int = 128


TRANSNET_TINY_TEST = TransNetConfig(filters=(4,), dilations=(1, 2), head_dim=16)


class DDCNNBlock(nn.Module):
    """Parallel temporal-dilated 3D convs, concatenated."""

    filters: int
    dilations: tuple[int, ...]
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        outs = [
            nn.Conv(
                self.filters,
                kernel_size=(3, 3, 3),
                kernel_dilation=(d, 1, 1),
                padding="SAME",
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name=f"conv_d{d}",
            )(x)
            for d in self.dilations
        ]
        return nn.relu(jnp.concatenate(outs, axis=-1))


class TransNet(nn.Module):
    cfg: TransNetConfig = TransNetConfig()
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, frames):
        """frames: uint8 [B, T, 27, 48, 3] -> logits [B, T]."""
        x = frames.astype(self.dtype) / 255.0
        for i, f in enumerate(self.cfg.filters):
            x = DDCNNBlock(f, self.cfg.dilations, dtype=self.dtype, name=f"dd{i}a")(x)
            x = DDCNNBlock(f, self.cfg.dilations, dtype=self.dtype, name=f"dd{i}b")(x)
            # normalization between block pairs: without it the 6-conv
            # stack optimizes glacially at small batch (the published
            # TransNetV2 uses batch norm; layer norm is batch-size-free)
            x = nn.LayerNorm(dtype=jnp.float32, name=f"ln{i}")(x)
            x = nn.avg_pool(x, (1, 2, 2), strides=(1, 2, 2))
        # per-frame spatial pooling -> [B, T, C]
        x = x.mean(axis=(2, 3))
        x = nn.relu(
            nn.Dense(self.cfg.head_dim, dtype=self.dtype, param_dtype=jnp.float32, name="fc")(x)
        )
        logits = nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32, name="head")(x)
        return logits[..., 0]


class TransNetV2TPU(ModelInterface):
    """ModelInterface wrapper: windowed inference over arbitrary-length
    videos, returning per-frame transition probabilities."""

    MODEL_ID = "transnetv2-tpu"

    def __init__(self, batch_windows: int = 8, cfg: TransNetConfig = TransNetConfig()) -> None:
        self.batch_windows = batch_windows
        self.cfg = cfg
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        model = TransNet(self.cfg)

        def init(seed: int):
            dummy = jnp.zeros((1, WINDOW, INPUT_H, INPUT_W, 3), jnp.uint8)
            return model.init(jax.random.PRNGKey(seed), dummy)

        self._params = registry.load_params(self.MODEL_ID, init)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline, donate_kwargs

        self._apply = jax.jit(
            lambda p, x: jax.nn.sigmoid(model.apply(p, x)), **donate_kwargs(1)
        )
        self._pipeline = DevicePipeline("transnet", self._apply)

    def predict_transitions(self, frames: np.ndarray) -> np.ndarray:
        """frames: uint8 [T, H, W, 3] (any H/W; resized on host) -> [T]
        per-frame transition probabilities, overlap-averaged over windows."""
        if self._apply is None:
            raise RuntimeError("call setup() first")
        t = frames.shape[0]
        if t == 0:
            return np.zeros(0, np.float32)
        import cv2

        small = np.stack(
            [cv2.resize(f, (INPUT_W, INPUT_H), interpolation=cv2.INTER_AREA) for f in frames]
        )
        # window starts at STRIDE spacing, padded at the tail
        starts = list(range(0, max(1, t - WINDOW + STRIDE), STRIDE))
        windows = np.zeros((len(starts), WINDOW, INPUT_H, INPUT_W, 3), np.uint8)
        for i, s in enumerate(starts):
            chunk = small[s : s + WINDOW]
            windows[i, : len(chunk)] = chunk
            if len(chunk) < WINDOW:  # pad by repeating last frame
                windows[i, len(chunk):] = chunk[-1]
        probs_sum = np.zeros(t, np.float64)
        probs_cnt = np.zeros(t, np.float64)
        # submit every window batch before reading any back: H2D of batch
        # k+1 and compute of k overlap, readback resolves at drain
        for i in range(0, len(starts), self.batch_windows):
            self._pipeline.submit(self._params, windows[i : i + self.batch_windows])
        outs = self._pipeline.drain()
        for i, out in zip(range(0, len(starts), self.batch_windows), outs):
            for j, s in enumerate(starts[i : i + self.batch_windows]):
                end = min(s + WINDOW, t)
                probs_sum[s:end] += out[j, : end - s]
                probs_cnt[s:end] += 1
        return (probs_sum / np.maximum(probs_cnt, 1)).astype(np.float32)
