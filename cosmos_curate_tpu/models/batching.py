"""Batch-shape discipline for XLA: pad ragged host batches to a small set of
static sizes.

Everything under jit is compiled per shape (SURVEY/XLA semantics); clip
counts vary per task, so without padding every distinct batch size costs a
~20-40 s TPU compile. Padding to the next power of two bounds the number of
compiled programs at log2(max_batch) while wasting <2x FLOPs worst-case —
on the MXU that trade is strongly right.
"""

from __future__ import annotations

import numpy as np


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def pad_to(x: np.ndarray, target: int) -> np.ndarray:
    """Pad x's leading dim to exactly ``target`` rows by repeating the last
    row (padded rows stay in-distribution). The pad block is a broadcast
    VIEW of the last row — the only materialization is the concat output
    itself, so peak host memory is output + input, not output + input +
    an np.repeat copy of the pad rows (~2x lower for near-pow2 batches)."""
    n = x.shape[0]
    if target < n:
        raise ValueError(f"pad_to target {target} < batch size {n}")
    if target == n:
        return x
    pad = np.broadcast_to(x[-1:], (target - n, *x.shape[1:]))
    return np.concatenate([x, pad], axis=0)


def pad_batch(x: np.ndarray, *, max_pad_to: int = 4096) -> tuple[np.ndarray, int]:
    """Pad x's leading dim to the next power of two (repeating the last row,
    so padded rows stay in-distribution). Returns (padded, original_n).

    A batch already past ``max_pad_to`` is returned unpadded: the cap
    exists to bound pad waste at huge sizes, not to truncate work — the
    caller's batch shape becomes the compiled shape."""
    n = x.shape[0]
    if n == 0:
        return x, 0
    if max_pad_to < 1:
        raise ValueError(f"max_pad_to must be >= 1, got {max_pad_to}")
    if n >= max_pad_to:
        return x, n
    target = min(next_pow2(n), max_pad_to)
    if target <= n:
        return x, n
    return pad_to(x, target), n
