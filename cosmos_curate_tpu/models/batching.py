"""Batch-shape discipline for XLA: pad ragged host batches to a small set of
static sizes.

Everything under jit is compiled per shape (SURVEY/XLA semantics); clip
counts vary per task, so without padding every distinct batch size costs a
~20-40 s TPU compile. Padding to the next power of two bounds the number of
compiled programs at log2(max_batch) while wasting <2x FLOPs worst-case —
on the MXU that trade is strongly right.
"""

from __future__ import annotations

import numpy as np


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def pad_batch(x: np.ndarray, *, max_pad_to: int = 4096) -> tuple[np.ndarray, int]:
    """Pad x's leading dim to the next power of two (repeating the last row,
    so padded rows stay in-distribution). Returns (padded, original_n)."""
    n = x.shape[0]
    if n == 0:
        return x, 0
    target = min(next_pow2(n), max_pad_to)
    if target <= n:
        return x, n
    reps = np.repeat(x[-1:], target - n, axis=0)
    return np.concatenate([x, reps], axis=0), n
