"""Canonical mesh-axis registry: the single source of truth for axis names.

Every collective plane in this codebase is a ``jax.sharding.Mesh`` over the
same four logical axes (parallel/mesh.py, scaling-book convention):

  ``DCN``   — across hosts/slices (data-parallel only; rides DCN)
  ``DATA``  — batch shards within a slice
  ``MODEL`` — tensor/expert-parallel shards (rides ICI)
  ``SEQ``   — sequence/context-parallel shards (ring attention, Ulysses,
              windowed SR sequence parallelism)

A typo'd axis name in a ``PartitionSpec`` or ``shard_map`` spec only fails
minutes into a run on real chips — so axis names flow from here, never from
scattered string literals. The ``mesh-axis-literal`` lint rule
(analysis/rules/mesh_axis_literal.py) enforces this, and the shardcheck
pass (analysis/shard_check.py) validates specs against these axes with zero
device allocation. T5X-style logical-axis-name partitioning is the prior
art for centralizing the vocabulary (SNIPPETS [2]).
"""

from __future__ import annotations

DCN = "dcn"
DATA = "data"
MODEL = "model"
SEQ = "seq"

# Axis order matches MeshSpec / best_effort_mesh device reshaping.
MESH_AXES: tuple[str, ...] = (DCN, DATA, MODEL, SEQ)

# Axes a leading [B, ...] batch dimension shards over (shard_batch /
# batch_sharding in parallel/sharding.py).
BATCH_AXES: tuple[str, ...] = (DCN, DATA)
