"""Ring attention: sequence/context parallelism over a mesh axis.

First-class long-context support (the reference only windows at the data
layer; in-model long context lives inside vLLM — SURVEY.md §5). Here a
sequence is sharded across the ``seq`` mesh axis; each device holds one Q/K/V
chunk and K/V chunks rotate around the ring via ``lax.ppermute`` while a
numerically-stable online softmax accumulates output — compute overlaps the
ICI transfer and full attention is recovered exactly (Liu et al., Ring
Attention with Blockwise Transformers, 2023 — public technique).

Pure-XLA implementation (collectives emitted by the compiler); drop-in
upgrade path to a Pallas per-step kernel via the same chunk interface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from cosmos_curate_tpu.parallel import axes
from cosmos_curate_tpu.parallel.sharding import shard_map


def _online_softmax_step(o, m, l, s, v_cur):
    """Fold one score block into the running (output, max, normalizer)."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp with per-row rescale of previous accumulation
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur
    ).astype(o.dtype)
    return o_new, m_new, l_new


def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool, sm_scale: float):
    """Runs inside shard_map. q/k/v: [B, H, S_local, D] per device."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    q_f32 = q.astype(jnp.float32) * sm_scale
    o = jnp.zeros((b, h, s_q, d), jnp.float32)
    m = jnp.full((b, h, s_q), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_q), jnp.float32)

    q_pos = my_idx * s_q + jnp.arange(s_q)  # global positions of local queries

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        # After `step` rotations each device holds the chunk originally owned
        # by (my_idx - step) mod N.
        k_chunk_idx = (my_idx - step) % axis_size
        s = jnp.einsum("bhqd,bhkd->bhqk", q_f32, k_cur.astype(jnp.float32))
        if causal:
            k_pos = k_chunk_idx * s_k + jnp.arange(s_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        o, m, l = _online_softmax_step(o, m, l, s, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(axis_size))
    # Fully-masked rows (can't happen for causal with aligned chunks, but
    # guard against l == 0 for safety).
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = axes.SEQ,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Exact attention with sequence sharded over ``mesh`` axis ``seq_axis``.

    Inputs are global-view arrays ``[B, H, S, D]``; S must divide evenly by
    the axis extent. Use inside ``jax.jit`` with sharded operands — the
    shard_map keeps each device's chunk local and only K/V ring-hops travel.
    """
    from jax.sharding import PartitionSpec as P

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(
        _ring_attention_sharded, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def attention_reference(q, k, v, *, causal: bool = False, sm_scale: float | None = None):
    """Single-device exact attention used for parity tests."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale, k.astype(jnp.float32))
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
