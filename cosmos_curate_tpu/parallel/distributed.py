"""Multi-host bootstrap: the distributed communication backend.

Equivalent capability of the reference's collective bootstrap
(dedup/raft_actor.py:84-131 — NCCL unique-id broadcast over a Ray actor
pool) re-designed for TPU: one call to ``jax.distributed.initialize`` per
host turns N hosts x M chips into one device world; every collective after
that is emitted by XLA over ICI (intra-slice) / DCN (inter-slice). No NCCL,
no unique-id plumbing — the coordinator address is the only configuration.

Environment contract (set by the slurm CLI, k8s chart, or the operator):
  CURATE_COORDINATOR_ADDRESS  host:port of node rank 0
  CURATE_NUM_NODES            total hosts
  CURATE_NODE_RANK            this host's rank
"""

from __future__ import annotations

import os

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Join the multi-host world when the env contract is present.

    Idempotent; returns True when running multi-host. Single-host runs
    (no env) are untouched — the same pipeline code works in both modes.
    """
    global _initialized
    if _initialized:
        return True
    addr = os.environ.get("CURATE_COORDINATOR_ADDRESS")
    num = int(os.environ.get("CURATE_NUM_NODES", "1"))
    if not addr or num <= 1:
        return False
    rank = int(
        os.environ.get("CURATE_NODE_RANK", os.environ.get("SLURM_NODEID", "0"))
    )
    import jax

    logger.info("joining distributed world: %s rank %d/%d", addr, rank, num)
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=num, process_id=rank
    )
    _initialized = True
    return True


def node_rank_and_count() -> tuple[int, int]:
    rank = int(
        os.environ.get("CURATE_NODE_RANK", os.environ.get("SLURM_NODEID", "0"))
    )
    num = int(os.environ.get("CURATE_NUM_NODES", "1"))
    return rank, max(1, num)


def partition_tasks_for_node(tasks: list) -> list:
    """Deterministic task partition across nodes (host-level data
    parallelism): node i takes every num_nodes-th task. Single-node runs
    return the list unchanged.

    Partitioning happens after resume filtering, so if nodes run at
    DIFFERENT times (not a simultaneous srun step) an item can fall between
    partitions for one run; it is picked up by the next run (verified:
    repeated runs converge to full coverage). Simultaneous nodes see the
    same discovery list and split it exactly."""
    rank, num = node_rank_and_count()
    if num <= 1:
        return tasks
    return tasks[rank::num]


def global_mesh_spec():
    """MeshSpec with the dcn axis sized to the host count (data-parallel
    across hosts, model/seq within a slice — the scaling-book default)."""
    from cosmos_curate_tpu.parallel.mesh import MeshSpec

    num = int(os.environ.get("CURATE_NUM_NODES", "1"))
    return MeshSpec(dcn=max(1, num), data=-1, model=1, seq=1)
