"""Mesh construction: the TPU-native answer to the reference's NCCL planes.

The reference has three communication planes (SURVEY.md §5): Ray object store
between stages, NCCL for dedup k-means, vLLM-internal NCCL for TP. Here every
collective plane is a `jax.sharding.Mesh`: XLA emits ICI collectives within a
slice and DCN collectives across slices — no NCCL anywhere.

Axis convention (scaling-book style):
  ``dcn``   — across hosts/slices (data-parallel only; rides DCN)
  ``data``  — batch shards within a slice
  ``model`` — tensor-parallel shards (rides ICI)
  ``seq``   — sequence/context-parallel shards for ring attention
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; -1 axes absorb remaining devices (like reshape)."""

    dcn: int = 1
    data: int = -1
    model: int = 1
    seq: int = 1

    def axis_names(self) -> tuple[str, ...]:
        return ("dcn", "data", "model", "seq")


def local_mesh(axis_names: tuple[str, ...] = ("data", "model"), shape: tuple[int, ...] | None = None):
    """Mesh over this process's local devices (the ``entire_tpu_host`` worker
    claim). Default: all chips on one ``model`` axis when shape is None and
    one axis name given, else data×model split with model = all chips."""
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices()
    n = len(devices)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        elif len(axis_names) == 2:
            shape = (1, n)
        else:
            raise ValueError("provide an explicit shape for >2 axes")
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} local devices")
    return Mesh(np.array(devices).reshape(shape), axis_names=axis_names)


def best_effort_mesh(spec: MeshSpec | None = None):
    """Build the full (dcn, data, model, seq) mesh over all visible devices,
    resolving -1 axes. Single-host single-chip degenerates to (1,1,1,1)."""
    import jax
    from jax.sharding import Mesh

    spec = spec or MeshSpec()
    devices = jax.devices()
    n = len(devices)
    dims = [spec.dcn, spec.data, spec.model, spec.seq]
    n_fixed = int(np.prod([d for d in dims if d > 0]))
    n_free = sum(1 for d in dims if d <= 0)
    if n_free > 1:
        raise ValueError("at most one mesh axis may be -1")
    if n_free == 1:
        if n % n_fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {dims}")
        dims = [d if d > 0 else n // n_fixed for d in dims]
    if int(np.prod(dims)) != n:
        raise ValueError(f"mesh {dims} != {n} devices")
    return Mesh(np.array(devices).reshape(dims), axis_names=spec.axis_names())
