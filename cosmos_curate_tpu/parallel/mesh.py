"""Mesh construction: the TPU-native answer to the reference's NCCL planes.

The reference has three communication planes (SURVEY.md §5): Ray object store
between stages, NCCL for dedup k-means, vLLM-internal NCCL for TP. Here every
collective plane is a `jax.sharding.Mesh`: XLA emits ICI collectives within a
slice and DCN collectives across slices — no NCCL anywhere.

Axis names come from the canonical registry (parallel/axes.py):
``dcn`` / ``data`` / ``model`` / ``seq`` — see its docstring for semantics.
``MeshSpec.resolve`` is the device-free half (pure arithmetic over extents),
so build-time checks (analysis/shard_check.py) validate the same logic the
run-time mesh constructors use.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.parallel.axes import DATA, MESH_AXES, MODEL, SEQ


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; -1 axes absorb remaining devices (like reshape)."""

    dcn: int = 1
    data: int = -1
    model: int = 1
    seq: int = 1

    def axis_names(self) -> tuple[str, ...]:
        return MESH_AXES

    def extents(self) -> tuple[int, ...]:
        return (self.dcn, self.data, self.model, self.seq)

    def extent_errors(self) -> list[str]:
        """Structural problems with the declared extents (empty = well
        formed). The single source of this validation: ``resolve`` raises
        on them and shardcheck's ``mesh_tiling_errors`` reports them."""
        dims = self.extents()
        if any(d == 0 or d < -1 for d in dims):
            return [f"mesh axis extents must be positive or -1, got {dims}"]
        if sum(1 for d in dims if d == -1) > 1:
            return ["at most one mesh axis may be -1"]
        return []

    def resolve(self, num_devices: int) -> dict[str, int]:
        """Concrete extent per axis over ``num_devices``, with the single
        -1 axis absorbing the remainder. Raises ``ValueError`` when the
        spec cannot tile the device count — the same arithmetic
        ``best_effort_mesh`` builds with and shardcheck validates
        device-free."""
        for msg in self.extent_errors():
            raise ValueError(msg)
        dims = list(self.extents())
        n_free = sum(1 for d in dims if d == -1)
        n_fixed = int(np.prod([d for d in dims if d > 0]))
        if n_free == 1:
            if num_devices % n_fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {dims}"
                )
            dims = [d if d > 0 else num_devices // n_fixed for d in dims]
        if int(np.prod(dims)) != num_devices:
            raise ValueError(f"mesh {dims} != {num_devices} devices")
        return dict(zip(self.axis_names(), dims))


def local_mesh(axis_names: tuple[str, ...] = (DATA, MODEL), shape: tuple[int, ...] | None = None):
    """Mesh over this process's local devices (the ``entire_tpu_host`` worker
    claim). Default: all chips on one ``model`` axis when shape is None and
    one axis name given, else data×model split with model = all chips."""
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices()
    n = len(devices)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        elif len(axis_names) == 2:
            shape = (1, n)
        else:
            raise ValueError("provide an explicit shape for >2 axes")
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} local devices")
    return Mesh(np.array(devices).reshape(shape), axis_names=axis_names)


def seq_mesh(n: int):
    """Mesh over the first ``n`` visible devices on the ``seq`` axis — the
    sequence-parallel plane the windowed SR models shard_map over. Central
    so device selection is not re-derived (and hardcoded) per model; see
    the hardcoded-device-count lint rule."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n > len(devices):
        raise ValueError(f"seq mesh needs {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), axis_names=(SEQ,))


def best_effort_mesh(spec: MeshSpec | None = None):
    """Build the full (dcn, data, model, seq) mesh over all visible devices,
    resolving -1 axes. Single-host single-chip degenerates to (1,1,1,1)."""
    import jax
    from jax.sharding import Mesh

    spec = spec or MeshSpec()
    devices = jax.devices()
    dims = spec.resolve(len(devices))
    return Mesh(
        np.array(devices).reshape(tuple(dims.values())), axis_names=spec.axis_names()
    )
