from cosmos_curate_tpu.parallel.axes import BATCH_AXES, DATA, DCN, MESH_AXES, MODEL, SEQ
from cosmos_curate_tpu.parallel.mesh import (
    MeshSpec,
    best_effort_mesh,
    local_mesh,
    seq_mesh,
)
from cosmos_curate_tpu.parallel.sharding import (
    batch_shard_count,
    batch_sharding,
    named_sharding,
    replicated,
    shard_batch,
    shard_map,
    unshard_batch,
)

__all__ = [
    "BATCH_AXES",
    "DATA",
    "DCN",
    "MESH_AXES",
    "MODEL",
    "SEQ",
    "MeshSpec",
    "batch_shard_count",
    "batch_sharding",
    "best_effort_mesh",
    "local_mesh",
    "named_sharding",
    "replicated",
    "seq_mesh",
    "shard_batch",
    "shard_map",
    "unshard_batch",
]
