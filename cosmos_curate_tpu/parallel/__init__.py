from cosmos_curate_tpu.parallel.mesh import (
    MeshSpec,
    best_effort_mesh,
    local_mesh,
)
from cosmos_curate_tpu.parallel.sharding import (
    batch_sharding,
    named_sharding,
    replicated,
    shard_batch,
)

__all__ = [
    "MeshSpec",
    "batch_sharding",
    "best_effort_mesh",
    "local_mesh",
    "named_sharding",
    "replicated",
    "shard_batch",
]
