"""Sharding helpers: NamedSharding construction and host→device batch placement.

The scaling-book recipe: pick a mesh, annotate shardings on the big tensors,
let XLA insert collectives. These helpers keep annotations terse at stage
call sites, and centralize the host→device transfer (the critical data path
feeding chips from CPU prep stages, SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

from typing import Any

import numpy as np


def named_sharding(mesh, *spec_axes: str | tuple[str, ...] | None):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec_axes))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh, batch_axes: str | tuple[str, ...] = ("dcn", "data")):
    """Sharding for a [B, ...] batch: leading dim over the data axes."""
    axes = tuple(a for a in (batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)) if a in mesh.axis_names)
    return named_sharding(mesh, axes if axes else None)


def shard_batch(mesh, tree: Any, batch_axes: str | tuple[str, ...] = ("dcn", "data")):
    """Device-put a host pytree of [B, ...] numpy arrays, batch-sharded.

    Pads the batch up to a multiple of the data-axis extent (model code must
    mask or slice off padding; returned pad counts say how much was added).
    """
    import jax

    sharding = batch_sharding(mesh, batch_axes)
    n_shards = int(np.prod([mesh.shape[a] for a in _axes_tuple(batch_axes) if a in mesh.axis_names])) or 1

    def _pad(x):
        b = x.shape[0]
        rem = (-b) % n_shards
        if rem:
            pad = np.zeros((rem, *x.shape[1:]), x.dtype)
            x = np.concatenate([x, pad], axis=0)
        return x

    padded = jax.tree.map(_pad, tree)
    first = jax.tree.leaves(tree)[0]
    pad_count = (-first.shape[0]) % n_shards
    return jax.device_put(padded, sharding), pad_count


def _axes_tuple(batch_axes) -> tuple[str, ...]:
    return batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)
