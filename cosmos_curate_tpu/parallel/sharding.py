"""Sharding helpers: NamedSharding construction and host→device batch placement.

The scaling-book recipe: pick a mesh, annotate shardings on the big tensors,
let XLA insert collectives. These helpers keep annotations terse at stage
call sites, and centralize the host→device transfer (the critical data path
feeding chips from CPU prep stages, SURVEY.md §7 hard part 3).

Axis names come from parallel/axes.py; ``shard_map`` here is the
version-compat front door every shard_map call site uses (``jax.shard_map``
landed after this image's JAX, which only has the experimental API).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from cosmos_curate_tpu.parallel.axes import BATCH_AXES


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across JAX versions: the top-level API when present,
    else ``jax.experimental.shard_map`` (where ``check_vma`` was named
    ``check_rep``). Accepts ``jax.sharding.AbstractMesh`` too, so specs can
    be shape-checked under ``jax.eval_shape`` with zero devices — the
    mechanism behind ``cosmos-curate-tpu lint --shard-check``."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def named_sharding(mesh, *spec_axes: str | tuple[str, ...] | None):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec_axes))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh, batch_axes: str | tuple[str, ...] = BATCH_AXES):
    """Sharding for a [B, ...] batch: leading dim over the data axes.
    Axes absent from the mesh are dropped; with none left the batch is
    replicated (the single-axis / model-only mesh fallback)."""
    axes = tuple(a for a in _axes_tuple(batch_axes) if a in mesh.axis_names)
    return named_sharding(mesh, axes if axes else None)


def batch_shard_count(mesh, batch_axes: str | tuple[str, ...] = BATCH_AXES) -> int:
    """How many ways ``batch_sharding`` splits the leading dim on ``mesh``."""
    return int(
        np.prod([mesh.shape[a] for a in _axes_tuple(batch_axes) if a in mesh.axis_names])
    ) or 1


def shard_batch(mesh, tree: Any, batch_axes: str | tuple[str, ...] = BATCH_AXES):
    """Device-put a host pytree of [B, ...] numpy arrays, batch-sharded.

    Pads the batch up to a multiple of the data-axis extent (model code must
    mask or slice off padding; the returned pad count says how much was
    added — ``unshard_batch`` strips it). Every leaf must agree on the
    leading dim: a silently-wrong per-leaf pad is worse than a loud error.
    """
    import jax

    leaves = jax.tree.leaves(tree)
    if not leaves:
        raise ValueError("shard_batch: empty pytree — nothing to shard")
    batch_dims = {getattr(x, "shape", ())[:1] for x in leaves}
    if () in batch_dims:
        raise ValueError("shard_batch: scalar leaf has no batch dimension")
    if len(batch_dims) > 1:
        sizes = sorted(b[0] for b in batch_dims)
        raise ValueError(
            f"shard_batch: leaves disagree on the leading batch dim: {sizes}"
        )
    sharding = batch_sharding(mesh, batch_axes)
    n_shards = batch_shard_count(mesh, batch_axes)

    def _pad(x):
        b = x.shape[0]
        rem = (-b) % n_shards
        if rem:
            pad = np.zeros((rem, *x.shape[1:]), x.dtype)
            x = np.concatenate([x, pad], axis=0)
        return x

    padded = jax.tree.map(_pad, tree)
    pad_count = (-leaves[0].shape[0]) % n_shards
    return jax.device_put(padded, sharding), pad_count


def unshard_batch(tree: Any, pad_count: int) -> Any:
    """Host-side inverse of ``shard_batch``: gather each leaf back to numpy
    and strip the ``pad_count`` padding rows it appended."""
    import jax

    def _cut(x):
        x = np.asarray(x)
        return x[: x.shape[0] - pad_count] if pad_count else x

    return jax.tree.map(_cut, tree)


def _axes_tuple(batch_axes) -> tuple[str, ...]:
    return batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)
