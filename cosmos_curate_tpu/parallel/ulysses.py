"""Ulysses-style sequence parallelism: all-to-all head↔sequence exchange.

The complementary strategy to ring attention (DeepSpeed-Ulysses, public
technique): sequence-sharded activations are all-to-all'd so each device
holds *all* tokens for a subset of heads, runs dense local attention, and
all-to-all's back. One collective round instead of N ring hops — wins when
heads ≥ devices and the full sequence fits per-device; ring attention wins
for extreme lengths. Both ride ICI via XLA collectives.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from cosmos_curate_tpu.parallel import axes
from cosmos_curate_tpu.parallel.sharding import shard_map


def _ulysses_sharded(q, k, v, *, axis_name: str, causal: bool, sm_scale: float | None):
    from cosmos_curate_tpu.parallel.ring_attention import attention_reference

    # [B, H, S_local, D] -> [B, H_local, S, D]: scatter heads, gather sequence
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    seq_axis: str = axes.SEQ,
    causal: bool = False,
    sm_scale: float | None = None,
) -> jax.Array:
    """Exact attention over sequence-sharded ``[B, H, S, D]`` inputs; the
    head count must divide the ``seq_axis`` extent."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[seq_axis]
    if q.shape[1] % n:
        raise ValueError(f"heads ({q.shape[1]}) must divide by mesh axis {seq_axis}={n}")
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(_ulysses_sharded, axis_name=seq_axis, causal=causal, sm_scale=sm_scale)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
