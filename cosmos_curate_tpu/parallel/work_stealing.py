"""Shared-ledger work distribution for multi-node pipeline runs.

Equivalent capability of the reference's central scheduling loop
(cosmos-xenna ARCHITECTURE.md:25-27,83-93 — tasks move to idle nodes), built
on the storage layer instead of a cross-node object plane: every node pulls
small batches from one shared claim ledger under the output root, so a node
whose inputs are heavy simply claims fewer batches and a node that drains
early keeps pulling — the 9:1-skew case the static partition cannot fix.

Claim protocol (object-storage friendly, no atomic primitives required):
write ``work_claims/<record_id>.json`` with ``{rank, ts}``, read it back,
and process only if the read returns our rank. The read-back closes the
last-writer-wins window to the storage round-trip; a lost race costs at
most one duplicated task, and duplication is CORRECT here — outputs are
deterministic per task and resume records are idempotent (same property the
reference leans on for its retry semantics). Crashed claimers are covered
by a TTL: stale claims are re-claimable.

Enable on a multi-node run with ``CURATE_WORK_STEALING=1`` (the default
remains the exact static partition, whose disjoint accounting some
workflows assert on).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Sequence

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_TTL_S = 1800.0


def stealing_enabled() -> bool:
    return os.environ.get("CURATE_WORK_STEALING", "0") == "1"


def claim_next_batch(
    tasks: Sequence,
    output_path: str,
    *,
    record_id: Callable[[object], str],
    batch: int = 2,
    ttl_s: float = DEFAULT_TTL_S,
    rank: int | None = None,
) -> list:
    """Claim up to ``batch`` unclaimed (or stale-claimed) tasks.

    Scanning starts at a rank-dependent offset so simultaneous nodes mostly
    race for DIFFERENT tasks; the read-back check settles the rest.
    """
    from cosmos_curate_tpu.parallel.distributed import node_rank_and_count
    from cosmos_curate_tpu.storage.client import get_storage_client

    if rank is None:
        rank, _ = node_rank_and_count()
    client = get_storage_client(output_path)
    root = f"{output_path.rstrip('/')}/work_claims"
    claimed: list = []
    n = len(tasks)
    if n == 0:
        return claimed
    start = (rank * max(1, batch)) % n
    now = time.time()
    for j in range(n):
        task = tasks[(start + j) % n]
        rid = record_id(task)
        path = f"{root}/{rid}.json"
        try:
            rec = json.loads(client.read_bytes(path))
            if now - float(rec.get("ts", 0)) < ttl_s:
                # fresh claim blocks everyone INCLUDING our own rank — a
                # failing task is retried only after the TTL, and a
                # restarted node (same rank, fresh process) can reclaim
                # its own stale claims instead of skipping them forever
                continue
        except Exception:
            pass  # no claim yet (or unreadable: treat as stale)
        client.write_bytes(path, json.dumps({"rank": rank, "ts": now}).encode())
        try:
            winner = json.loads(client.read_bytes(path))
            if int(winner.get("rank", -1)) != rank:
                continue  # lost the write race
        except Exception:
            continue
        claimed.append(task)
        if len(claimed) >= batch:
            break
    if claimed:
        logger.info(
            "claimed %d task(s) from the shared ledger (rank %d)", len(claimed), rank
        )
    return claimed


def _heartbeat_claims(
    output_path: str, rids: list[str], ttl_s: float, stop: threading.Event
) -> None:
    """Re-write the claim records for ``rids`` every ttl/3 until stopped.
    Failures are logged and retried next period — a flaky beat at worst
    allows a duplicate, which the ledger's semantics already tolerate."""
    from cosmos_curate_tpu.parallel.distributed import node_rank_and_count
    from cosmos_curate_tpu.storage.client import get_storage_client

    rank, _ = node_rank_and_count()
    client = get_storage_client(output_path)
    root = f"{output_path.rstrip('/')}/work_claims"
    period = max(1.0, ttl_s / 3.0)
    while not stop.wait(period):
        for rid in rids:
            try:
                client.write_bytes(
                    f"{root}/{rid}.json",
                    json.dumps({"rank": rank, "ts": time.time()}).encode(),
                )
            except Exception:
                logger.exception("claim heartbeat failed for %s", rid)


def run_with_stealing(
    tasks: Sequence,
    output_path: str,
    run_batch: Callable[[list], list],
    *,
    record_id: Callable[[object], str],
    batch: int = 0,
    ttl_s: float = DEFAULT_TTL_S,
    is_done: Callable[[object], bool] | None = None,
    poll_s: float = 15.0,
) -> list:
    """Drain ``tasks`` by pulling claim batches until every task is claimed
    AND finished.

    ``run_batch`` processes one claimed batch and returns its outputs.
    ``batch=0`` (default) sizes claims adaptively — about half a node's
    fair share per pull, shrinking as the ledger drains — so each node pays
    ~2·log(share) pipeline spin-ups instead of one per pair of tasks, while
    the tail still rebalances at fine grain.

    When nothing is claimable but tasks remain (fresh claims held by other
    nodes), the node LINGERS: tasks whose ``is_done`` turns true drop off;
    tasks whose claimer crashed become claimable at the TTL and are taken
    over. Without the linger, a peer crashing after claiming would leave
    its tasks processed by no one while the run reports success. Pass
    ``is_done=None`` to keep the old exit-when-dry behavior."""
    from cosmos_curate_tpu.parallel.distributed import node_rank_and_count

    _, n_nodes = node_rank_and_count()
    out: list = []
    remaining = list(tasks)
    while remaining:
        size = batch or max(1, len(remaining) // (2 * max(1, n_nodes)))
        got = claim_next_batch(
            remaining, output_path, record_id=record_id, batch=size, ttl_s=ttl_s
        )
        if got:
            # heartbeat while the batch runs: an adaptive batch can hold
            # tasks serially for longer than the TTL, and a claim written
            # once would expire mid-run — a peer would take over and
            # duplicate the compute (ADVICE r3). Refreshing the claim
            # JSONs keeps them fresh for exactly as long as we're alive.
            stop = threading.Event()
            beat = threading.Thread(
                target=_heartbeat_claims,
                args=(output_path, [record_id(t) for t in got], ttl_s, stop),
                daemon=True,
            )
            beat.start()
            try:
                out += run_batch(got) or []
            finally:
                stop.set()
                beat.join(timeout=5)
            claimed_ids = {record_id(t) for t in got}
            remaining = [t for t in remaining if record_id(t) not in claimed_ids]
            continue
        if is_done is None:
            break
        before = len(remaining)
        remaining = [t for t in remaining if not is_done(t)]
        if not remaining:
            break
        if len(remaining) == before:
            logger.info(
                "waiting on %d task(s) claimed elsewhere (takeover after "
                "claim TTL if the claimer died)", len(remaining),
            )
            time.sleep(poll_s)
    return out
