"""S3 backend (requires boto3; constructed only when importable).

Equivalent capability of the reference's S3 client
(cosmos_curate/core/utils/storage/s3_client.py:56-627): ranged reads,
paginated listing, retrying uploads. Only loaded via
``storage.client.get_storage_client`` when boto3 exists.
"""

from __future__ import annotations

from typing import Iterator

from cosmos_curate_tpu.storage.client import ObjectInfo, StorageClient


def _split(path: str) -> tuple[str, str]:
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


class S3StorageClient(StorageClient):
    def __init__(self, **session_kwargs) -> None:
        import boto3

        if not session_kwargs:
            from cosmos_curate_tpu.utils.user_config import s3_session_kwargs

            session_kwargs = s3_session_kwargs()
        endpoint = session_kwargs.pop("endpoint_url", None)
        self._s3 = boto3.session.Session(**session_kwargs).client(
            "s3", endpoint_url=endpoint
        )

    def read_bytes(self, path: str) -> bytes:
        bucket, key = _split(path)
        try:
            return self._s3.get_object(Bucket=bucket, Key=key)["Body"].read()
        except Exception as e:
            # normalize the missing-object error to the contract every
            # caller's warn-and-skip path relies on (the REST clients raise
            # FileNotFoundError on 404 already)
            code = getattr(e, "response", {}).get("Error", {}).get("Code", "")
            if code in ("NoSuchKey", "404", "NotFound"):
                raise FileNotFoundError(path) from e
            raise

    def write_bytes(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        self._s3.put_object(Bucket=bucket, Key=key, Body=data)

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        try:
            self._s3.head_object(Bucket=bucket, Key=key)
            return True
        except self._s3.exceptions.ClientError:
            return False

    def delete(self, path: str) -> None:
        bucket, key = _split(path)
        self._s3.delete_object(Bucket=bucket, Key=key)

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        bucket, key = _split(prefix)
        paginator = self._s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=bucket, Prefix=key):
            for obj in page.get("Contents", []):
                p = f"s3://{bucket}/{obj['Key']}"
                if suffixes is None or p.lower().endswith(suffixes):
                    yield ObjectInfo(p, obj["Size"])
