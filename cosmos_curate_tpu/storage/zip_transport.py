"""Zip transport: move whole directories through URLs or storage paths.

Equivalent capability of the reference's presigned-URL transport
(cosmos_curate/core/utils/storage/presigned_s3_zip.py —
``zip_and_upload_directory_multipart``:334, ``download_and_extract_zip``:479
fanned out to every node): the credential-less IO path a job service uses
when callers hand it presigned URLs instead of bucket credentials.

Here: zip/unzip are local CPU work; the byte transport goes through the
storage layer for ``s3://``/``gs://``/local destinations and through plain
HTTP(S) for presigned URLs. Multi-node fan-out needs no special channel —
every node calls ``download_and_extract`` itself (object storage/HTTP is
the rendezvous), which replaces the reference's one-Ray-task-per-node
broadcast.
"""

from __future__ import annotations

import io
import os
import zipfile
from dataclasses import dataclass, field
from pathlib import Path

from cosmos_curate_tpu.storage.client import read_bytes, write_bytes
from cosmos_curate_tpu.storage.retry import chaos_storage_fault, sleep_backoff
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_HTTP = ("http://", "https://")


@dataclass
class PresignedMultipart:
    """A presigned S3 multipart upload, as handed out by a job submitter.

    The submitter initiates the multipart upload with its own credentials
    and presigns one URL per part plus the completion (and optionally
    abort) call; the uploader here never sees credentials — matching the
    reference's zip_and_upload_directory_multipart contract
    (core/utils/storage/presigned_s3_zip.py:334-478)."""

    part_urls: list[str] = field(default_factory=list)  # part 1 first
    complete_url: str = ""
    abort_url: str | None = None
    part_size: int = 64 * 1024 * 1024  # S3 minimum is 5 MiB per part

    @classmethod
    def from_dict(cls, d: dict) -> "PresignedMultipart":
        return cls(
            part_urls=list(d["part_urls"]),
            complete_url=d["complete_url"],
            abort_url=d.get("abort_url"),
            part_size=int(d.get("part_size", 64 * 1024 * 1024)),
        )


def zip_directory_to_file(src_dir: str | Path, zip_path: str | Path) -> int:
    """Deterministic zip of a directory tree (sorted entries, fixed mtimes)
    STREAMED to a file — per-file memory, not per-archive (the reference's
    multipart path exists for the same reason). Returns the zip size."""
    root = Path(src_dir)
    with zipfile.ZipFile(zip_path, "w", zipfile.ZIP_DEFLATED) as zf:
        for f in sorted(root.rglob("*")):
            if f.is_file():
                info = zipfile.ZipInfo(str(f.relative_to(root)))
                with f.open("rb") as src, zf.open(info, "w") as dst:
                    import shutil

                    shutil.copyfileobj(src, dst, length=1 << 20)
    return os.path.getsize(zip_path)


def zip_directory(src_dir: str | Path) -> bytes:
    """In-memory variant for small directories (tests, small artifacts)."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".zip") as f:
        zip_directory_to_file(src_dir, f.name)
        f.seek(0)
        return f.read()


def zip_and_upload_directory(src_dir: str | Path, dest: "str | PresignedMultipart") -> int:
    """Zip ``src_dir`` and upload it to ``dest`` (storage path, presigned
    HTTP URL, or a :class:`PresignedMultipart`). Returns the zip size in
    bytes. The archive is staged on local disk; only one part (multipart)
    or the transport step (single-PUT) holds bytes in memory (for local
    destinations it is an os-level rename, zero extra memory)."""
    import shutil
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".zip")
    os.close(fd)
    try:
        size = zip_directory_to_file(src_dir, tmp)
        if isinstance(dest, PresignedMultipart):
            _multipart_put(tmp, size, dest)
        elif dest.startswith(_HTTP):
            with open(tmp, "rb") as f:
                _http_put(dest, f.read())
        elif "://" not in dest:
            Path(dest).parent.mkdir(parents=True, exist_ok=True)
            shutil.move(tmp, dest)
            tmp = None  # consumed
        else:
            with open(tmp, "rb") as f:
                write_bytes(dest, f.read())
        logger.info("uploaded %s (%d bytes) -> %s", src_dir, size, _redact_dest(dest))
        return size
    finally:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)


def _multipart_put(zip_path: str, size: int, spec: PresignedMultipart, *, retries: int = 3) -> None:
    """Stream the staged zip through presigned part URLs with per-part
    retry, then complete. A failed part re-sends ONLY that part (the
    single-PUT path re-uploads everything — the reason multipart exists,
    reference presigned_s3_zip.py:334); completion posts the standard
    CompleteMultipartUpload XML with the collected ETags."""
    n_parts = max(1, -(-size // spec.part_size))
    if n_parts > len(spec.part_urls):
        raise ValueError(
            f"zip needs {n_parts} parts of {spec.part_size} B but only "
            f"{len(spec.part_urls)} presigned part URLs were provided"
        )
    etags: list[str] = []
    try:
        with open(zip_path, "rb") as f:
            for i in range(n_parts):
                data = f.read(spec.part_size)
                etags.append(_put_part(spec.part_urls[i], data, retries=retries))
        parts_xml = "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{e}</ETag></Part>"
            for i, e in enumerate(etags)
        )
        xml = f"<CompleteMultipartUpload>{parts_xml}</CompleteMultipartUpload>"
        _http_request(spec.complete_url, xml.encode(), method="POST", retries=retries)
        logger.info("multipart upload complete: %d parts, %d bytes", n_parts, size)
    except Exception:
        if spec.abort_url:
            try:
                _http_request(spec.abort_url, None, method="DELETE", retries=1)
                logger.info("aborted multipart upload after failure")
            except Exception:
                logger.exception("multipart abort also failed; upload may leak parts")
        raise


def _put_part(url: str, data: bytes, *, retries: int) -> str:
    headers = _http_request(url, data, method="PUT", retries=retries)
    etag = next((v for k, v in headers.items() if k.lower() == "etag"), "")
    if not etag:
        # fail on the FIRST part: completing with an empty <ETag> would be
        # rejected only after every byte has been uploaded
        raise RuntimeError(f"part PUT returned no ETag header: {_redact(url)}")
    return etag


def download_and_extract(src: str, dest_dir: str | Path) -> list[str]:
    """GET a zip from a storage path or presigned URL and extract it.

    Zip-slip safe: entries escaping ``dest_dir`` are rejected.
    """
    import shutil

    dest = Path(dest_dir)
    dest.mkdir(parents=True, exist_ok=True)
    extracted: list[str] = []
    if src.startswith(_HTTP) or "://" in src:
        data = _http_get(src) if src.startswith(_HTTP) else read_bytes(src)
        zf_source = io.BytesIO(data)
    else:
        zf_source = src  # local path: zipfile streams from disk
    with zipfile.ZipFile(zf_source) as zf:
        for info in zf.infolist():
            if info.is_dir():
                continue
            target = dest / info.filename
            if not target.resolve().is_relative_to(dest.resolve()):
                raise ValueError(f"zip entry escapes destination: {info.filename!r}")
            target.parent.mkdir(parents=True, exist_ok=True)
            with zf.open(info) as src_f, open(target, "wb") as dst_f:
                shutil.copyfileobj(src_f, dst_f, length=1 << 20)
            extracted.append(str(target))
    logger.info("extracted %d files from %s", len(extracted), _redact(src))
    return extracted


def _http_put(url: str, data: bytes) -> None:
    _http_request(url, data, method="PUT", retries=1)


def _http_request(
    url: str, data: bytes | None, *, method: str, retries: int
) -> dict[str, str]:
    import urllib.request

    last: Exception | None = None
    for attempt in range(retries):
        try:
            chaos_storage_fault()
            req = urllib.request.Request(url, data=data, method=method)
            if method == "PUT":
                req.add_header("Content-Type", "application/zip")
            with urllib.request.urlopen(req, timeout=600) as resp:
                if resp.status >= 300:
                    raise RuntimeError(f"{method} failed with {resp.status}")
                return dict(resp.headers)
        except Exception as e:  # noqa: BLE001
            last = e
            if attempt + 1 < retries:
                # keep this transport's slower schedule (presigned uploads
                # are long calls), now with full jitter like the rest
                sleep_backoff(attempt, base=1.0, cap=8.0)
    raise RuntimeError(f"{method} {_redact(url)} failed after {retries} attempts: {last}")


def _http_get(url: str) -> bytes:
    import urllib.request

    with urllib.request.urlopen(url, timeout=600) as resp:
        return resp.read()


def _redact(url: str) -> str:
    """Presigned URLs carry signatures in the query string; never log them."""
    return url.split("?", 1)[0] if url.startswith(_HTTP) else url


def _redact_dest(dest: "str | PresignedMultipart") -> str:
    if isinstance(dest, PresignedMultipart):
        return f"<multipart x{len(dest.part_urls)}: {_redact(dest.complete_url)}>"
    return _redact(dest)
