"""Zip transport: move whole directories through URLs or storage paths.

Equivalent capability of the reference's presigned-URL transport
(cosmos_curate/core/utils/storage/presigned_s3_zip.py —
``zip_and_upload_directory_multipart``:334, ``download_and_extract_zip``:479
fanned out to every node): the credential-less IO path a job service uses
when callers hand it presigned URLs instead of bucket credentials.

Here: zip/unzip are local CPU work; the byte transport goes through the
storage layer for ``s3://``/``gs://``/local destinations and through plain
HTTP(S) for presigned URLs. Multi-node fan-out needs no special channel —
every node calls ``download_and_extract`` itself (object storage/HTTP is
the rendezvous), which replaces the reference's one-Ray-task-per-node
broadcast.
"""

from __future__ import annotations

import io
import os
import zipfile
from pathlib import Path

from cosmos_curate_tpu.storage.client import read_bytes, write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_HTTP = ("http://", "https://")


def zip_directory_to_file(src_dir: str | Path, zip_path: str | Path) -> int:
    """Deterministic zip of a directory tree (sorted entries, fixed mtimes)
    STREAMED to a file — per-file memory, not per-archive (the reference's
    multipart path exists for the same reason). Returns the zip size."""
    root = Path(src_dir)
    with zipfile.ZipFile(zip_path, "w", zipfile.ZIP_DEFLATED) as zf:
        for f in sorted(root.rglob("*")):
            if f.is_file():
                info = zipfile.ZipInfo(str(f.relative_to(root)))
                with f.open("rb") as src, zf.open(info, "w") as dst:
                    import shutil

                    shutil.copyfileobj(src, dst, length=1 << 20)
    return os.path.getsize(zip_path)


def zip_directory(src_dir: str | Path) -> bytes:
    """In-memory variant for small directories (tests, small artifacts)."""
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".zip") as f:
        zip_directory_to_file(src_dir, f.name)
        f.seek(0)
        return f.read()


def zip_and_upload_directory(src_dir: str | Path, dest: str) -> int:
    """Zip ``src_dir`` and PUT it to ``dest`` (storage path or presigned
    HTTP URL). Returns the zip size in bytes. The archive is staged on
    local disk; only the transport step holds it in memory (for local
    destinations it is an os-level rename, zero extra memory)."""
    import shutil
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".zip")
    os.close(fd)
    try:
        size = zip_directory_to_file(src_dir, tmp)
        if dest.startswith(_HTTP):
            with open(tmp, "rb") as f:
                _http_put(dest, f.read())
        elif "://" not in dest:
            Path(dest).parent.mkdir(parents=True, exist_ok=True)
            shutil.move(tmp, dest)
            tmp = None  # consumed
        else:
            with open(tmp, "rb") as f:
                write_bytes(dest, f.read())
        logger.info("uploaded %s (%d bytes) -> %s", src_dir, size, _redact(dest))
        return size
    finally:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)


def download_and_extract(src: str, dest_dir: str | Path) -> list[str]:
    """GET a zip from a storage path or presigned URL and extract it.

    Zip-slip safe: entries escaping ``dest_dir`` are rejected.
    """
    import shutil

    dest = Path(dest_dir)
    dest.mkdir(parents=True, exist_ok=True)
    extracted: list[str] = []
    if src.startswith(_HTTP) or "://" in src:
        data = _http_get(src) if src.startswith(_HTTP) else read_bytes(src)
        zf_source = io.BytesIO(data)
    else:
        zf_source = src  # local path: zipfile streams from disk
    with zipfile.ZipFile(zf_source) as zf:
        for info in zf.infolist():
            if info.is_dir():
                continue
            target = dest / info.filename
            if not target.resolve().is_relative_to(dest.resolve()):
                raise ValueError(f"zip entry escapes destination: {info.filename!r}")
            target.parent.mkdir(parents=True, exist_ok=True)
            with zf.open(info) as src_f, open(target, "wb") as dst_f:
                shutil.copyfileobj(src_f, dst_f, length=1 << 20)
            extracted.append(str(target))
    logger.info("extracted %d files from %s", len(extracted), _redact(src))
    return extracted


def _http_put(url: str, data: bytes) -> None:
    import urllib.request

    req = urllib.request.Request(url, data=data, method="PUT")
    req.add_header("Content-Type", "application/zip")
    with urllib.request.urlopen(req, timeout=600) as resp:
        if resp.status >= 300:
            raise RuntimeError(f"PUT failed with {resp.status}")


def _http_get(url: str) -> bytes:
    import urllib.request

    with urllib.request.urlopen(url, timeout=600) as resp:
        return resp.read()


def _redact(url: str) -> str:
    """Presigned URLs carry signatures in the query string; never log them."""
    return url.split("?", 1)[0] if url.startswith(_HTTP) else url
