"""SDK-free S3 backend over the REST API (stdlib urllib + SigV4).

Capability twin of the reference's boto3-backed S3 client
(cosmos_curate/core/utils/storage/s3_client.py:56-627): byte reads (full and
ranged), retrying writes, existence probes, paginated ListObjectsV2, and
multipart upload for large objects (the reference leans on boto3's
TransferConfig for the same). Unlike storage/s3.py this backend has **no SDK
dependency**, so it is constructible — and testable against an in-process
fake server (tests/storage/fake_s3.py) — in the zero-egress image.

Endpoint resolution: explicit ``endpoint_url`` (config or
``AWS_ENDPOINT_URL``) uses path-style addressing (MinIO/fake-server
convention); otherwise virtual-hosted AWS endpoints are derived from the
region.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Iterator

from cosmos_curate_tpu.storage.client import ObjectInfo, StorageClient
from cosmos_curate_tpu.storage.retry import (
    chaos_storage_fault,
    is_retryable_status,
    sleep_backoff,
)
from cosmos_curate_tpu.storage.sigv4 import Credentials, payload_hash, sign_request
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MULTIPART_THRESHOLD = 64 * 1024 * 1024
MULTIPART_CHUNK = 32 * 1024 * 1024
_RETRIES = 4


class S3Error(RuntimeError):
    def __init__(self, status: int, body: str, context: str) -> None:
        super().__init__(f"S3 {context} failed: HTTP {status}: {body[:500]}")
        self.status = status


def _split(path: str) -> tuple[str, str]:
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


class S3RestClient(StorageClient):
    def __init__(
        self,
        *,
        access_key_id: str | None = None,
        secret_access_key: str | None = None,
        session_token: str = "",
        region: str | None = None,
        endpoint_url: str | None = None,
    ) -> None:
        from cosmos_curate_tpu.utils.user_config import get_section

        cfg = get_section("s3")
        self._creds = Credentials(
            access_key_id=access_key_id
            or cfg.get("access_key_id")
            or os.environ.get("AWS_ACCESS_KEY_ID", ""),
            secret_access_key=secret_access_key
            or cfg.get("secret_access_key")
            or os.environ.get("AWS_SECRET_ACCESS_KEY", ""),
            session_token=session_token or os.environ.get("AWS_SESSION_TOKEN", ""),
        )
        self._region = (
            region or cfg.get("region") or os.environ.get("AWS_DEFAULT_REGION") or "us-east-1"
        )
        self._endpoint = (
            endpoint_url or cfg.get("endpoint_url") or os.environ.get("AWS_ENDPOINT_URL") or ""
        ).rstrip("/")
        if not self._creds.access_key_id or not self._creds.secret_access_key:
            raise RuntimeError(
                "s3:// access needs credentials: set s3.access_key_id/secret_access_key "
                "in the user config or AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY"
            )

    # -- wire helpers ------------------------------------------------------

    def _url_parts(self, bucket: str, key: str) -> tuple[str, str, str]:
        """(scheme, host, uri-encoded path) — path keeps any prefix carried
        by a custom endpoint (e.g. MinIO behind a reverse-proxy path)."""
        enc_key = urllib.parse.quote(key, safe="/-_.~")
        if self._endpoint:
            u = urllib.parse.urlparse(self._endpoint)
            prefix = u.path.rstrip("/")
            path = f"{prefix}/{bucket}/{enc_key}" if key else f"{prefix}/{bucket}"
            return u.scheme, u.netloc, path
        host = f"{bucket}.s3.{self._region}.amazonaws.com"
        return "https", host, f"/{enc_key}"

    def _request(
        self,
        method: str,
        bucket: str,
        key: str,
        *,
        query: dict[str, str] | None = None,
        data: bytes = b"",
        headers: dict[str, str] | None = None,
        context: str = "",
        retryable: bool = True,
    ) -> tuple[int, bytes, dict[str, str]]:
        query = query or {}
        scheme, host, url_path = self._url_parts(bucket, key)
        signed = sign_request(
            method=method,
            host=host,
            path=url_path,
            query=query,
            headers=headers or {},
            payload_sha256=payload_hash(data),
            creds=self._creds,
            region=self._region,
            )
        qs = urllib.parse.urlencode(sorted(query.items()), quote_via=urllib.parse.quote)
        url = f"{scheme}://{host}{url_path}" + (f"?{qs}" if qs else "")
        last: Exception | None = None
        # empty-body PUT/POST must still send Content-Length: 0; data=None
        # would omit it and some endpoints reject the length-less request
        req_body = data if data or method.upper() in ("PUT", "POST") else None
        for attempt in range(_RETRIES):
            req = urllib.request.Request(url, data=req_body, method=method.upper())
            for k, v in signed.items():
                if k != "host":
                    req.add_header(k, v)
            try:
                chaos_storage_fault()
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return resp.status, resp.read(), dict(resp.headers)
            except urllib.error.HTTPError as e:
                body = e.read()
                if is_retryable_status(e.code) and retryable and attempt + 1 < _RETRIES:
                    last = e
                else:
                    return e.code, body, dict(e.headers or {})
            except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
                if not retryable or attempt + 1 == _RETRIES:
                    raise
                last = e
            sleep_backoff(attempt)
        raise RuntimeError(f"S3 {context or method} exhausted retries: {last}")

    # -- StorageClient -----------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        bucket, key = _split(path)
        status, body, _ = self._request("GET", bucket, key, context=f"get {path}")
        if status == 404:
            # match local-disk semantics so callers' missing-file handling
            # is backend-agnostic
            raise FileNotFoundError(path)
        if status != 200:
            raise S3Error(status, body.decode(errors="replace"), f"get {path}")
        return body

    def read_range(self, path: str, start: int, end: int) -> bytes:
        """Inclusive byte range, reference ranged-read capability."""
        bucket, key = _split(path)
        status, body, _ = self._request(
            "GET", bucket, key, headers={"range": f"bytes={start}-{end}"}, context=f"get {path}"
        )
        if status not in (200, 206):
            raise S3Error(status, body.decode(errors="replace"), f"ranged get {path}")
        if status == 200:
            # endpoint ignored the Range header and sent the whole object
            return body[start : end + 1]
        return body

    def write_bytes(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        if len(data) >= MULTIPART_THRESHOLD:
            self._multipart_upload(bucket, key, data)
            return
        status, body, _ = self._request("PUT", bucket, key, data=data, context=f"put {path}")
        if status not in (200, 201):
            raise S3Error(status, body.decode(errors="replace"), f"put {path}")

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        status, _, _ = self._request("HEAD", bucket, key, context=f"head {path}")
        if status == 200:
            return True
        if status == 404:
            return False
        # auth failures / persistent outages must surface, not read as absent
        raise S3Error(status, "", f"head {path}")

    def size(self, path: str) -> int:
        bucket, key = _split(path)
        status, body, headers = self._request("HEAD", bucket, key, context=f"head {path}")
        if status != 200:
            raise S3Error(status, "", f"head {path}")
        lower = {k.lower(): v for k, v in headers.items()}
        return int(lower.get("content-length", "0"))

    def delete(self, path: str) -> None:
        bucket, key = _split(path)
        status, body, _ = self._request("DELETE", bucket, key, context=f"delete {path}")
        if status not in (200, 204):
            raise S3Error(status, body.decode(errors="replace"), f"delete {path}")

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        bucket, key = _split(prefix)
        token = ""
        while True:
            query = {"list-type": "2", "prefix": key, "max-keys": "1000"}
            if not recursive:
                query["delimiter"] = "/"
            if token:
                query["continuation-token"] = token
            status, body, _ = self._request(
                "GET", bucket, "", query=query, context=f"list {prefix}"
            )
            if status != 200:
                raise S3Error(status, body.decode(errors="replace"), f"list {prefix}")
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for el in root.findall(f"{ns}Contents"):
                k = el.findtext(f"{ns}Key") or ""
                size = int(el.findtext(f"{ns}Size") or 0)
                p = f"s3://{bucket}/{k}"
                if suffixes is None or p.lower().endswith(suffixes):
                    yield ObjectInfo(p, size)
            if (root.findtext(f"{ns}IsTruncated") or "false") != "true":
                return
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            if not token:
                return

    # -- multipart ---------------------------------------------------------

    def _multipart_upload(self, bucket: str, key: str, data: bytes) -> None:
        status, body, _ = self._request(
            "POST", bucket, key, query={"uploads": ""}, context="create multipart"
        )
        if status != 200:
            raise S3Error(status, body.decode(errors="replace"), "create multipart")
        root = ET.fromstring(body)
        ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
        upload_id = root.findtext(f"{ns}UploadId") or ""
        etags: list[str] = []
        try:
            for i in range(0, len(data), MULTIPART_CHUNK):
                part_num = len(etags) + 1
                status, body, headers = self._request(
                    "PUT",
                    bucket,
                    key,
                    query={"partNumber": str(part_num), "uploadId": upload_id},
                    data=data[i : i + MULTIPART_CHUNK],
                    context=f"upload part {part_num}",
                )
                if status != 200:
                    raise S3Error(status, body.decode(errors="replace"), f"part {part_num}")
                lower = {k.lower(): v for k, v in headers.items()}
                etags.append(lower.get("etag", '""').strip('"'))
            parts_xml = "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>\"{e}\"</ETag></Part>"
                for n, e in enumerate(etags, 1)
            )
            payload = (
                f'<CompleteMultipartUpload>{parts_xml}</CompleteMultipartUpload>'.encode()
            )
            status, body, _ = self._request(
                "POST",
                bucket,
                key,
                query={"uploadId": upload_id},
                data=payload,
                context="complete multipart",
            )
            # S3 can return 200 with an <Error> body on complete failures.
            if status != 200 or b"<Error>" in body:
                raise S3Error(status, body.decode(errors="replace"), "complete multipart")
        except Exception:
            self._request(
                "DELETE",
                bucket,
                key,
                query={"uploadId": upload_id},
                context="abort multipart",
                retryable=False,
            )
            raise
