"""GCS backend (requires google-cloud-storage; constructed only when
importable — see storage/client.py gating).

The TPU-native twin of the reference's cloud backends (its S3/Azure pair,
cosmos_curate/core/utils/storage/{s3,azure}_client.py): on GCP TPU fleets
the object store is typically GCS.
"""

from __future__ import annotations

from typing import Iterator

from cosmos_curate_tpu.storage.client import ObjectInfo, StorageClient


def _split(path: str) -> tuple[str, str]:
    rest = path[len("gs://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


class GcsStorageClient(StorageClient):
    def __init__(self, **client_kwargs) -> None:
        from google.cloud import storage

        self._client = storage.Client(**client_kwargs)

    def read_bytes(self, path: str) -> bytes:
        bucket, key = _split(path)
        try:
            return self._client.bucket(bucket).blob(key).download_as_bytes()
        except Exception as e:
            # normalize missing-object to the FileNotFoundError contract
            # (the REST clients already raise it on 404)
            if type(e).__name__ == "NotFound" or getattr(e, "code", None) == 404:
                raise FileNotFoundError(path) from e
            raise

    def write_bytes(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        self._client.bucket(bucket).blob(key).upload_from_string(
            data, content_type="application/octet-stream"
        )

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        return self._client.bucket(bucket).blob(key).exists()

    def delete(self, path: str) -> None:
        bucket, key = _split(path)
        blob = self._client.bucket(bucket).blob(key)
        if blob.exists():
            blob.delete()

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        bucket, key = _split(prefix)
        for blob in self._client.list_blobs(bucket, prefix=key):
            p = f"gs://{bucket}/{blob.name}"
            if suffixes is None or p.lower().endswith(suffixes):
                yield ObjectInfo(p, blob.size or 0)
