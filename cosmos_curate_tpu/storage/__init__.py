from cosmos_curate_tpu.storage.client import (
    StorageClient,
    LocalStorageClient,
    get_storage_client,
    is_remote_path,
    read_bytes,
    write_bytes,
)

__all__ = [
    "LocalStorageClient",
    "StorageClient",
    "get_storage_client",
    "is_remote_path",
    "read_bytes",
    "write_bytes",
]
