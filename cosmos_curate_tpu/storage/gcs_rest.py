"""SDK-free GCS backend over the JSON API (stdlib urllib).

Twin of storage/gcs.py without the google-cloud-storage dependency, so the
``gs://`` scheme works — and is testable against an in-process fake server
(tests/storage/fake_gcs.py) — in the zero-SDK image. Auth is a bearer token
(``GCS_OAUTH_TOKEN`` env or ``gcs.oauth_token`` config); the standard
``STORAGE_EMULATOR_HOST`` convention selects an unauthenticated emulator
endpoint, matching the public GCS client libraries' behavior.

Reference capability: cosmos_curate/core/utils/storage/* cloud backends.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator

from cosmos_curate_tpu.storage.client import ObjectInfo, StorageClient
from cosmos_curate_tpu.storage.retry import (
    chaos_storage_fault,
    is_retryable_status,
    sleep_backoff,
)

_RETRIES = 4


class GcsError(RuntimeError):
    def __init__(self, status: int, body: str, context: str) -> None:
        super().__init__(f"GCS {context} failed: HTTP {status}: {body[:500]}")
        self.status = status


def _split(path: str) -> tuple[str, str]:
    rest = path[len("gs://"):]
    bucket, _, key = rest.partition("/")
    return bucket, key


class GcsRestClient(StorageClient):
    def __init__(self, *, host: str | None = None, token: str | None = None) -> None:
        from cosmos_curate_tpu.utils.user_config import get_section

        cfg = get_section("gcs")
        self._host = (
            host
            or os.environ.get("STORAGE_EMULATOR_HOST")
            or "https://storage.googleapis.com"
        ).rstrip("/")
        if not self._host.startswith("http"):
            self._host = f"http://{self._host}"
        self._token = token or os.environ.get("GCS_OAUTH_TOKEN") or cfg.get("oauth_token") or ""
        emulator = "STORAGE_EMULATOR_HOST" in os.environ or host is not None
        if not self._token and not emulator:
            raise RuntimeError(
                "gs:// access needs an OAuth token (GCS_OAUTH_TOKEN / gcs.oauth_token) "
                "or STORAGE_EMULATOR_HOST"
            )

    def _request(
        self,
        method: str,
        url: str,
        *,
        data: bytes = b"",
        content_type: str = "application/octet-stream",
        context: str = "",
    ) -> tuple[int, bytes]:
        last: Exception | None = None
        # empty-body POST must still send Content-Length: 0 (zero-byte
        # object upload); data=None would omit it
        req_body = data if data or method.upper() == "POST" else None
        for attempt in range(_RETRIES):
            req = urllib.request.Request(url, data=req_body, method=method)
            if self._token:
                req.add_header("authorization", f"Bearer {self._token}")
            if data:
                req.add_header("content-type", content_type)
            try:
                chaos_storage_fault()
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                body = e.read()
                if is_retryable_status(e.code) and attempt + 1 < _RETRIES:
                    last = e
                else:
                    return e.code, body
            except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
                if attempt + 1 == _RETRIES:
                    raise
                last = e
            sleep_backoff(attempt)
        raise RuntimeError(f"GCS {context or method} exhausted retries: {last}")

    def _obj_url(self, bucket: str, key: str, **params: str) -> str:
        enc = urllib.parse.quote(key, safe="")
        qs = urllib.parse.urlencode(params)
        return f"{self._host}/storage/v1/b/{bucket}/o/{enc}" + (f"?{qs}" if qs else "")

    def read_bytes(self, path: str) -> bytes:
        bucket, key = _split(path)
        status, body = self._request(
            "GET", self._obj_url(bucket, key, alt="media"), context=f"get {path}"
        )
        if status == 404:
            # match local-disk semantics so callers' missing-file handling
            # is backend-agnostic
            raise FileNotFoundError(path)
        if status != 200:
            raise GcsError(status, body.decode(errors="replace"), f"get {path}")
        return body

    def write_bytes(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        url = (
            f"{self._host}/upload/storage/v1/b/{bucket}/o?"
            + urllib.parse.urlencode({"uploadType": "media", "name": key})
        )
        status, body = self._request("POST", url, data=data, context=f"put {path}")
        if status != 200:
            raise GcsError(status, body.decode(errors="replace"), f"put {path}")

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        status, body = self._request("GET", self._obj_url(bucket, key), context=f"stat {path}")
        if status == 200:
            return True
        if status == 404:
            return False
        # auth failures / persistent outages must surface, not read as absent
        raise GcsError(status, body.decode(errors="replace"), f"stat {path}")

    def delete(self, path: str) -> None:
        bucket, key = _split(path)
        status, body = self._request(
            "DELETE", self._obj_url(bucket, key), context=f"delete {path}"
        )
        if status not in (200, 204, 404):
            raise GcsError(status, body.decode(errors="replace"), f"delete {path}")

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        bucket, key = _split(prefix)
        token = ""
        while True:
            params = {"prefix": key, "maxResults": "1000"}
            if not recursive:
                params["delimiter"] = "/"
            if token:
                params["pageToken"] = token
            url = f"{self._host}/storage/v1/b/{bucket}/o?" + urllib.parse.urlencode(params)
            status, body = self._request("GET", url, context=f"list {prefix}")
            if status != 200:
                raise GcsError(status, body.decode(errors="replace"), f"list {prefix}")
            payload = json.loads(body or b"{}")
            for item in payload.get("items", []):
                p = f"gs://{bucket}/{item['name']}"
                if suffixes is None or p.lower().endswith(suffixes):
                    yield ObjectInfo(p, int(item.get("size", 0)))
            token = payload.get("nextPageToken", "")
            if not token:
                return
