"""SDK-free Azure Blob backend over the REST API (stdlib urllib).

Capability twin of the reference's azure-sdk client
(cosmos_curate/core/utils/storage/azure_client.py:54-640): byte reads (full
and ranged), retrying writes, existence probes, paginated container listing
with markers, and block-list upload for large blobs (the SDK's
``max_single_put_size``/``max_block_size`` split). No SDK dependency, so the
backend is constructible — and testable against an in-process fake server
(tests/storage/fake_azure.py) — in the zero-egress image.

Auth: Shared Key (storage/azure_shared_key.py) when ``account_key`` is
configured, or a SAS token appended to every request when ``sas_token`` is.

Path model: ``az://container/blob`` with the account from config/env
(``azure.account_name`` / ``AZURE_STORAGE_ACCOUNT``), matching the
reference's AzurePrefix convention.

Endpoint resolution: explicit ``endpoint_url`` (config or
``AZURE_STORAGE_ENDPOINT``) uses Azurite-style path addressing
(``http://host:port/<account>/<container>/<blob>``); otherwise
``https://<account>.blob.core.windows.net``.
"""

from __future__ import annotations

import base64
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Iterator

from cosmos_curate_tpu.storage.azure_shared_key import AzureCredentials, sign_request
from cosmos_curate_tpu.storage.client import ObjectInfo, StorageClient
from cosmos_curate_tpu.storage.retry import (
    chaos_storage_fault,
    is_retryable_status,
    sleep_backoff,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

BLOCK_THRESHOLD = 64 * 1024 * 1024
BLOCK_CHUNK = 32 * 1024 * 1024
_RETRIES = 4


class AzureError(RuntimeError):
    def __init__(self, status: int, body: str, context: str) -> None:
        super().__init__(f"Azure {context} failed: HTTP {status}: {body[:500]}")
        self.status = status


def _split(path: str) -> tuple[str, str]:
    rest = path[len("az://"):]
    container, _, blob = rest.partition("/")
    return container, blob


class AzureRestClient(StorageClient):
    def __init__(
        self,
        *,
        account_name: str | None = None,
        account_key: str | None = None,
        sas_token: str | None = None,
        endpoint_url: str | None = None,
    ) -> None:
        import os

        from cosmos_curate_tpu.utils.user_config import get_section

        cfg = get_section("azure")
        self._account = (
            account_name or cfg.get("account_name") or os.environ.get("AZURE_STORAGE_ACCOUNT", "")
        )
        self._key = (
            account_key or cfg.get("account_key") or os.environ.get("AZURE_STORAGE_KEY", "")
        )
        self._sas = (
            sas_token or cfg.get("sas_token") or os.environ.get("AZURE_STORAGE_SAS_TOKEN", "")
        ).lstrip("?")
        self._endpoint = (
            endpoint_url
            or cfg.get("endpoint_url")
            or os.environ.get("AZURE_STORAGE_ENDPOINT", "")
        ).rstrip("/")
        if not self._account:
            raise RuntimeError(
                "az:// access needs an account: set azure.account_name in the user "
                "config or AZURE_STORAGE_ACCOUNT"
            )
        if not self._key and not self._sas:
            raise RuntimeError(
                "az:// access needs credentials: set azure.account_key or "
                "azure.sas_token (or AZURE_STORAGE_KEY / AZURE_STORAGE_SAS_TOKEN)"
            )

    # -- wire helpers ------------------------------------------------------

    def _url_parts(self, container: str, blob: str) -> tuple[str, str, str]:
        """(scheme, host, uri-encoded path)."""
        enc = urllib.parse.quote(blob, safe="/-_.~")
        if self._endpoint:
            u = urllib.parse.urlparse(self._endpoint)
            prefix = u.path.rstrip("/")
            if not prefix.endswith(f"/{self._account}"):
                prefix = f"{prefix}/{self._account}"
            path = f"{prefix}/{container}" + (f"/{enc}" if blob else "")
            return u.scheme, u.netloc, path
        host = f"{self._account}.blob.core.windows.net"
        return "https", host, f"/{container}" + (f"/{enc}" if blob else "")

    def _request(
        self,
        method: str,
        container: str,
        blob: str,
        *,
        query: dict[str, str] | None = None,
        data: bytes = b"",
        headers: dict[str, str] | None = None,
        context: str = "",
        retryable: bool = True,
    ) -> tuple[int, bytes, dict[str, str]]:
        query = {k.lower(): v for k, v in (query or {}).items()}
        scheme, host, url_path = self._url_parts(container, blob)
        headers = dict(headers or {})
        # empty-body PUT/POST must still send Content-Length: 0 (Azure
        # returns 411 otherwise); data=None would omit it
        req_body = data if data or method.upper() in ("PUT", "POST") else None
        if req_body is not None:
            # urllib injects a default content-type on bodied requests; pin it
            # so the signed and sent values agree.
            headers.setdefault("content-type", "application/octet-stream")
        if self._key:
            headers = sign_request(
                method=method,
                account=self._account,
                path=url_path,
                query=query,
                headers=headers,
                content_length=len(data),
                creds=AzureCredentials(self._account, self._key),
            )
        qs = urllib.parse.urlencode(sorted(query.items()), quote_via=urllib.parse.quote)
        if self._sas and not self._key:
            # SAS is the fallback auth; appending it alongside Shared Key
            # signing would invalidate the signature (canonicalized resource
            # must cover every query parameter)
            qs = f"{qs}&{self._sas}" if qs else self._sas
        url = f"{scheme}://{host}{url_path}" + (f"?{qs}" if qs else "")
        last: Exception | None = None
        for attempt in range(_RETRIES):
            req = urllib.request.Request(url, data=req_body, method=method.upper())
            for k, v in headers.items():
                req.add_header(k, v)
            try:
                chaos_storage_fault()
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return resp.status, resp.read(), dict(resp.headers)
            except urllib.error.HTTPError as e:
                body = e.read()
                if is_retryable_status(e.code) and retryable and attempt + 1 < _RETRIES:
                    last = e
                else:
                    return e.code, body, dict(e.headers or {})
            except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
                if not retryable or attempt + 1 == _RETRIES:
                    raise
                last = e
            sleep_backoff(attempt)
        raise RuntimeError(f"Azure {context or method} exhausted retries: {last}")

    # -- StorageClient -----------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        container, blob = _split(path)
        status, body, _ = self._request("GET", container, blob, context=f"get {path}")
        if status == 404:
            # match local-disk semantics so callers' missing-file handling
            # is backend-agnostic
            raise FileNotFoundError(path)
        if status != 200:
            raise AzureError(status, body.decode(errors="replace"), f"get {path}")
        return body

    def read_range(self, path: str, start: int, end: int) -> bytes:
        """Inclusive byte range."""
        container, blob = _split(path)
        status, body, _ = self._request(
            "GET",
            container,
            blob,
            headers={"range": f"bytes={start}-{end}"},
            context=f"get {path}",
        )
        if status not in (200, 206):
            raise AzureError(status, body.decode(errors="replace"), f"ranged get {path}")
        if status == 200:
            return body[start : end + 1]
        return body

    def write_bytes(self, path: str, data: bytes) -> None:
        container, blob = _split(path)
        if len(data) >= BLOCK_THRESHOLD:
            self._block_upload(container, blob, data)
            return
        status, body, _ = self._request(
            "PUT",
            container,
            blob,
            data=data,
            headers={"x-ms-blob-type": "BlockBlob"},
            context=f"put {path}",
        )
        if status != 201:
            raise AzureError(status, body.decode(errors="replace"), f"put {path}")

    def exists(self, path: str) -> bool:
        container, blob = _split(path)
        status, _, _ = self._request("HEAD", container, blob, context=f"head {path}")
        if status == 200:
            return True
        if status == 404:
            return False
        raise AzureError(status, "", f"head {path}")

    def size(self, path: str) -> int:
        container, blob = _split(path)
        status, _, headers = self._request("HEAD", container, blob, context=f"head {path}")
        if status != 200:
            raise AzureError(status, "", f"head {path}")
        lower = {k.lower(): v for k, v in headers.items()}
        return int(lower.get("content-length", "0"))

    def delete(self, path: str) -> None:
        container, blob = _split(path)
        status, body, _ = self._request("DELETE", container, blob, context=f"delete {path}")
        if status not in (200, 202, 204):
            raise AzureError(status, body.decode(errors="replace"), f"delete {path}")

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        container, blob_prefix = _split(prefix)
        marker = ""
        while True:
            query = {
                "restype": "container",
                "comp": "list",
                "prefix": blob_prefix,
                "maxresults": "1000",
            }
            if not recursive:
                query["delimiter"] = "/"
            if marker:
                query["marker"] = marker
            status, body, _ = self._request(
                "GET", container, "", query=query, context=f"list {prefix}"
            )
            if status != 200:
                raise AzureError(status, body.decode(errors="replace"), f"list {prefix}")
            root = ET.fromstring(body)
            for el in root.iter("Blob"):
                name = el.findtext("Name") or ""
                size = int(el.findtext("Properties/Content-Length") or 0)
                p = f"az://{container}/{name}"
                if suffixes is None or p.lower().endswith(suffixes):
                    yield ObjectInfo(p, size)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return

    # -- block-list upload -------------------------------------------------

    def _block_upload(self, container: str, blob: str, data: bytes) -> None:
        """Put Block per chunk, then commit with Put Block List (the Azure
        analogue of S3 multipart; azure_client.py's SDK does the same split
        above max_single_put_size)."""
        block_ids: list[str] = []
        for i in range(0, len(data), BLOCK_CHUNK):
            bid = base64.b64encode(f"block-{len(block_ids):08d}".encode()).decode()
            status, body, _ = self._request(
                "PUT",
                container,
                blob,
                query={"comp": "block", "blockid": bid},
                data=data[i : i + BLOCK_CHUNK],
                context=f"put block {len(block_ids)}",
            )
            if status != 201:
                raise AzureError(
                    status, body.decode(errors="replace"), f"put block {len(block_ids)}"
                )
            block_ids.append(bid)
        blocks_xml = "".join(f"<Latest>{b}</Latest>" for b in block_ids)
        payload = f'<?xml version="1.0" encoding="utf-8"?><BlockList>{blocks_xml}</BlockList>'.encode()
        status, body, _ = self._request(
            "PUT",
            container,
            blob,
            query={"comp": "blocklist"},
            data=payload,
            context="put block list",
        )
        if status != 201:
            raise AzureError(status, body.decode(errors="replace"), "put block list")
