"""Typed output writers over the storage abstraction.

Equivalent capability of the reference's writer helpers
(cosmos_curate/core/utils/storage/writer_utils.py:62-370): json / jsonl /
text / csv / parquet / pickle, all routed through ``write_bytes`` so they work
against any backend and inherit atomic local writes.
"""

from __future__ import annotations

import csv
import io
import json
import pickle
from typing import Any, Iterable, Mapping

import numpy as np

from cosmos_curate_tpu.storage.client import write_bytes


class _NumpyJSONEncoder(json.JSONEncoder):
    def default(self, o: Any) -> Any:
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        if hasattr(o, "hex") and hasattr(o, "int"):  # uuid.UUID
            return str(o)
        return super().default(o)


def write_json(path: str, obj: Any, *, indent: int | None = 2) -> None:
    write_bytes(path, json.dumps(obj, indent=indent, cls=_NumpyJSONEncoder).encode())


def write_jsonl(path: str, rows: Iterable[Mapping[str, Any]]) -> None:
    buf = io.StringIO()
    for row in rows:
        buf.write(json.dumps(row, cls=_NumpyJSONEncoder))
        buf.write("\n")
    write_bytes(path, buf.getvalue().encode())


def write_text(path: str, text: str) -> None:
    write_bytes(path, text.encode())


def write_csv(path: str, rows: Iterable[Mapping[str, Any]], fieldnames: list[str]) -> None:
    buf = io.StringIO()
    writer = csv.DictWriter(buf, fieldnames=fieldnames)
    writer.writeheader()
    for row in rows:
        writer.writerow(row)
    write_bytes(path, buf.getvalue().encode())


def write_pickle(path: str, obj: Any) -> None:
    write_bytes(path, pickle.dumps(obj, protocol=5))


def write_parquet(path: str, columns: Mapping[str, Any]) -> None:
    """Columnar write via pyarrow (available in this image)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table(dict(columns))
    sink = io.BytesIO()
    pq.write_table(table, sink)
    write_bytes(path, sink.getvalue())


def write_npy(path: str, arr: np.ndarray) -> None:
    sink = io.BytesIO()
    np.save(sink, arr)
    write_bytes(path, sink.getvalue())
