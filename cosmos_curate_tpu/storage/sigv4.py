"""AWS Signature Version 4 request signing (stdlib-only).

Implements the canonical-request / string-to-sign / signing-key derivation
from the public SigV4 spec so the S3 REST backend (storage/s3_rest.py) needs
no SDK. Capability twin of the auth layer boto3 provides for the reference's
S3 client (cosmos_curate/core/utils/storage/s3_client.py:56).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


@dataclass(frozen=True)
class Credentials:
    access_key_id: str
    secret_access_key: str
    session_token: str = ""


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, *, encode_slash: bool) -> str:
    safe = "-_.~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def canonical_query(params: dict[str, str]) -> str:
    pairs = sorted(
        (_uri_encode(k, encode_slash=True), _uri_encode(v, encode_slash=True))
        for k, v in params.items()
    )
    return "&".join(f"{k}={v}" for k, v in pairs)


def sign_request(
    *,
    method: str,
    host: str,
    path: str,
    query: dict[str, str],
    headers: dict[str, str],
    payload_sha256: str,
    creds: Credentials,
    region: str,
    service: str = "s3",
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """Return ``headers`` plus the SigV4 ``Authorization`` (and date/token)
    headers for the described request. ``path`` must already be URI-encoded
    the way it will be sent on the wire."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    out = dict(headers)
    out["host"] = host
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_sha256
    if creds.session_token:
        out["x-amz-security-token"] = creds.session_token

    signed = sorted(k.lower() for k in out)
    canonical_headers = "".join(f"{k}:{str(out[_find(out, k)]).strip()}\n" for k in signed)
    signed_headers = ";".join(signed)

    canonical_request = "\n".join(
        [
            method.upper(),
            path or "/",
            canonical_query(query),
            canonical_headers,
            signed_headers,
            payload_sha256,
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    k_date = _hmac(("AWS4" + creds.secret_access_key).encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()
    out["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={creds.access_key_id}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return out


def _find(d: dict[str, str], lower_key: str) -> str:
    for k in d:
        if k.lower() == lower_key:
            return k
    raise KeyError(lower_key)


def payload_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest() if data else _EMPTY_SHA256
