"""Parquet -> Lance conversion for downstream consumers of the
reference's lance layout.

Equivalent capability of the reference's lance output path
(core/utils/storage/writer_utils.py:176 ``write_lance_fragments`` +
read_write/metadata_writer_stage.py:1090 ``consolidate_lance_fragments``:
per-chunk fragments staged with JSON sidecars, consolidated into one
committed dataset under ``iv2_embd_lance`` / ``lance/v0``).

This image cannot ship the ``lance`` wheel (zero egress, not baked in),
and the Lance v2 container format is a versioned binary spec that cannot
be honestly validated without the reader — so instead of an unverifiable
from-scratch writer, this module is the documented CONVERSION TOOL: our
pipelines emit parquet (readable everywhere), and any environment with
``pip install pylance`` turns those outputs into a real committed lance
dataset with the same columns, via this module or the
``export-lance`` CLI. The conversion logic (directory walk, table
assembly, embedding list-column handling) is testable without lance; the
final ``lance.write_dataset`` call is the only gated line.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def load_embedding_tables(src: str | Path) -> dict[str, Any]:
    """Read every embeddings parquet under ``src`` into one pyarrow table
    per model subdirectory (the layout ``ClipWriterStage`` emits:
    ``embeddings/<model>/<chunk>.parquet`` with clip_uuid + embedding)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    src = Path(src)
    tables: dict[str, Any] = {}
    groups: dict[str, list] = {}
    if any(src.glob("*.parquet")):  # src IS one model directory
        groups[src.name] = sorted(src.glob("*.parquet"))
    else:
        for sub in sorted(p for p in src.iterdir() if p.is_dir()):
            files = sorted(sub.glob("*.parquet"))
            if files:
                groups[sub.name] = files
    for model, files in groups.items():
        tables[model] = pa.concat_tables([pq.read_table(f) for f in files])
    return tables


def export_parquet_to_lance(
    src: str | Path, dest: str | Path, *, mode: str = "create"
) -> dict[str, int]:
    """Convert pipeline embeddings parquet output into lance dataset(s).

    ``src``: the run's ``embeddings/`` dir (or one model subdir).
    ``dest``: output root; each model becomes ``<dest>/<model>.lance``.
    Returns {dataset_path: num_rows}. Requires the ``lance`` package
    (``pip install pylance``) — raises with that guidance otherwise.
    """
    tables = load_embedding_tables(src)
    if not tables:
        raise FileNotFoundError(f"no embeddings parquet found under {src}")
    try:
        import lance
    except ImportError as e:  # pragma: no cover - exercised via fake module
        raise RuntimeError(
            "lance is not installed in this environment; run "
            "`pip install pylance` where the conversion should happen "
            "(the pipeline's parquet output is self-contained until then)"
        ) from e
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    written: dict[str, int] = {}
    for model, table in tables.items():
        uri = str(dest / f"{model}.lance")
        lance.write_dataset(table, uri, mode=mode)
        written[uri] = table.num_rows
        logger.info("wrote %d rows to %s", table.num_rows, uri)
    return written
