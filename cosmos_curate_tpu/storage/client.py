"""Uniform storage abstraction over local disk and object stores.

Equivalent capability of the reference's storage layer
(cosmos_curate/core/utils/storage/storage_client.py:39-288,
storage_utils.py:39-1170): one path model covering local paths and
``s3://`` / ``gs://`` / ``az://`` URLs, a `StorageClient` per backend, and
module-level convenience helpers that dispatch on the path.

Cloud backends are **gated**: boto3 / google-cloud-storage are not in this
image, so `S3StorageClient` / `GcsStorageClient` raise a clear error at
construction unless their SDK is importable. The interface (and all callers)
are written against `StorageClient`, so enabling a backend is dependency-only.
"""

from __future__ import annotations

import abc
import contextvars
import os
import queue
import shutil
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterator

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_REMOTE_SCHEMES = ("s3://", "gs://", "az://")


def is_remote_path(path: str | os.PathLike[str]) -> bool:
    return str(path).startswith(_REMOTE_SCHEMES)


def relative_to_prefix(path: str, prefix: str) -> str | None:
    """``path`` relative to ``prefix``, or None when not under it.

    Exact string prefix for remote URLs; local paths are normalized first
    (a listing of ``./videos`` yields ``videos/...`` entries — a naive
    startswith would misattribute every file)."""
    base = prefix.rstrip("/")
    if path.startswith(base + "/"):
        return path[len(base) + 1:]
    if is_remote_path(prefix):
        return None
    norm_base = os.path.normpath(base)
    norm_path = os.path.normpath(path)
    if norm_path.startswith(norm_base + os.sep):
        return norm_path[len(norm_base) + 1:]
    return None


@dataclass(frozen=True)
class ObjectInfo:
    path: str
    size: int


class StorageClient(abc.ABC):
    """Backend-agnostic byte-level storage operations."""

    @abc.abstractmethod
    def read_bytes(self, path: str) -> bytes: ...

    @abc.abstractmethod
    def write_bytes(self, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def exists(self, path: str) -> bool: ...

    @abc.abstractmethod
    def delete(self, path: str) -> None: ...

    @abc.abstractmethod
    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]: ...

    def list_relative(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None
    ) -> list[str]:
        """Paths under ``prefix`` relative to it (reference
        ``get_files_relative``)."""
        out = []
        for info in self.list_files(prefix, suffixes=suffixes):
            rel = relative_to_prefix(info.path, prefix)
            out.append(rel if rel is not None else info.path)
        return out


class LocalStorageClient(StorageClient):
    def read_bytes(self, path: str) -> bytes:
        return Path(path).read_bytes()

    def write_bytes(self, path: str, data: bytes) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_name(p.name + ".tmp")
        tmp.write_bytes(data)
        tmp.replace(p)  # atomic on POSIX

    def exists(self, path: str) -> bool:
        return Path(path).exists()

    def delete(self, path: str) -> None:
        p = Path(path)
        if p.is_dir():
            shutil.rmtree(p)
        elif p.exists():
            p.unlink()

    def list_files(
        self, prefix: str, *, suffixes: tuple[str, ...] | None = None, recursive: bool = True
    ) -> Iterator[ObjectInfo]:
        base = Path(prefix)
        if base.is_file():
            yield ObjectInfo(str(base), base.stat().st_size)
            return
        if not base.exists():
            return
        pattern = "**/*" if recursive else "*"
        for p in sorted(base.glob(pattern)):
            if p.is_file() and (suffixes is None or p.suffix.lower() in suffixes):
                yield ObjectInfo(str(p), p.stat().st_size)


class _GatedClient(StorageClient):
    """Raises a clear error for backends that can't be constructed here."""

    scheme = ""
    reason = ""

    def __init__(self) -> None:
        raise RuntimeError(f"{self.scheme} storage unavailable: {self.reason}")

    def read_bytes(self, path): ...  # pragma: no cover
    def write_bytes(self, path, data): ...  # pragma: no cover
    def exists(self, path): ...  # pragma: no cover
    def delete(self, path): ...  # pragma: no cover
    def list_files(self, prefix, *, suffixes=None, recursive=True): ...  # pragma: no cover


def _make_s3_client() -> StorageClient:
    try:
        import boto3  # noqa: F401
    except ImportError:
        # SDK-free REST backend (SigV4 over urllib) — constructible whenever
        # credentials are configured, so s3:// works in the zero-SDK image.
        from cosmos_curate_tpu.storage.s3_rest import S3RestClient

        try:
            return S3RestClient()
        except RuntimeError as e:
            class S3Gated(_GatedClient):
                scheme, reason = "s3://", f"{e} (installing boto3 also works)"

            return S3Gated()
    from cosmos_curate_tpu.storage.s3 import S3StorageClient

    return S3StorageClient()


def _make_gcs_client() -> StorageClient:
    try:
        import google.cloud.storage  # noqa: F401
    except ImportError:
        from cosmos_curate_tpu.storage.gcs_rest import GcsRestClient

        try:
            return GcsRestClient()
        except RuntimeError as e:
            class GcsGated(_GatedClient):
                scheme, reason = "gs://", f"{e} (installing google-cloud-storage also works)"

            return GcsGated()
    from cosmos_curate_tpu.storage.gcs import GcsStorageClient

    return GcsStorageClient()


def _make_azure_client() -> StorageClient:
    # No SDK path: the REST backend (Shared Key / SAS over urllib) IS the
    # Azure client in this build.
    from cosmos_curate_tpu.storage.azure_rest import AzureRestClient

    try:
        return AzureRestClient()
    except RuntimeError as e:
        class AzureGated(_GatedClient):
            scheme, reason = "az://", str(e)

        return AzureGated()


_LOCAL = LocalStorageClient()


def get_storage_client(path: str | os.PathLike[str]) -> StorageClient:
    s = str(path)
    if s.startswith("s3://"):
        return _make_s3_client()
    if s.startswith("gs://"):
        return _make_gcs_client()
    if s.startswith("az://"):
        return _make_azure_client()
    return _LOCAL


def backend_name(path: str | os.PathLike[str]) -> str:
    s = str(path)
    for scheme in _REMOTE_SCHEMES:
        if s.startswith(scheme):
            return scheme[:-3]  # "s3://" -> "s3"
    return "local"


def read_bytes(path: str | os.PathLike[str]) -> bytes:
    """Read with one trace span per request (backend/path/bytes attributes;
    the backends' retry loops annotate ``attempt`` onto it via
    storage/retry.py). Zero-cost when tracing is off."""
    from cosmos_curate_tpu.observability.tracing import traced_span

    p = str(path)
    with traced_span("storage.read", backend=backend_name(p), path=p) as span:
        data = get_storage_client(p).read_bytes(p)
        span.set_attribute("bytes", len(data))
        return data


def write_bytes(path: str | os.PathLike[str], data: bytes) -> None:
    """Write with one trace span per request (see :func:`read_bytes`)."""
    from cosmos_curate_tpu.observability.tracing import traced_span

    p = str(path)
    with traced_span(
        "storage.write", backend=backend_name(p), path=p, bytes=len(data)
    ):
        get_storage_client(p).write_bytes(p, data)


class BackgroundUploader:
    """Queue writes to a background thread so the hot loop never blocks on
    storage (reference ``BackgroundUploader``, storage_client.py). Each
    write runs under the SUBMITTER's contextvars context, so its storage
    span parents onto the submitting stage's trace instead of fragmenting."""

    def __init__(self, max_queue: int = 64) -> None:
        self._q: queue.Queue[tuple[str, bytes, Any] | None] = queue.Queue(
            maxsize=max_queue
        )
        self._errors: list[tuple[str, Exception]] = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            path, data, ctx = item
            try:
                ctx.run(write_bytes, path, data)
            except Exception as e:
                logger.exception("background upload failed: %s", path)
                self._errors.append((path, e))

    def submit(self, path: str, data: bytes) -> None:
        self._q.put((path, data, contextvars.copy_context()))

    def close(self) -> list[tuple[str, Exception]]:
        """Drain, stop, and return any failures."""
        self._q.put(None)
        self._thread.join()
        return self._errors
