"""Azure Storage Shared Key request signing (stdlib only).

Implements the Blob-service Shared Key scheme (the auth the reference's
azure SDK client uses under the hood, cosmos_curate/core/utils/storage/
azure_client.py:54-640): an HMAC-SHA256 over a canonicalized request string,
keyed by the base64-decoded account key, carried as
``Authorization: SharedKey <account>:<signature>``.

Spec shape (the 2015-02-21+ Blob string-to-sign):

    VERB \n Content-Encoding \n Content-Language \n Content-Length \n
    Content-MD5 \n Content-Type \n Date \n If-Modified-Since \n If-Match \n
    If-None-Match \n If-Unmodified-Since \n Range \n
    CanonicalizedHeaders CanonicalizedResource

where Content-Length is the empty string when zero, CanonicalizedHeaders is
every ``x-ms-*`` header lowercased/sorted as ``name:value\n``, and
CanonicalizedResource is ``/<account><url path>`` followed by each query
parameter (lowercased, sorted) as ``\n<name>:<value>``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
from dataclasses import dataclass
from email.utils import formatdate

API_VERSION = "2021-08-06"


@dataclass(frozen=True)
class AzureCredentials:
    account_name: str
    account_key: str  # base64-encoded, as the portal hands it out


def rfc1123_now() -> str:
    return formatdate(usegmt=True)


def string_to_sign(
    *,
    method: str,
    account: str,
    path: str,
    query: dict[str, str],
    headers: dict[str, str],
    content_length: int,
) -> str:
    low = {k.lower(): v.strip() for k, v in headers.items()}
    ms_headers = "".join(
        f"{k}:{low[k]}\n" for k in sorted(low) if k.startswith("x-ms-")
    )
    resource = f"/{account}{path}"
    lowq = {k.lower(): v for k, v in query.items()}
    for name in sorted(lowq):
        resource += f"\n{name}:{lowq[name]}"
    return "\n".join(
        [
            method.upper(),
            low.get("content-encoding", ""),
            low.get("content-language", ""),
            str(content_length) if content_length else "",
            low.get("content-md5", ""),
            low.get("content-type", ""),
            "",  # Date — always empty: x-ms-date is set instead
            low.get("if-modified-since", ""),
            low.get("if-match", ""),
            low.get("if-none-match", ""),
            low.get("if-unmodified-since", ""),
            low.get("range", ""),
        ]
    ) + "\n" + ms_headers + resource


def sign_request(
    *,
    method: str,
    account: str,
    path: str,
    query: dict[str, str],
    headers: dict[str, str],
    content_length: int,
    creds: AzureCredentials,
) -> dict[str, str]:
    """Return headers with ``x-ms-date``/``x-ms-version``/``Authorization``
    added. ``path`` is the URL path as sent on the wire (including any
    emulator account prefix); the canonicalized resource prepends the account
    name per spec."""
    out = dict(headers)
    out.setdefault("x-ms-date", rfc1123_now())
    out.setdefault("x-ms-version", API_VERSION)
    sts = string_to_sign(
        method=method,
        account=account,
        path=path,
        query=query,
        headers=out,
        content_length=content_length,
    )
    key = base64.b64decode(creds.account_key)
    sig = base64.b64encode(hmac.new(key, sts.encode(), hashlib.sha256).digest()).decode()
    out["Authorization"] = f"SharedKey {creds.account_name}:{sig}"
    return out
