"""Shared HTTP retry policy for the storage backends.

One place for the backoff schedule and the retryable-status set that were
previously copy-pasted across ``s3_rest.py``, ``azure_rest.py``,
``gcs_rest.py`` and ``zip_transport.py`` (each with drifting behavior:
S3/Azure failed fast on HTTP 429 — the one status that explicitly asks
for a retry).

Backoff is exponential with **full jitter** (AWS architecture-blog
recipe): ``sleep ~ U(0, min(cap, base * 2**attempt))``. Without jitter a
fleet of workers that all saw the same outage retries in lockstep and
re-creates the thundering herd every ``base * 2**k`` seconds; full jitter
spreads the herd across the whole window.

The chaos harness's ``storage.request`` site lives in
:func:`chaos_storage_fault` so every backend inherits fault injection by
calling it at the top of its request attempt loop (a no-op single check
when chaos is disarmed).
"""

from __future__ import annotations

import random
import time

from cosmos_curate_tpu import chaos

# 429 (throttling) and the transient 5xx family. 501/505 etc. are
# deterministic and excluded — retrying them only delays the error.
RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})

DEFAULT_BASE_S = 0.2
DEFAULT_CAP_S = 5.0


def is_retryable_status(status: int) -> bool:
    return status in RETRYABLE_STATUSES


def backoff_s(
    attempt: int,
    *,
    base: float = DEFAULT_BASE_S,
    cap: float = DEFAULT_CAP_S,
    rng: random.Random | None = None,
) -> float:
    """Full-jitter backoff for the ``attempt``-th failure (0-based)."""
    ceiling = min(cap, base * (2.0**attempt))
    return (rng or random).uniform(0.0, ceiling)


def sleep_backoff(
    attempt: int,
    *,
    base: float = DEFAULT_BASE_S,
    cap: float = DEFAULT_CAP_S,
    rng: random.Random | None = None,
) -> float:
    """Sleep the jittered backoff; returns the slept duration (for logs).

    Also annotates the enclosing ``storage.*`` trace span (the one the
    client layer opened) with the retry count and summed backoff, so a slow
    storage span is attributable to retries vs a slow backend. Non-storage
    callers of this helper (API caption, state db) leave their ambient
    stage spans untouched — stamping retry attributes on an unrelated span
    would misattribute the wait."""
    d = backoff_s(attempt, base=base, cap=cap, rng=rng)
    from cosmos_curate_tpu.observability.tracing import current_span

    span = current_span()
    if span is not None and span.name.startswith("storage."):
        span.set_attribute("attempt", attempt + 2)  # the one about to run
        span.set_attribute(
            "backoff_s", round(float(span.attributes.get("backoff_s", 0.0)) + d, 4)
        )
    time.sleep(d)
    return d


def chaos_storage_fault() -> None:
    """The storage backends' shared injection site: an armed
    ``storage.request`` rule raises :class:`~cosmos_curate_tpu.chaos.InjectedFault`
    (a ``ConnectionError``), which the callers' attempt loops treat exactly
    like a real network failure/timeout — retried with backoff, surfaced
    after the budget."""
    chaos.fire(chaos.SITE_STORAGE_REQUEST)
