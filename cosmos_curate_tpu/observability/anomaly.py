"""Stall/anomaly detector for the live ops plane.

The deep observability built through PRs 4–14 (flight recorder, dispatch
and flow aggregates, caption phases) only materializes at run finalize —
useless for telling a healthy slow job from a silently wedged one while it
runs. This module is the live half: a detector that evaluates SUCCESSIVE
live-status snapshots (observability/live_status.py) and emits structured
anomaly events the moment a run starts misbehaving, long before any
deadline kill or operator `kill -9`.

Anomaly kinds (each tunable via :class:`AnomalyConfig` / ``CURATE_ANOMALY_*``
env knobs):

- ``stuck_batch`` — an in-flight batch's age exceeds
  ``max(stuck_min_age_s, stuck_factor × stage p99 batch seconds)``. This is
  the detection-beats-the-timeout signal: a chaos ``worker.batch.hang``
  injection must produce this event BEFORE ``batch_timeout_s`` SIGKILLs the
  worker (scripts/run_chaos_checks.sh closes that loop).
- ``starved_stage`` — a started stage sits at busy≈0 with an empty input
  queue while an EARLIER stage's queue is full: work exists upstream but is
  not flowing (wedged producer, dead pool, routing bug).
- ``dispatch_gap_spike`` — a device stage's dispatch-gap fraction over the
  last snapshot window exceeds the threshold: the host stopped keeping the
  device fed mid-run (GC storm, input starvation, fetch stall).
- ``heartbeat_degraded`` — a node's heartbeat age crossed the degraded
  threshold but the failure detector has not (yet) declared it dead: the
  early warning before remote_plane's deadline fires.
- ``throughput_declining`` — completed-batches/s over the trend window fell
  below ``throughput_drop_frac`` of its earlier peak: the run is slowing
  down without any single batch being stuck.

Every verdict is emitted once at ONSET (keyed, so a stuck batch is one
event, not one per tick) into four sinks at once: a trace span event on the
ambient run span (tracing.add_span_event), the
``pipeline_anomalies_total{stage,kind}`` counter, the bounded stage_timer
anomaly aggregate (which the flight recorder snapshots into
run_report.json's ``anomalies`` section), and the snapshot itself (which
``/v1/jobs/<id>/status`` and `cosmos-curate-tpu top` serve live; the job
service additionally journals them per job).

Pure over snapshots: feed :meth:`AnomalyDetector.observe` dicts and it
returns the new onsets — trivially unit-testable from synthetic sequences
(tests/observability/test_anomaly.py).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class AnomalyConfig:
    """Detector tuning. Defaults are sized so a few-second snapshot cadence
    flags a wedged batch within ~2 ticks while staying quiet on the bursty
    stage timings real pipelines have (cold compiles, first-batch setup)."""

    # stuck_batch: age > max(min_age, factor * stage p99); p99 falls back to
    # min_age when the stage has no completed batches yet (cold start must
    # not page on the first slow compile)
    stuck_min_age_s: float = 10.0
    stuck_factor: float = 5.0
    # starved_stage: busy_frac <= starved_busy_frac with own queue empty
    # while an earlier stage queues >= starved_queue_depth
    starved_busy_frac: float = 0.05
    starved_queue_depth: int = 8
    # dispatch_gap_spike: delta gap/(gap+compute) over the last window
    gap_frac_threshold: float = 0.75
    gap_min_dispatches: int = 8
    # heartbeat_degraded: node silent for this long (should sit below the
    # failure detector's declare-dead deadline, default 15 s)
    heartbeat_degraded_s: float = 10.0
    # throughput_declining: rate over the newest HALF of the trend window
    # fell below drop_frac * the earlier half's rate
    trend_window: int = 5
    trend_drop_frac: float = 0.3
    trend_min_rate: float = 0.2  # batches/s below which the trend is noise
    # flap suppression: starved_stage / throughput_declining must hold for
    # this many CONSECUTIVE snapshots before onset — batchy pipelines
    # legitimately idle a stage (or complete nothing) for one tick, and
    # pipeline warmup looks exactly like starvation for the first window
    persistence: int = 2

    @classmethod
    def from_env(cls) -> "AnomalyConfig":
        return cls(
            stuck_min_age_s=_env_f("CURATE_ANOMALY_STUCK_MIN_AGE_S", cls.stuck_min_age_s),
            stuck_factor=_env_f("CURATE_ANOMALY_STUCK_FACTOR", cls.stuck_factor),
            starved_busy_frac=_env_f(
                "CURATE_ANOMALY_STARVED_BUSY_FRAC", cls.starved_busy_frac
            ),
            starved_queue_depth=int(
                _env_f("CURATE_ANOMALY_STARVED_QUEUE_DEPTH", cls.starved_queue_depth)
            ),
            gap_frac_threshold=_env_f(
                "CURATE_ANOMALY_GAP_FRAC", cls.gap_frac_threshold
            ),
            heartbeat_degraded_s=_env_f(
                "CURATE_ANOMALY_HEARTBEAT_S", cls.heartbeat_degraded_s
            ),
            trend_drop_frac=_env_f(
                "CURATE_ANOMALY_TREND_DROP_FRAC", cls.trend_drop_frac
            ),
        )


# kinds that must HOLD for `persistence` consecutive snapshots before
# onset (flap suppression); the others carry intrinsic hysteresis in their
# thresholds and should fire on first observation
_PERSIST_KINDS = frozenset({"starved_stage", "throughput_declining"})


class AnomalyDetector:
    """Evaluates successive live-status snapshots; emits onsets once.

    Not thread-safe by itself: one publisher (runner loop) drives it.
    ``emit=False`` turns it into a pure evaluator (unit tests)."""

    def __init__(self, config: AnomalyConfig | None = None, *, emit: bool = True) -> None:
        self.config = config or AnomalyConfig.from_env()
        self.emit = emit
        # (kind, stage, subject) of conditions currently holding: an
        # anomaly re-emits only after its condition clears and recurs
        self._active: set[tuple] = set()
        # key -> consecutive snapshots a _PERSIST_KINDS condition has held
        self._pending: dict[tuple, int] = {}
        self._prev: dict | None = None
        # (ts, total completed batches) ring for the throughput trend
        self._trend: list[tuple[float, float]] = []
        # bounded tail of RECENT onsets (old ones roll off — a long run's
        # late anomalies are exactly what must stay visible) + the
        # monotonic total that snapshot readers key deltas on
        from collections import deque

        self.emitted: "deque[dict]" = deque(maxlen=self._EMITTED_CAP)
        self.emitted_total = 0

    _EMITTED_CAP = 256

    # ------------------------------------------------------------------
    def observe(self, snapshot: dict) -> list[dict]:
        """Evaluate one snapshot against the detector's history. Returns the
        NEW onsets (conditions that were not active last tick) as structured
        events; resolved conditions re-arm silently."""
        ts = snapshot.get("ts")
        now = float(ts) if ts is not None else time.time()  # ts=0.0 is a time
        raw: dict[tuple, dict] = {}  # conditions holding THIS tick
        stages = snapshot.get("stages") or {}
        stage_list = list(stages.items())
        for name, st in stage_list:
            self._check_stuck(now, name, st, raw)
        self._check_starved(stage_list, raw)
        self._check_gap(snapshot, raw)
        self._check_heartbeats(snapshot, raw)
        self._check_trend(now, stage_list, raw)
        self._prev = snapshot
        # flap suppression: persisted kinds only count as present once
        # they held `persistence` consecutive snapshots
        found: dict[tuple, dict] = {}
        for key, ev in raw.items():
            if key[0] in _PERSIST_KINDS:
                held = self._pending.get(key, 0) + 1
                self._pending[key] = held
                if held < max(1, self.config.persistence):
                    continue
            found[key] = ev
        for key in [k for k in self._pending if k not in raw]:
            del self._pending[key]
        onsets = [ev for key, ev in found.items() if key not in self._active]
        self._active = set(found)
        for ev in onsets:
            self._record(ev)
        return onsets

    # ------------------------------------------------------------------
    def _check_stuck(self, now: float, name: str, st: dict, found: dict) -> None:
        cfg = self.config
        p99 = float(st.get("p99_s") or 0.0)
        threshold = max(cfg.stuck_min_age_s, cfg.stuck_factor * p99)
        for b in st.get("inflight") or ():
            age = float(b.get("age_s") or 0.0)
            if age <= threshold:
                continue
            key = ("stuck_batch", name, b.get("batch_id"))
            found[key] = {
                "kind": "stuck_batch",
                "stage": name,
                "batch_id": b.get("batch_id"),
                "age_s": round(age, 3),
                "threshold_s": round(threshold, 3),
                "stage_p99_s": round(p99, 3),
                "worker": b.get("worker"),
                "detail": (
                    f"batch {b.get('batch_id')} in flight {age:.1f}s "
                    f"(> {threshold:.1f}s = max(min_age, "
                    f"{cfg.stuck_factor:g}×p99 {p99:.2f}s))"
                ),
            }

    def _check_starved(self, stage_list: list, found: dict) -> None:
        cfg = self.config
        for i, (name, st) in enumerate(stage_list):
            if i == 0 or not st.get("workers"):
                continue
            if st.get("finished"):
                continue
            if not int(st.get("dispatched") or 0):
                # never had flow: that's pipeline warmup (first upstream
                # batch still cooking), not flow that STOPPED — the stuck/
                # trend checks cover a pipeline wedged from the start
                continue
            if float(st.get("busy_frac") or 0.0) > cfg.starved_busy_frac:
                continue
            if int(st.get("queue_depth") or 0) > 0 or st.get("inflight"):
                continue
            blocked = [
                up
                for up, up_st in stage_list[:i]
                if int(up_st.get("queue_depth") or 0) >= cfg.starved_queue_depth
            ]
            if not blocked:
                continue
            key = ("starved_stage", name, None)
            found[key] = {
                "kind": "starved_stage",
                "stage": name,
                "upstream": blocked[-1],
                "upstream_queue_depth": int(
                    dict(stage_list)[blocked[-1]].get("queue_depth") or 0
                ),
                "detail": (
                    f"stage idle (busy≈0, empty queue) while upstream "
                    f"{blocked[-1]} queues "
                    f"{dict(stage_list)[blocked[-1]].get('queue_depth')} tasks"
                ),
            }

    def _check_gap(self, snapshot: dict, found: dict) -> None:
        """Dispatch-gap spike over the DELTA between snapshots — the
        cumulative gap_frac in the aggregate hides a mid-run stall."""
        cfg = self.config
        cur = snapshot.get("dispatch") or {}
        prev = (self._prev or {}).get("dispatch") or {}
        for name, agg in cur.items():
            p = prev.get(name) or {}
            d_n = int(agg.get("dispatches", 0)) - int(p.get("dispatches", 0))
            if d_n < cfg.gap_min_dispatches:
                continue
            d_gap = float(agg.get("gap_s", 0.0)) - float(p.get("gap_s", 0.0))
            d_busy = d_gap + float(agg.get("compute_s", 0.0)) - float(
                p.get("compute_s", 0.0)
            )
            if d_busy <= 0:
                continue
            frac = d_gap / d_busy
            if frac <= cfg.gap_frac_threshold:
                continue
            key = ("dispatch_gap_spike", name, None)
            found[key] = {
                "kind": "dispatch_gap_spike",
                "stage": name,
                "window_gap_frac": round(frac, 4),
                "window_dispatches": d_n,
                "detail": (
                    f"device idle {frac:.0%} of the last {d_n} dispatches "
                    f"(> {cfg.gap_frac_threshold:.0%}) — host stopped "
                    f"feeding the device"
                ),
            }

    def _check_heartbeats(self, snapshot: dict, found: dict) -> None:
        cfg = self.config
        for node, info in (snapshot.get("nodes") or {}).items():
            age = float(info.get("heartbeat_age_s") or 0.0)
            if age <= cfg.heartbeat_degraded_s:
                continue
            key = ("heartbeat_degraded", node, None)
            found[key] = {
                "kind": "heartbeat_degraded",
                "stage": node,  # node rides the stage label for the counter
                "node": node,
                "heartbeat_age_s": round(age, 3),
                "detail": (
                    f"node {node} silent {age:.1f}s "
                    f"(> {cfg.heartbeat_degraded_s:.1f}s; failure detector "
                    f"declares dead at its own deadline)"
                ),
            }

    def _check_trend(self, now: float, stage_list: list, found: dict) -> None:
        cfg = self.config
        total = sum(float(st.get("completed") or 0) for _, st in stage_list)
        self._trend.append((now, total))
        if len(self._trend) > cfg.trend_window:
            self._trend = self._trend[-cfg.trend_window :]
        if len(self._trend) < cfg.trend_window:
            return
        # half-window rates, not per-tick deltas: batchy pipelines complete
        # nothing for one snapshot all the time — the signal is the NEWER
        # half of the window slowing against the older half
        mid = len(self._trend) // 2
        t0, c0 = self._trend[0]
        tm, cm = self._trend[mid]
        t1, c1 = self._trend[-1]
        early = (cm - c0) / (tm - t0) if tm > t0 else 0.0
        late = (c1 - cm) / (t1 - tm) if t1 > tm else 0.0
        if early < cfg.trend_min_rate:
            return  # run is idling or tiny; a trend over noise is noise
        if late >= cfg.trend_drop_frac * early:
            return
        key = ("throughput_declining", "_run", None)
        found[key] = {
            "kind": "throughput_declining",
            "stage": "_run",
            "rate": round(late, 4),
            "peak_rate": round(early, 4),
            "detail": (
                f"completed-batch rate fell to {late:.2f}/s from "
                f"{early:.2f}/s (< {cfg.trend_drop_frac:.0%} of the earlier "
                f"window)"
            ),
        }

    # ------------------------------------------------------------------
    def _record(self, ev: dict) -> None:
        ev.setdefault("ts", time.time())
        self.emitted.append(ev)  # deque: oldest roll off past the cap
        self.emitted_total += 1
        if not self.emit:
            return
        logger.warning("anomaly %s at %s: %s", ev["kind"], ev["stage"], ev["detail"])
        try:
            from cosmos_curate_tpu.observability.stage_timer import record_anomaly

            record_anomaly(ev)
        except Exception:
            pass
        try:
            from cosmos_curate_tpu.observability.tracing import add_span_event

            add_span_event(
                f"anomaly.{ev['kind']}",
                **{k: v for k, v in ev.items() if k not in ("kind", "ts")},
            )
        except Exception:
            pass
