"""Post-run artifact collection.

Equivalent capability of the reference's artifact transport
(cosmos_curate/core/utils/artifacts/ — ``RayFileTransport`` fan-in +
``ArtifactDelivery`` 3-phase staging/collect/upload, ARCHITECTURE.md:138-171):
profiling and trace artifacts produced by worker processes land in
node-local staging dirs; after the run they are swept into the run's output
prefix through the storage layer (local or remote). Multi-node runs sweep
per node — every node pushes its own staging dir to the shared prefix, so
no cross-node fan-in channel is needed (object storage is the rendezvous).
"""

from __future__ import annotations

import os
from pathlib import Path

from cosmos_curate_tpu.storage.client import write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

def collect_artifacts(
    output_path: str,
    *,
    staging_dirs: tuple[str, ...] | None = None,
    node_tag: str | None = None,
    cleanup: bool = True,
) -> int:
    """Sweep staged artifacts into ``<output>/profile/collected/<node>/``.

    Returns the number of files collected. Local-path outputs get real file
    copies; remote outputs (s3://, gs://) upload through the storage layer.
    """
    if staging_dirs is None:
        # this run's worker trace staging only (per-run dir: concurrent
        # pipelines must not sweep each other's files)
        from cosmos_curate_tpu.observability.tracing import default_staging_dir

        staging_dirs = (default_staging_dir(),)
    tag = node_tag or os.environ.get("CURATE_NODE_RANK", "0")
    dest_root = f"{output_path.rstrip('/')}/profile/collected/node{tag}"
    n = 0
    for staging in staging_dirs:
        root = Path(staging)
        if not root.is_dir():
            continue
        for f in sorted(root.rglob("*")):
            if not f.is_file():
                continue
            rel = f.relative_to(root)
            try:
                write_bytes(f"{dest_root}/{root.name}/{rel}", f.read_bytes())
                n += 1
                if cleanup:
                    f.unlink()
            except Exception as e:
                logger.warning("artifact collection failed for %s: %s", f, e)
    if n:
        logger.info("collected %d artifacts into %s", n, dest_root)
    return n
