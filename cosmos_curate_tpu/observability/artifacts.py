"""Crash-safe cross-node artifact collection & delivery.

Equivalent capability of the reference's artifact transport
(cosmos_curate/core/utils/artifacts/collector.py:604 ``RayFileTransport`` —
streaming chunk fan-in with double-layer backpressure — and
delivery.py:420 ``ArtifactDelivery`` 3-phase staging/collect/finalize).
Workers write to node-local staging dirs during the run; artifacts survive
SIGKILLed workers because collection happens post-pipeline.

TPU-native design: there is no Ray object store here, so the shared storage
layer (local dir, s3://, gs://) is the rendezvous instead of driver-side
actor fan-in. Each node runs a **collector** that pushes its staging tree to
``<output>/profile/collected/node<rank>/`` with:

- **chunked transfer** — files above ``chunk_bytes`` stream up as numbered
  chunk objects, so peak memory is one chunk, not one file (the reference's
  ``_FileChunk`` bound);
- **bounded in-flight uploads** — a small worker pool fed by a bounded queue
  gives the same two-level backpressure as the reference's generator limit +
  ``ray.wait`` loop;
- **a per-node manifest** (sizes + CRC32 per file, error isolation per
  file) written last, atomically — a node crash mid-collect leaves no
  manifest and the node is simply re-collectable.

The **driver** then runs delivery's finalize phase: merge all node
manifests into one run index, verify chunk counts/CRCs, and reassemble
chunked files when the destination is a local path.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from cosmos_curate_tpu.storage.client import get_storage_client, write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_CHUNK_BYTES = 8 * 1024 * 1024
MANIFEST_NAME = "_manifest.json"
INDEX_NAME = "index.json"


@dataclass
class CollectResult:
    node: str
    files: int = 0
    bytes: int = 0
    errors: list[str] = field(default_factory=list)


def _collected_root(output_path: str) -> str:
    return f"{output_path.rstrip('/')}/profile/collected"


class ArtifactCollector:
    """Per-node phase: push one node's staging dirs to the shared prefix."""

    def __init__(
        self,
        output_path: str,
        *,
        node_tag: str | None = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        max_in_flight: int = 4,
    ) -> None:
        self.output_path = output_path
        self.node = node_tag or os.environ.get("CURATE_NODE_RANK", "0")
        self.chunk_bytes = chunk_bytes
        self.max_in_flight = max(1, max_in_flight)
        self.dest_root = f"{_collected_root(output_path)}/node{self.node}"

    # -- upload pool -------------------------------------------------------

    def _uploader(self, q: "queue.Queue", errors: list[str], lock: threading.Lock) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            rel, dest, data = item
            try:
                write_bytes(dest, data)
            except Exception as e:  # per-file isolation: record, keep going
                with lock:
                    errors.append(f"{rel}: {e!r}")

    def collect(
        self, staging_dirs: tuple[str, ...] | None = None, *, cleanup: bool = True
    ) -> CollectResult:
        if staging_dirs is None:
            from cosmos_curate_tpu.observability.tracing import default_staging_dir

            staging_dirs = (default_staging_dir(),)

        result = CollectResult(node=self.node)
        manifest: dict[str, Any] = {"node": self.node, "files": {}, "errors": []}
        # bounded queue = backpressure: the walker blocks once max_in_flight
        # chunks are queued, so peak memory stays ~chunk_bytes * max_in_flight
        q: "queue.Queue" = queue.Queue(maxsize=self.max_in_flight)
        errors: list[str] = []
        lock = threading.Lock()
        workers = [
            threading.Thread(target=self._uploader, args=(q, errors, lock), daemon=True)
            for _ in range(self.max_in_flight)
        ]
        for w in workers:
            w.start()

        collected_paths: list[Path] = []
        try:
            for staging in staging_dirs:
                root = Path(staging)
                if not root.is_dir():
                    continue
                for f in sorted(root.rglob("*")):
                    if not f.is_file() or f.name == MANIFEST_NAME:
                        continue
                    rel = f"{root.name}/{f.relative_to(root)}"
                    try:
                        entry = self._submit_file(f, rel, q)
                    except Exception as e:
                        manifest["errors"].append(f"{rel}: {e!r}")
                        result.errors.append(f"{rel}: {e!r}")
                        continue
                    manifest["files"][rel] = entry
                    result.files += 1
                    result.bytes += entry["size"]
                    collected_paths.append(f)
        finally:
            for _ in workers:
                q.put(None)
            for w in workers:
                w.join()

        manifest["errors"].extend(errors)
        result.errors.extend(errors)
        # manifest last + atomic: its presence marks a complete collection
        write_bytes(
            f"{self.dest_root}/{MANIFEST_NAME}", json.dumps(manifest, indent=1).encode()
        )
        if cleanup:
            failed = {e.split(":", 1)[0] for e in manifest["errors"]}
            for staging in staging_dirs:
                root = Path(staging)
                for f in collected_paths:
                    try:
                        rel = f"{root.name}/{f.relative_to(root)}"
                    except ValueError:
                        continue
                    if rel not in failed and f.exists():
                        f.unlink()
        if result.files or result.errors:
            logger.info(
                "node %s: collected %d artifacts (%d bytes, %d errors) -> %s",
                self.node, result.files, result.bytes, len(result.errors), self.dest_root,
            )
        return result

    def _submit_file(self, f: Path, rel: str, q: "queue.Queue") -> dict[str, Any]:
        size = f.stat().st_size
        crc = 0
        if size <= self.chunk_bytes:
            data = f.read_bytes()
            crc = zlib.crc32(data)
            q.put((rel, f"{self.dest_root}/{rel}", data))
            return {"size": size, "crc32": crc, "chunks": 0}
        # chunked: stream the file so only one chunk is resident at a time
        n = 0
        with open(f, "rb") as fh:
            while True:
                data = fh.read(self.chunk_bytes)
                if not data:
                    break
                crc = zlib.crc32(data, crc)
                q.put((rel, f"{self.dest_root}/{rel}.chunk{n:05d}", data))
                n += 1
        return {"size": size, "crc32": crc, "chunks": n}


@dataclass
class DeliveryReport:
    nodes: list[str]
    files: int
    bytes: int
    errors: list[str]
    missing_nodes: list[str]

    @property
    def ok(self) -> bool:
        return not self.errors and not self.missing_nodes


def finalize_delivery(
    output_path: str,
    *,
    expected_nodes: list[str] | None = None,
    reassemble: bool = True,
) -> DeliveryReport:
    """Driver phase: merge node manifests into one run index, verify chunked
    files, and (for local destinations) reassemble chunks in place."""
    root = _collected_root(output_path)
    client = get_storage_client(root)
    is_local = "://" not in output_path

    manifests: dict[str, dict] = {}
    for info in client.list_files(root):
        # node manifests live at exactly <root>/node<tag>/_manifest.json;
        # staged artifacts are always at least one level deeper
        rel = info.path[len(root):].lstrip("/")
        parts = rel.split("/")
        if len(parts) != 2 or parts[1] != MANIFEST_NAME or not parts[0].startswith("node"):
            continue
        node = parts[0].removeprefix("node")
        try:
            manifests[node] = json.loads(client.read_bytes(info.path))
        except Exception as e:
            manifests[node] = {"files": {}, "errors": [f"unreadable manifest: {e!r}"]}

    errors: list[str] = []
    files = 0
    total = 0
    for node, man in sorted(manifests.items()):
        node_root = f"{root}/node{node}"
        errors.extend(f"node{node}: {e}" for e in man.get("errors", []))
        for rel, entry in man.get("files", {}).items():
            files += 1
            total += entry["size"]
            if entry.get("chunks"):
                chunk_paths = [
                    f"{node_root}/{rel}.chunk{i:05d}" for i in range(entry["chunks"])
                ]
                missing = [p for p in chunk_paths if not client.exists(p)]
                if missing:
                    errors.append(f"node{node}: {rel} missing {len(missing)} chunks")
                    continue
                if reassemble and is_local:
                    crc = 0
                    dest = Path(f"{node_root}/{rel}")
                    tmp = dest.with_name(dest.name + ".tmp")
                    with open(tmp, "wb") as out:
                        for p in chunk_paths:
                            data = client.read_bytes(p)
                            crc = zlib.crc32(data, crc)
                            out.write(data)
                    if crc != entry["crc32"]:
                        errors.append(f"node{node}: {rel} CRC mismatch after reassembly")
                        tmp.unlink()
                        continue
                    tmp.replace(dest)
                    for p in chunk_paths:
                        client.delete(p)

    missing_nodes = [
        n for n in (expected_nodes or []) if str(n).removeprefix("node") not in manifests
    ]
    errors.extend(f"node{n}: no manifest (node crashed before collect?)" for n in missing_nodes)

    index = {
        "nodes": sorted(manifests),
        "files": files,
        "bytes": total,
        "errors": errors,
        "missing_nodes": missing_nodes,
    }
    write_bytes(f"{root}/{INDEX_NAME}", json.dumps(index, indent=1).encode())
    return DeliveryReport(sorted(manifests), files, total, errors, missing_nodes)


def collect_artifacts(
    output_path: str,
    *,
    staging_dirs: tuple[str, ...] | None = None,
    node_tag: str | None = None,
    cleanup: bool = True,
) -> int:
    """One-node convenience wrapper (original API): collect this node's
    staging dirs and return the number of files pushed."""
    collector = ArtifactCollector(output_path, node_tag=node_tag)
    return collector.collect(staging_dirs, cleanup=cleanup).files
