"""Live run-status snapshots: the in-flight counterpart of run_report.json.

Every runner (SequentialRunner, PipelinedRunner, StreamingRunner)
periodically publishes a bounded JSON snapshot of its live state —
per-stage queue depths, busy fractions, in-flight batch ids with ages and
retry/death counts, worker counts, object-plane and caption-KV occupancy,
node heartbeat ages — under the run's output directory
(``<output>/report/live/status.json``). Snapshots are swapped ATOMICALLY
(tmp file + ``os.replace``), so a concurrent reader (`cosmos-curate-tpu
top`, `report --follow`, the job service's ``/v1/jobs/<id>/status``) always
sees either the previous or the current snapshot, never torn JSON.

Cheap by construction: the publisher reuses the bounded aggregates
stage_timer already maintains (dispatch, caption phases, object plane) plus
counters the runner loops already keep — no new hot-path instrumentation —
and rate-limits itself to ``CURATE_LIVE_STATUS_INTERVAL_S`` (default 2 s),
so a snapshot costs one small JSON serialize + one rename every few
seconds.

Wiring: ``run_split`` exports ``CURATE_LIVE_STATUS_DIR`` derived from the
run's output path (local roots only — atomic rename needs a real
filesystem); runners construct a :class:`LiveStatusPublisher` from the env
at ``run()`` time and publish from their main loop. The publisher ALSO
drives the stall/anomaly detector (observability/anomaly.py) over each
snapshot and embeds the verdicts, so every reader of the snapshot gets the
detector's opinion for free.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Callable

from cosmos_curate_tpu.utils import schema_stamp
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LIVE_STATUS_DIR_ENV = "CURATE_LIVE_STATUS_DIR"
LIVE_STATUS_ENABLE_ENV = "CURATE_LIVE_STATUS"  # "0" disables publishing
LIVE_STATUS_INTERVAL_ENV = "CURATE_LIVE_STATUS_INTERVAL_S"
DEFAULT_INTERVAL_S = 2.0
STATUS_FILE = "status.json"
STATUS_REL = "report/live/status.json"

# at most this many in-flight batches per stage ride a snapshot (oldest
# first — the stuck ones are what the detector and the operator care about)
MAX_INFLIGHT_PER_STAGE = 16


def status_path(output_path: str) -> str:
    """Canonical snapshot location for a run output root."""
    return f"{output_path.rstrip('/')}/{STATUS_REL}"


def live_status_dir() -> str | None:
    """The directory THIS process publishes snapshots to (env-configured by
    run_split / the service job child), or None when live status is off."""
    if os.environ.get(LIVE_STATUS_ENABLE_ENV, "1") == "0":
        return None
    return os.environ.get(LIVE_STATUS_DIR_ENV) or None


def export_live_status_dir(output_path: str) -> str | None:
    """Derive the snapshot dir from a run's output root and export it for
    this process (and every worker it spawns). Remote roots (s3://, gs://)
    are skipped — the atomic-swap contract needs a local filesystem — and
    ``CURATE_LIVE_STATUS=0`` disables publishing outright. Each run
    OVERWRITES the env var: a process running several pipelines back to
    back must publish each run under its own output root, never the first
    one's. Returns the dir in effect, or None."""
    if os.environ.get(LIVE_STATUS_ENABLE_ENV, "1") == "0":
        return None
    if "://" in output_path:
        os.environ.pop(LIVE_STATUS_DIR_ENV, None)
        return None
    d = str(Path(output_path) / "report" / "live")
    os.environ[LIVE_STATUS_DIR_ENV] = d
    return d


def read_status(path_or_dir: str) -> dict | None:
    """Tolerant snapshot reader: accepts the status file, its directory, or
    a run output root; returns None when absent or unreadable (a reader
    racing the very first publish must not crash)."""
    p = Path(path_or_dir)
    candidates = [p]
    if not p.name.endswith(".json"):
        candidates = [p / STATUS_FILE, p / "report" / "live" / STATUS_FILE]
    for c in candidates:
        try:
            return json.loads(c.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
    return None


def snapshot_age_s(snapshot: dict, now: float | None = None) -> float:
    now = time.time() if now is None else now
    return max(0.0, now - float(snapshot.get("ts") or now))


class LiveStatusPublisher:
    """Rate-limited atomic snapshot writer + anomaly-detector driver.

    Construct with :meth:`from_env` (None when live status is off) or with
    an explicit directory. ``maybe_publish(build)`` is the hot-loop entry:
    it calls ``build()`` only when the interval elapsed, augments the
    snapshot with the shared stage_timer sections, runs the detector, and
    swaps the file. Publish failures are swallowed after one loud log —
    status IO must never take down a run."""

    def __init__(
        self,
        directory: str,
        *,
        runner: str = "",
        interval_s: float | None = None,
        detector: "Any | None" = None,
    ) -> None:
        self.dir = Path(directory)
        self.runner = runner
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get(LIVE_STATUS_INTERVAL_ENV, "") or DEFAULT_INTERVAL_S
                )
            except ValueError:
                interval_s = DEFAULT_INTERVAL_S
        self.interval_s = max(0.0, interval_s)
        if detector is None:
            from cosmos_curate_tpu.observability.anomaly import AnomalyDetector

            detector = AnomalyDetector()
        self.detector = detector
        self.seq = 0
        self._last_publish = 0.0
        self._started = time.time()
        self._warned = False

    @classmethod
    def from_env(
        cls, *, runner: str = "", detector: "Any | None" = None
    ) -> "LiveStatusPublisher | None":
        d = live_status_dir()
        return cls(d, runner=runner, detector=detector) if d else None

    @property
    def path(self) -> Path:
        return self.dir / STATUS_FILE

    # ------------------------------------------------------------------
    def maybe_publish(self, build: Callable[[], dict]) -> dict | None:
        """Publish if the interval elapsed; returns the snapshot or None."""
        now = time.monotonic()
        if now - self._last_publish < self.interval_s:
            return None
        self._last_publish = now
        return self.publish(build())

    def publish(self, snapshot: dict, *, final: bool = False) -> dict:
        """Augment, detect, and atomically swap one snapshot."""
        self.seq += 1
        # schema_version is the canonical stamp ("version" stays as the
        # legacy alias pre-stamp readers like `top` polled for)
        schema_stamp.stamp(snapshot, "live-status")
        snapshot.setdefault("version", schema_stamp.SCHEMA_VERSIONS["live-status"])
        snapshot.setdefault("ts", time.time())
        snapshot["seq"] = self.seq
        snapshot["pid"] = os.getpid()
        snapshot.setdefault("runner", self.runner)
        snapshot["state"] = "finished" if final else snapshot.get("state", "running")
        snapshot.setdefault("wall_s", round(snapshot["ts"] - self._started, 3))
        self._augment(snapshot)
        if not final:
            # the detector evaluates running snapshots only: a finished
            # run's zero throughput / idle stages are not anomalies
            try:
                self.detector.observe(snapshot)
            except Exception:
                logger.exception("anomaly detector failed (snapshot unaffected)")
        snapshot["anomalies"] = list(self.detector.emitted)[-16:]
        # the monotonic total, NOT the bounded tail's length: readers (the
        # service relay) key new-anomaly deltas on this
        snapshot["anomaly_count"] = int(
            getattr(self.detector, "emitted_total", len(self.detector.emitted))
        )
        self._write(snapshot)
        return snapshot

    def finalize(self, snapshot: dict | None = None) -> None:
        """Terminal snapshot: state=finished so readers (and `top`) can tell
        'run done' from 'publisher died'."""
        self.publish(snapshot or {}, final=True)

    # ------------------------------------------------------------------
    def _augment(self, snapshot: dict) -> None:
        """Attach the bounded aggregates stage_timer already keeps — the
        'no new hot-path instrumentation' contract: everything here is a
        read of existing state."""
        from cosmos_curate_tpu.observability import stage_timer as st

        snapshot.setdefault("node", st.node_id())
        try:
            snapshot.setdefault("dispatch", st.dispatch_summaries())
            caption = st.caption_phase_summaries()
            if caption:
                snapshot.setdefault("caption", caption)
            plane = st.object_plane_summaries()
            if plane:
                snapshot.setdefault("object_plane", plane)
        except Exception:
            logger.exception("live status aggregate collection failed")

    def _write(self, snapshot: dict) -> None:
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            tmp = self.dir / f".{STATUS_FILE}.{os.getpid()}.tmp"
            tmp.write_text(json.dumps(snapshot), encoding="utf-8")
            os.replace(tmp, self.path)  # atomic swap: readers never see torn JSON
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning(
                    "live status publish to %s failed (%s); further failures "
                    "silent", self.path, e,
                )


# ---------------------------------------------------------------------------
# rendering (shared by `cosmos-curate-tpu top` and `report --follow`)


def render_status(snapshot: dict, *, now: float | None = None) -> str:
    """Human view of one snapshot: an htop-for-pipelines per-stage table
    plus anomaly verdicts and the object-plane/caption one-liners."""
    now = time.time() if now is None else now
    lines: list[str] = []
    age = snapshot_age_s(snapshot, now)
    state = snapshot.get("state", "?")
    lines.append(
        f"run: {state.upper()}  runner={snapshot.get('runner', '?')}  "
        f"wall {float(snapshot.get('wall_s') or 0.0):.1f}s  "
        f"snapshot #{snapshot.get('seq', '?')} ({age:.1f}s old)  "
        f"node={snapshot.get('node', '?')} pid={snapshot.get('pid', '?')}"
    )
    if state == "running" and age > 30.0:
        lines.append(
            f"  WARNING: snapshot is {age:.0f}s stale — publisher wedged or killed?"
        )
    stages = snapshot.get("stages") or {}
    if stages:
        lines.append(
            f"  {'stage':<36} {'wrk':>3} {'queue':>5} {'busy%':>5} "
            f"{'done':>6} {'err':>4} {'dlq':>4} {'inflight':>8} {'oldest':>7}"
        )
        for name, st in stages.items():
            inflight = st.get("inflight") or []
            oldest = max((float(b.get("age_s") or 0.0) for b in inflight), default=0.0)
            lines.append(
                f"  {name:<36} {st.get('workers', 0):>3} "
                f"{st.get('queue_depth', 0):>5} "
                f"{100.0 * float(st.get('busy_frac') or 0.0):>4.0f}% "
                f"{st.get('completed', 0):>6} {st.get('errored', 0):>4} "
                f"{st.get('dead_lettered', 0):>4} {len(inflight):>8} "
                f"{oldest:>6.1f}s"
            )
    nodes = snapshot.get("nodes") or {}
    if nodes:
        hb = ", ".join(
            f"{n}={float(i.get('heartbeat_age_s') or 0.0):.1f}s"
            for n, i in sorted(nodes.items())
        )
        lines.append(f"  node heartbeat ages: {hb}")
    if snapshot.get("store_bytes"):
        lines.append(
            f"  object store: {float(snapshot['store_bytes']) / 1e6:.1f} MB in flight"
        )
    caption = snapshot.get("caption") or {}
    for name, agg in caption.items():
        if agg.get("kv_blocks_total"):
            lines.append(
                f"  kv pool [{name}]: {agg.get('kv_blocks_used', 0)}/"
                f"{agg.get('kv_blocks_total', 0)} blocks"
            )
    anomalies = snapshot.get("anomalies") or []
    if anomalies:
        lines.append(f"  anomalies ({snapshot.get('anomaly_count', len(anomalies))}):")
        for ev in anomalies[-8:]:
            t = time.strftime("%H:%M:%S", time.localtime(float(ev.get("ts") or 0)))
            lines.append(f"    [{t}] {ev.get('kind')} @ {ev.get('stage')}: {ev.get('detail')}")
    else:
        lines.append("  anomalies: none")
    return "\n".join(lines)
