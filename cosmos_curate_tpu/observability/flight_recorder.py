"""Run flight recorder: one end-of-run artifact that answers "where did
the time go".

Before this existed, a performance question about a run meant hand-merging
three sources: per-process NDJSON span files (driver + every worker, via
the artifact rendezvous), the device pipeline's dispatch aggregates
(``stage_timer.dispatch_summaries`` + worker at-exit dumps), and the
pipelined runner's flow gauges — plus the DLQ for what was dropped. The
flight recorder merges all of them at run finalize into a single
``<output>/report/run_report.json``:

- **span tree** — every NDJSON span under ``<output>/profile`` (the
  driver's ``traces/driver.ndjson`` plus worker files delivered through
  ``observability/artifacts.py``), the set of trace ids (ONE id means the
  cross-process propagation held end to end), and the **critical path**:
  from the root span, repeatedly descend into the longest child;
- **per-stage time** — from the runner's busy-seconds accounting when a
  runner is handed in, else derived from ``stage.*.process`` spans;
- **device dispatch** and **stage flow** aggregates, verbatim;
- **drop accounting** — dead-lettered batch counts and the DLQ run dir.

Render it with ``cosmos-curate-tpu report <run>`` (cli/report_cli.py);
``bench.py`` stamps the report path into every BENCH row.
"""

from __future__ import annotations

import json
import time
from typing import Any

from cosmos_curate_tpu.storage.client import get_storage_client, write_bytes
from cosmos_curate_tpu.utils import schema_stamp
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

REPORT_REL = "report/run_report.json"


def report_path(output_path: str) -> str:
    return f"{output_path.rstrip('/')}/{REPORT_REL}"


# -- span collection ---------------------------------------------------------


def clear_trace_artifacts(output_path: str, *, rank: int | None = None) -> int:
    """Delete span files (``*.ndjson``) a PRIOR traced run left under
    ``<output>/profile``. A traced re-run into the same root overwrites
    only the base driver file — stale rotation parts and collected worker
    files would keep the old run's trace ids and hand the new run a false
    DISCONNECTED verdict (and a critical path rooted in dead spans).

    ``rank=None`` (single node) clears everything, including stale
    ``report/node-stats-*.json`` sidecars. With ``rank`` set (multi-node)
    the clear is scoped to files only THIS rank ever writes — its
    ``driver-n<rank>`` NDJSON (base + rotation parts), its
    ``collected/node<rank>/`` worker spans, and its node-stats sidecar —
    so peers already writing to the shared root are never touched (rank 0
    additionally owns a prior single-node run's plain ``driver.ndjson``
    files, so growing a root from one node to N starts clean too). A
    re-run with FEWER nodes than the prior run leaves the dead ranks'
    files behind (no rank owns them at startup); use a fresh output root
    when shrinking the topology. Returns the number of files removed."""
    root = f"{output_path.rstrip('/')}/profile"
    client = get_storage_client(root)
    removed = 0
    try:
        files = list(client.list_files(root, suffixes=(".ndjson",)))
    except Exception:
        files = []
    for info in files:
        if rank is not None:
            name = info.path.rsplit("/", 1)[-1]
            own = name.startswith(f"driver-n{rank}.") or (
                f"/collected/node{rank}/" in info.path
            )
            # rank 0 exists in every topology, so it also owns the files a
            # prior SINGLE-node run left behind (plain driver.ndjson +
            # parts) — without this, growing a root from 1 node to N mixes
            # the old trace into the merge
            if rank == 0 and name.startswith("driver."):
                own = True
            if not own:
                continue
        try:
            client.delete(info.path)
            removed += 1
        except Exception:
            logger.warning("could not remove stale span file %s", info.path)
    # stale sidecars feed load_node_stats at merge time: a dead run's ranks
    # would add their drops/busy-seconds to the merged report
    report_root = f"{output_path.rstrip('/')}/report"
    report_client = get_storage_client(report_root)
    try:
        sidecars = [
            info
            for info in report_client.list_files(report_root, suffixes=(".json",))
            if info.path.rsplit("/", 1)[-1].startswith("node-stats-")
        ]
    except Exception:
        sidecars = []
    for info in sidecars:
        if rank is not None and info.path.rsplit("/", 1)[-1] != f"node-stats-{rank}.json":
            continue
        try:
            report_client.delete(info.path)
            removed += 1
        except Exception:
            logger.warning("could not remove stale node stats %s", info.path)
    if removed:
        logger.info("flight recorder: cleared %d stale trace artifact(s)", removed)
    return removed


def collect_spans(output_path: str) -> list[dict]:
    """Every span record under ``<output>/profile`` (driver NDJSON + worker
    NDJSONs delivered by the artifact collector). Unreadable files/lines are
    skipped — a torn trace must not void the report."""
    root = f"{output_path.rstrip('/')}/profile"
    client = get_storage_client(root)
    spans: list[dict] = []
    try:
        files = list(client.list_files(root, suffixes=(".ndjson",)))
    except Exception:
        return spans
    for info in files:
        try:
            text = client.read_bytes(info.path).decode("utf-8", "replace")
        except Exception:
            logger.warning("flight recorder: unreadable span file %s", info.path)
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "span_id" in rec and "name" in rec:
                spans.append(rec)
    return spans


def _critical_path(spans: list[dict]) -> list[dict]:
    """Root -> leaf chain following the longest child at every level.

    Root = the longest span whose parent is absent from the collected set
    (cross-process parents ARE in the set when propagation worked; a
    disconnected fragment shows up as extra roots and extra trace ids)."""
    if not spans:
        return []
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)

    def dur(s: dict) -> float:
        return float(s.get("duration_s") or 0.0)

    path = []
    node = max(roots, key=dur)
    seen = set()
    while node is not None and node["span_id"] not in seen:
        seen.add(node["span_id"])
        path.append(
            {
                "name": node["name"],
                "duration_s": round(dur(node), 4),
                "span_id": node["span_id"],
                "pid": node.get("pid"),
            }
        )
        kids = children.get(node["span_id"])
        node = max(kids, key=dur) if kids else None
    return path


def _by_name(spans: list[dict]) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for s in spans:
        agg = out.setdefault(s["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0})
        d = float(s.get("duration_s") or 0.0)
        agg["count"] += 1
        agg["total_s"] = round(agg["total_s"] + d, 4)
        agg["max_s"] = round(max(agg["max_s"], d), 4)
    return out


def _stage_times_from_spans(spans: list[dict]) -> dict[str, float]:
    """Summed ``stage.<name>.process`` span seconds — the fallback when no
    runner with busy-seconds accounting is available."""
    out: dict[str, float] = {}
    for s in spans:
        name = s["name"]
        if name.startswith("stage.") and name.endswith(".process"):
            stage = name[len("stage."):-len(".process")]
            out[stage] = round(out.get(stage, 0.0) + float(s.get("duration_s") or 0.0), 4)
    return out


# -- report ------------------------------------------------------------------


def load_report(path: str, *, strict: bool = False) -> dict | None:
    """Read an existing ``run_report.json`` (None when absent). Unreadable
    content returns None, or raises ValueError with ``strict=True`` —
    callers that treat a torn report as a hard error (report CLI without
    --rebuild) want the distinction from plain absence."""
    client = get_storage_client(path)
    try:
        if not client.exists(path):
            return None
        return json.loads(client.read_bytes(path))
    except (OSError, ValueError) as e:
        if strict:
            raise ValueError(f"unreadable report {path}: {e}") from e
        return None


def runner_stats(runner: Any) -> dict:
    """The report sections only the process that RAN the pipeline can
    source: runner accounting plus this process's in-memory dispatch/flow
    aggregates. ``runner=None`` yields the aggregate-only skeleton."""
    from cosmos_curate_tpu.observability.stage_timer import (
        anomaly_summaries,
        caption_phase_summaries,
        dispatch_summaries,
        index_op_summaries,
        search_summaries,
        object_plane_summaries,
        stage_flow_summaries,
    )

    stats: dict[str, Any] = {
        "dispatch": dispatch_summaries(),
        "stage_flow": stage_flow_summaries(),
        "caption_phases": caption_phase_summaries(),
        # corpus-index traffic (adds/queries/probe fan-out per recorder
        # name) — the pipeline_index_* counters' end-of-run snapshot
        "index_ops": index_op_summaries(),
        # index-server read path: request counts, latency p50/p99, warm
        # shard-cache byte traffic, compaction generations
        "search": search_summaries(),
        # cross-host transfers per node (driver's own + relayed agent
        # deltas); the engine runner also snapshots this as
        # ``runner.object_plane`` at finalize
        "object_plane": object_plane_summaries(),
        # stall/anomaly detector verdicts (observability/anomaly.py):
        # per-(stage, kind) counts + the bounded recent-events tail
        "anomalies": anomaly_summaries(),
        "stage_times": dict(getattr(runner, "stage_times", None) or {}),
    }
    node_plan = getattr(runner, "node_plan", None)
    if node_plan:
        stats["node_plan"] = node_plan
    # node-loss receipts: declared deaths + what lineage reconstruction
    # recomputed (engine/runner.py) — the robustness counterpart of the
    # object_plane section
    node_events = getattr(runner, "node_events", None)
    reconstructed = int(getattr(runner, "objects_reconstructed", 0) or 0)
    if node_events or reconstructed:
        stats["node_events"] = {
            "deaths": list(node_events or []),
            "objects_reconstructed": reconstructed,
            "reconstruction_seconds": round(
                float(getattr(runner, "reconstruction_seconds", 0.0) or 0.0), 4
            ),
        }
    wall = getattr(runner, "pipeline_wall_s", 0.0)
    if wall:
        stats["wall_s"] = round(float(wall), 4)
    overlap = getattr(runner, "overlap_frac", None)
    if overlap is not None:
        stats["pipeline_overlap_frac"] = round(float(overlap), 4)
    counts = getattr(runner, "stage_counts", None)
    if counts:
        stats["stage_counts"] = counts
    dlq = getattr(runner, "dlq", None)
    dead = getattr(runner, "dead_lettered", 0) or getattr(dlq, "recorded", 0)
    stats["dead_lettered"] = int(dead or 0)
    if dlq is not None and getattr(dlq, "recorded", 0):
        stats["dlq_run_dir"] = str(dlq.run_dir)
    return stats


def write_node_stats(
    output_path: str, rank: int, runner: Any = None, *, extra: dict | None = None
) -> str:
    """Persist this node's runner-sourced sections as a per-node sidecar.

    Multi-node runs build the merged report at merge-summaries time, in a
    process where every node runner's memory is gone — without the sidecar
    the merged report would claim ``dead_lettered: 0`` and empty
    dispatch/flow sections no matter what the run actually did.

    ``extra`` overrides runner-sourced keys: work-stealing nodes run the
    pipeline once per stolen batch on one runner, and every ``run()`` resets
    its DLQ accounting, so the caller passes drop totals accumulated across
    batches in place of the last batch's."""
    from cosmos_curate_tpu.observability.tracing import suppress_tracing

    stats = runner_stats(runner)
    if extra:
        stats.update(extra)
    stats["node_rank"] = rank
    schema_stamp.stamp(stats, "node-stats")
    path = f"{output_path.rstrip('/')}/report/node-stats-{rank}.json"
    with suppress_tracing():
        write_bytes(path, json.dumps(stats, indent=1).encode())
    return path


def load_node_stats(output_path: str) -> dict | None:
    """Merge all ``report/node-stats-*.json`` sidecars into one
    prior-shaped dict (None when there are none): ``stage_times``,
    ``stage_counts`` and ``dead_lettered`` sum across nodes; dispatch/flow
    aggregates are namespaced per node (``n<rank>/<name>``) — their derived
    fractions must not be averaged blind. ``wall_s`` is the max across
    nodes (data-parallel nodes run concurrently, so the run lasts as long
    as its slowest node); ``pipeline_overlap_frac`` is the mean over the
    nodes that reported one."""
    root = f"{output_path.rstrip('/')}/report"
    client = get_storage_client(root)
    try:
        files = list(client.list_files(root, suffixes=(".json",)))
    except Exception:
        return None
    merged: dict[str, Any] = {
        "dispatch": {}, "stage_flow": {}, "caption_phases": {}, "index_ops": {},
        "search": {},
        "object_plane": {}, "stage_times": {}, "stage_counts": {},
        "dead_lettered": 0,
    }
    dlq_dirs: list[str] = []
    overlaps: list[float] = []
    found = False
    for info in files:
        if not info.path.rsplit("/", 1)[-1].startswith("node-stats-"):
            continue
        try:
            stats = json.loads(client.read_bytes(info.path))
        except (OSError, ValueError):
            continue
        found = True
        rank = stats.get("node_rank", "?")
        for key in ("dispatch", "stage_flow", "caption_phases", "index_ops", "search"):
            for name, agg in (stats.get(key) or {}).items():
                merged[key][f"n{rank}/{name}"] = agg
        # object-plane aggregates are already keyed per node: sum numeric
        # fields when two sidecars report the same node (driver rank saw
        # agent deltas AND the agent rank dumped its own totals)
        for node, agg in (stats.get("object_plane") or {}).items():
            into = merged["object_plane"].setdefault(node, {})
            for k, v in agg.items():
                if isinstance(v, (int, float)):
                    into[k] = round(into.get(k, 0) + v, 4)
        for name, s in (stats.get("stage_times") or {}).items():
            merged["stage_times"][name] = round(
                merged["stage_times"].get(name, 0.0) + float(s), 4
            )
        for name, counts in (stats.get("stage_counts") or {}).items():
            into = merged["stage_counts"].setdefault(name, {})
            for k, v in counts.items():
                if isinstance(v, (int, float)):
                    into[k] = into.get(k, 0) + v
        merged["dead_lettered"] += int(stats.get("dead_lettered", 0) or 0)
        # anomaly verdicts: counts sum across nodes, the recent tail
        # concatenates (bounded — it was bounded per node already)
        anom = stats.get("anomalies")
        if anom:
            into = merged.setdefault(
                "anomalies", {"total": 0, "counts": {}, "recent": []}
            )
            into["total"] += int(anom.get("total", 0) or 0)
            for k, v in (anom.get("counts") or {}).items():
                into["counts"][k] = into["counts"].get(k, 0) + int(v)
            into["recent"] = (into["recent"] + list(anom.get("recent") or []))[-64:]
        # node-loss receipts concatenate (deaths) / sum (reconstruction):
        # every rank's driver sees only the agents IT lost
        ne = stats.get("node_events")
        if ne:
            into = merged.setdefault(
                "node_events",
                {"deaths": [], "objects_reconstructed": 0, "reconstruction_seconds": 0.0},
            )
            into["deaths"].extend(ne.get("deaths") or [])
            into["objects_reconstructed"] += int(ne.get("objects_reconstructed", 0) or 0)
            into["reconstruction_seconds"] = round(
                into["reconstruction_seconds"]
                + float(ne.get("reconstruction_seconds", 0.0) or 0.0),
                4,
            )
        if stats.get("dlq_run_dir"):
            dlq_dirs.append(stats["dlq_run_dir"])
        if stats.get("wall_s"):
            merged["wall_s"] = max(
                merged.get("wall_s", 0.0), float(stats["wall_s"])
            )
        if stats.get("pipeline_overlap_frac") is not None:
            overlaps.append(float(stats["pipeline_overlap_frac"]))
    if not found:
        return None
    if dlq_dirs:
        merged["dlq_run_dir"] = ",".join(dlq_dirs)
    if overlaps:
        merged["pipeline_overlap_frac"] = round(sum(overlaps) / len(overlaps), 4)
    return merged


def build_run_report(
    output_path: str,
    *,
    runner: Any = None,
    extra: dict | None = None,
    prior: dict | None = None,
) -> dict:
    """Assemble the report dict (no write). ``runner`` contributes
    stage_times/stage_counts/DLQ/overlap when given; span-derived numbers
    fill the gaps so the report works for any runner (or none).

    ``prior`` is a previously-written report for the same run: sections
    this process cannot source (dispatch/flow aggregates live in the
    ORIGINAL driver's memory, runner stats in its runner) are carried over
    instead of being overwritten with empties — a later ``report
    --rebuild`` must not degrade the artifact."""
    spans = collect_spans(output_path)
    trace_ids = sorted({s.get("trace_id", "") for s in spans if s.get("trace_id")})
    pids = sorted({s.get("pid") for s in spans if s.get("pid") is not None})
    # "version" is the legacy alias of the schema stamp (pre-stamp readers
    # grep for it); both come from the one published number in
    # utils/schema_stamp.SCHEMA_VERSIONS — never hand-write either.
    report: dict[str, Any] = schema_stamp.stamp({}, "run-report")
    report["version"] = schema_stamp.SCHEMA_VERSIONS["run-report"]
    report.update({
        "generated_at": time.time(),
        "output_path": output_path,
        "span_count": len(spans),
        "trace_ids": trace_ids,
        # ONE trace id across every process = the propagation held;
        # vacuously false with no spans (tracing was off)
        "connected": len(trace_ids) == 1,
        "processes": len(pids),
        "critical_path": _critical_path(spans),
        "spans_by_name": _by_name(spans),
    })
    stats = runner_stats(runner)
    report["dispatch"] = stats["dispatch"]
    report["stage_flow"] = stats["stage_flow"]
    report["caption_phases"] = stats["caption_phases"]
    report["index_ops"] = stats["index_ops"]
    report["search"] = stats.get("search") or {}
    report["object_plane"] = stats["object_plane"]
    report["anomalies"] = stats.get("anomalies") or {}
    if stats.get("node_plan"):
        report["node_plan"] = stats["node_plan"]
    if stats.get("node_events"):
        report["node_events"] = stats["node_events"]
    # precedence: live runner accounting > prior/sidecar accounting (it
    # includes setup time spans don't book to the stage) > span-derived
    report["stage_times"] = (
        stats["stage_times"]
        or (prior or {}).get("stage_times")
        or _stage_times_from_spans(spans)
    )
    wall = stats.get("wall_s") or (prior or {}).get("wall_s") or 0.0
    if not wall and report["critical_path"]:
        wall = report["critical_path"][0]["duration_s"]
    report["wall_s"] = round(float(wall or 0.0), 4)
    if "pipeline_overlap_frac" in stats:
        report["pipeline_overlap_frac"] = stats["pipeline_overlap_frac"]
    if stats.get("stage_counts"):
        report["stage_counts"] = stats["stage_counts"]
    report["dead_lettered"] = stats["dead_lettered"]
    if "dlq_run_dir" in stats:
        report["dlq_run_dir"] = stats["dlq_run_dir"]
    if prior:
        # stage_times/wall_s are handled above (they have span-derived
        # fallbacks that would always win this not-set check)
        for key in (
            "dispatch", "stage_flow", "caption_phases", "index_ops", "search",
            "object_plane", "anomalies", "node_plan", "node_events",
            "stage_counts", "dead_lettered", "dlq_run_dir",
        ):
            if not report.get(key) and prior.get(key):
                report[key] = prior[key]
        # presence, not truthiness: overlap 0.0 is a measurement
        # ("stages ran in lockstep"), not absence of one
        if "pipeline_overlap_frac" not in report and "pipeline_overlap_frac" in prior:
            report["pipeline_overlap_frac"] = prior["pipeline_overlap_frac"]
    if extra:
        report.update(extra)
    return report


def write_run_report(
    output_path: str,
    *,
    runner: Any = None,
    extra: dict | None = None,
    require_spans: bool = False,
    prior: dict | None = None,
) -> dict:
    """Build the report and deliver it to ``<output>/report/run_report.json``
    through the storage layer (local dir, s3://, gs:// — the same rendezvous
    artifacts use). Returns the report with ``report_path`` set.

    ``require_spans=True`` skips the write (returning the unwritten report)
    when no spans were collected — finalize paths that run for traced AND
    untraced runs must not litter untraced output roots with empty reports."""
    from cosmos_curate_tpu.observability.tracing import suppress_tracing

    report = build_run_report(output_path, runner=runner, extra=extra, prior=prior)
    if require_spans and not report["span_count"]:
        return report
    path = report_path(output_path)
    report["report_path"] = path
    with suppress_tracing():  # the recorder's own IO is not run signal
        write_bytes(path, json.dumps(report, indent=1).encode())
    logger.info(
        "flight recorder: %d spans, %d trace(s) -> %s",
        report["span_count"], len(report["trace_ids"]), path,
    )
    return report


# -- rendering ---------------------------------------------------------------


def render_report(report: dict) -> str:
    """Human view: trace connectivity, the critical path, and per-stage /
    per-span-name time breakdowns (what `cosmos-curate-tpu report` prints)."""
    lines: list[str] = []
    lines.append(f"run report: {report.get('output_path', '?')}")
    n_traces = len(report.get("trace_ids", []))
    if report.get("connected"):
        status = f"CONNECTED ({report['trace_ids'][0]})"
    elif n_traces:
        status = f"DISCONNECTED — {n_traces} trace ids"
    else:
        status = "no spans (tracing was off)"
    lines.append(
        f"trace: {status}; {report.get('span_count', 0)} spans from "
        f"{report.get('processes', 0)} process(es); wall {report.get('wall_s', 0):.2f}s"
    )
    cp = report.get("critical_path") or []
    if cp:
        total = cp[0]["duration_s"] or 0.0
        lines.append(f"critical path ({total:.2f}s):")
        for depth, node in enumerate(cp):
            pct = f" ({100.0 * node['duration_s'] / total:.0f}%)" if total else ""
            prefix = "  " + "  " * depth + ("└─ " if depth else "")
            pid = f" [pid {node['pid']}]" if node.get("pid") is not None else ""
            lines.append(f"{prefix}{node['name']}  {node['duration_s']:.2f}s{pct}{pid}")
    stage_times = report.get("stage_times") or {}
    if stage_times:
        wall = report.get("wall_s") or 0.0
        lines.append("per-stage time (busy seconds):")
        for name, s in sorted(stage_times.items(), key=lambda kv: -kv[1]):
            pct = f"  {100.0 * s / wall:5.1f}% of wall" if wall else ""
            lines.append(f"  {name:<40} {s:9.2f}s{pct}")
    dispatch = report.get("dispatch") or {}
    if dispatch:
        lines.append("device dispatch (per pipeline):")
        for name, agg in sorted(dispatch.items()):
            lines.append(
                f"  {name:<40} {agg.get('dispatches', 0):5d} dispatches  "
                f"compute {agg.get('compute_s', 0.0):8.2f}s  "
                f"gap_frac {agg.get('gap_frac', 0.0):.3f}"
            )
    flow = report.get("stage_flow") or {}
    if flow:
        lines.append("stage flow:")
        for name, agg in sorted(flow.items()):
            lines.append(
                f"  {name:<40} busy {agg.get('busy_s', 0.0):8.2f}s  "
                f"busy_frac_mean {agg.get('busy_frac_mean', 0.0):.3f}  "
                f"queue_peak {agg.get('queue_depth_peak', 0)}"
            )
    plane = report.get("object_plane") or {}
    if plane:
        lines.append("object plane (per node):")
        for node, agg in sorted(plane.items()):
            moved = agg.get("fetch_bytes", 0) + agg.get("prefetch_bytes", 0)
            lines.append(
                f"  {node:<24} moved {moved / 1e6:9.2f}MB  "
                f"demand-wait {agg.get('fetch_wait_s', 0.0):7.2f}s  "
                f"prefetch {agg.get('prefetches', 0)} "
                f"(hits {agg.get('prefetch_hits', 0)}, "
                f"misses {agg.get('prefetch_misses', 0)})"
            )
    node_plan = report.get("node_plan") or {}
    if node_plan:
        lines.append("node plan (stage -> workers per node):")
        for stage, counts in node_plan.items():
            placed = ", ".join(
                f"{nid or 'driver'}={n}" for nid, n in sorted(counts.items())
            )
            lines.append(f"  {stage:<40} {placed}")
    events = report.get("node_events") or {}
    if events:
        deaths = events.get("deaths") or []
        lines.append(
            f"node events: {len(deaths)} death(s), "
            f"{events.get('objects_reconstructed', 0)} object(s) reconstructed "
            f"in {events.get('reconstruction_seconds', 0.0):.2f}s"
        )
        for ev in deaths:
            lines.append(
                f"  {ev.get('node', '?'):<24} {ev.get('reason', '?')} "
                f"({ev.get('workers_lost', 0)} worker(s) lost)"
            )
    index_ops = report.get("index_ops") or {}
    if index_ops:
        lines.append("corpus index:")
        for name, agg in sorted(index_ops.items()):
            lines.append(
                f"  {name:<40} adds {agg.get('adds', 0):7d}  "
                f"queries {agg.get('queries', 0):7d}  "
                f"dupes {agg.get('duplicates', 0):6d}  "
                f"probe_fanout {agg.get('probe_fanout_mean', 0.0):.2f}  "
                f"query {agg.get('query_s', 0.0):.2f}s"
            )
    search = report.get("search") or {}
    if search:
        lines.append("search serving:")
        for name, agg in sorted(search.items()):
            lines.append(
                f"  {name:<40} req {agg.get('searches', 0):7d}  "
                f"p50 {agg.get('latency_p50_ms', 0.0):7.1f}ms  "
                f"p99 {agg.get('latency_p99_ms', 0.0):7.1f}ms  "
                f"qps {agg.get('qps', 0.0):8.1f}  "
                f"cache_hit {agg.get('cache_hit_ratio', 0.0):.2f}  "
                f"gen {agg.get('generation', 0)}"
            )
    caption = report.get("caption_phases") or {}
    if caption:
        lines.append("caption engine phases:")
        for name, agg in sorted(caption.items()):
            lines.append(
                f"  {name:<40} prep {agg.get('prep_s', 0.0):7.2f}s  "
                f"prefill {agg.get('prefill_s', 0.0):7.2f}s  "
                f"decode {agg.get('decode_s', 0.0):7.2f}s  "
                f"idle_frac {agg.get('idle_frac', 0.0):.3f}  "
                f"prefix_hits {agg.get('prefix_cache_hits', 0)}"
            )
            if agg.get("kv_blocks_total"):
                lines.append(
                    f"  {'':<40} kv_blocks {agg.get('kv_blocks_peak', 0)}/"
                    f"{agg.get('kv_blocks_total', 0)} peak  "
                    f"prefix_block_refs {agg.get('prefix_block_refs', 0)}  "
                    f"cow {agg.get('kv_cow_copies', 0)}  "
                    f"interleaved_steps {agg.get('interleaved_steps', 0)}"
                )
            # per-owner accounting: which job/stage consumed the shared
            # engine (cross-job continuous batching receipt)
            for owner, sub in sorted((agg.get("owners") or {}).items()):
                lines.append(
                    f"    owner {owner:<36} requests {sub.get('requests', 0):6d}  "
                    f"decode_tokens {sub.get('decode_tokens', 0):8d}  "
                    f"drives {sub.get('drives', 0)}"
                )
    anomalies = report.get("anomalies") or {}
    if anomalies.get("total"):
        lines.append(
            f"anomalies: {anomalies['total']} "
            f"(stall/anomaly detector — see docs/OBSERVABILITY.md)"
        )
        for key, n in sorted(anomalies.get("counts", {}).items()):
            lines.append(f"  {key:<40} {n}")
        for ev in (anomalies.get("recent") or [])[-5:]:
            lines.append(
                f"    {ev.get('kind', '?')} @ {ev.get('stage', '?')}: "
                f"{ev.get('detail', '')}"
            )
    dead = report.get("dead_lettered", 0)
    if dead:
        lines.append(
            f"dead-lettered batches: {dead} "
            f"(dlq: {report.get('dlq_run_dir', '?')} — `cosmos-curate-tpu dlq list`)"
        )
    else:
        lines.append("dead-lettered batches: 0")
    return "\n".join(lines)
