"""Per-task stage timing statistics + per-dispatch device timings.

Equivalent capability of the reference's ``StageTimer``
(cosmos_curate/core/utils/infra/performance_utils.py — per-task wall/idle
stats behind ``--perf-profile``, feeding the summary and spans).

``DispatchRecord``/``record_dispatch`` carry the finer-grained signal the
async device pipeline (models/device_pipeline.py) emits per micro-batch:
H2D transfer, device compute, D2H readback, and — the number that proves
or disproves overlap — the *dispatch gap*, the wall time the device sat
idle between finishing micro-batch k and receiving k+1. A synchronous
dispatch loop shows gap ≈ host batch-prep time; a pipelined one shows ~0.
The per-stage aggregates feed bench.py and engine/metrics.py (autoscaler
and tuning read the exported gauges).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np


@dataclass
class StageTimer:
    stage_name: str
    samples_s: list[float] = field(default_factory=list)
    idle_s: float = 0.0
    _last_end: float | None = None

    @contextlib.contextmanager
    def time_process(self):
        start = time.monotonic()
        if self._last_end is not None:
            self.idle_s += start - self._last_end
        try:
            yield
        finally:
            end = time.monotonic()
            self.samples_s.append(end - start)
            self._last_end = end

    def summary(self) -> dict:
        arr = np.asarray(self.samples_s)
        if arr.size == 0:
            return {"stage": self.stage_name, "count": 0}
        return {
            "stage": self.stage_name,
            "count": int(arr.size),
            "total_s": float(arr.sum()),
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p90_s": float(np.percentile(arr, 90)),
            "max_s": float(arr.max()),
            "idle_s": self.idle_s,
        }


@dataclass(frozen=True)
class DispatchRecord:
    """One device micro-batch dispatch, as observed from the host."""

    h2d_s: float  # jax.device_put of the host micro-batch
    compute_s: float  # device busy time (after the previous batch finished)
    d2h_s: float  # deferred np.asarray readback at drain
    gap_s: float  # device idle between previous completion and this dispatch
    rows: int  # valid rows in the micro-batch
    padded_rows: int  # rows actually dispatched (bucket size)


# Aggregates per pipeline name — NOT a record log: a long-lived engine
# worker dispatches millions of micro-batches over a run, so per-record
# retention would grow without bound for data nothing reads (the prometheus
# counters already carry the stream).
_DISPATCH_LOCK = threading.Lock()
_DISPATCH: dict[str, dict] = {}
# Aggregates folded in from OTHER processes' dump files
# (merge_new_dumped_summaries). Kept separate from _DISPATCH so this
# process's own at-exit dump never re-exports them — a later merge over
# the same dump dir would count every worker's stats twice.
_FOLDED: dict[str, dict] = {}

# When set, every process that recorded dispatches writes its aggregate
# summaries to <dir>/dispatch-<pid>.json at exit — how engine WORKERS get
# their stats back to a parent (bench.py) that wants one merged view.
DISPATCH_DUMP_DIR_ENV = "CURATE_DISPATCH_DUMP_DIR"
_DUMP_REGISTERED = False


def _new_agg() -> dict:
    return {
        "dispatches": 0, "rows": 0, "padded_rows": 0,
        "h2d_s": 0.0, "compute_s": 0.0, "d2h_s": 0.0, "gap_s": 0.0,
    }


NODE_ID_ENV = "CURATE_NODE_ID"


def node_id() -> str:
    """Which node THIS process runs on, for per-node attribution in
    dispatch/flow/object-plane summaries. Node agents stamp the env into
    every worker they spawn; the driver and its local workers default to
    ``driver``."""
    return os.environ.get(NODE_ID_ENV) or "driver"


def record_dispatch(name: str, rec: DispatchRecord) -> None:
    """Fold one dispatch into the per-name aggregate and forward the
    gap/compute signal to the engine's prometheus gauges (no-op when the
    exporter is absent)."""
    with _DISPATCH_LOCK:
        agg = _DISPATCH.setdefault(name, _new_agg())
        agg["dispatches"] += 1
        agg["rows"] += rec.rows
        agg["padded_rows"] += rec.padded_rows
        agg["h2d_s"] += rec.h2d_s
        agg["compute_s"] += rec.compute_s
        agg["d2h_s"] += rec.d2h_s
        agg["gap_s"] += rec.gap_s
    _maybe_register_dump()
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_dispatch(
            name, gap_s=rec.gap_s, compute_s=rec.compute_s,
            h2d_s=rec.h2d_s, d2h_s=rec.d2h_s,
        )
    except Exception:  # metrics must never take down a dispatch path
        pass


def _maybe_register_dump() -> None:
    global _DUMP_REGISTERED
    if _DUMP_REGISTERED or not os.environ.get(DISPATCH_DUMP_DIR_ENV):
        return
    import atexit

    # resolve the env var at EXIT time, not registration time: a process
    # spanning several phases (bench's cold/warm passes) must dump where
    # the var points when it dies, not where it pointed at first dispatch
    atexit.register(_dump_summaries, None)
    _DUMP_REGISTERED = True


# Reserved dump key carrying a process's object-plane aggregate alongside
# its dispatch summaries (spawned workers have no exporter and no control
# link of their own — the dump is their only way home for store_read
# telemetry). Never a stage name: stages are class names.
OBJECT_PLANE_DUMP_KEY = "__object_plane__"


def _dump_summaries(path: str | None) -> None:
    try:
        import json

        path = path or os.environ.get(DISPATCH_DUMP_DIR_ENV)
        if not path:
            return
        d = Path(path)
        d.mkdir(parents=True, exist_ok=True)
        # dump this process's OWN dispatches only: aggregates merged in
        # from other processes' dumps (_FOLDED) are already on disk in
        # THEIR files, and re-exporting them would double-count on the
        # next merge over this dir
        with _DISPATCH_LOCK:
            items = {k: dict(v) for k, v in _DISPATCH.items()}
        out = _summarize(items)
        with _OP_LOCK:
            op = {k: _OP.get(k, 0.0) for k in OBJECT_PLANE_KEYS if _OP.get(k)}
        if op:
            out[OBJECT_PLANE_DUMP_KEY] = {**op, "node": node_id()}
        (d / f"dispatch-{os.getpid()}.json").write_text(json.dumps(out))
    except Exception:  # a failed dump must never break process exit
        pass


def _iter_dumps(path: str):
    """Yield ``(file, parsed dict)`` for every readable dispatch-*.json
    dump under ``path`` — the one parser both merge entry points share."""
    import json

    d = Path(path)
    if not d.is_dir():
        return
    for f in sorted(d.glob("dispatch-*.json")):
        try:
            yield f, json.loads(f.read_text())
        except (OSError, ValueError):
            continue


def _fold(into: dict, agg: dict) -> None:
    for k in into:
        if isinstance(into[k], (int, float)):
            into[k] += agg.get(k, 0)
    # per-node attribution survives the merge: one source node passes
    # through; aggregates folded across nodes say so instead of lying
    node = agg.get("node")
    if node:
        into["node"] = node if into.get("node") in (None, node) else "mixed"


def load_dumped_summaries(path: str) -> dict[str, dict]:
    """Merge dispatch summaries dumped by other processes (engine workers)
    under ``path`` into one name -> aggregate view."""
    merged: dict[str, dict] = {}
    for _f, data in _iter_dumps(path):
        for name, agg in data.items():
            if name == OBJECT_PLANE_DUMP_KEY:
                continue  # not a dispatch stage (merge_new_* folds it)
            _fold(merged.setdefault(name, _new_agg()), agg)
    for agg in merged.values():
        busy = agg["gap_s"] + agg["compute_s"]
        agg["gap_frac"] = round(agg["gap_s"] / busy, 4) if busy > 0 else 0.0
    return merged


# dump files already folded into THIS process's aggregates (path strings):
# a driver that runs several engine pipelines against the same dump dir
# must not double-count a worker's aggregate on the second merge
_MERGED_DUMPS: set[str] = set()


def merge_new_dumped_summaries(path: str) -> dict[str, dict]:
    """Fold worker-dumped dispatch aggregates into THIS process's in-memory
    aggregates AND its prometheus counters, each dump file at most once.

    This is how the driver completes its ``pipeline_device_*`` series on
    engine runs: spawned workers cannot serve their own exporter, so their
    at-exit dumps (``CURATE_DISPATCH_DUMP_DIR``) are merged at finalize.
    Returns what was newly merged (name -> aggregate)."""
    merged: dict[str, dict] = {}
    own = f"dispatch-{os.getpid()}.json"  # never re-ingest our own dump
    for f, data in _iter_dumps(path):
        key = str(f)
        if key in _MERGED_DUMPS or f.name == own:
            continue
        _MERGED_DUMPS.add(key)
        for name, agg in data.items():
            if name == OBJECT_PLANE_DUMP_KEY:
                # a spawned worker's store_read (and any other object-plane)
                # telemetry comes home through its dump: fold it under the
                # worker's node id so per-node summaries and the
                # pipeline_object_plane_* counters stay complete
                record_node_object_plane(
                    agg.get("node") or node_id(),
                    {k: v for k, v in agg.items() if k in OBJECT_PLANE_KEYS},
                )
                continue
            _fold(merged.setdefault(name, _new_agg()), agg)
            with _DISPATCH_LOCK:
                _fold(_FOLDED.setdefault(name, _new_agg()), agg)
    if merged:
        try:
            from cosmos_curate_tpu.engine.metrics import get_metrics

            m = get_metrics()
            for name, agg in merged.items():
                m.observe_dispatch_aggregate(name, agg)
        except Exception:  # metrics must never take down finalize
            pass
    return merged


def reset_dispatch_stats() -> None:
    with _DISPATCH_LOCK:
        _DISPATCH.clear()
        _FOLDED.clear()


# ---------------------------------------------------------------------------
# Per-stage flow aggregates from the pipelined runner (core/
# pipelined_runner.py): batch busy time folds in per process_data call,
# queue-depth/busy-fraction snapshots per runner tick. Bounded aggregates,
# not a log — the prometheus gauges carry the stream.
_FLOW_LOCK = threading.Lock()
_FLOW: dict[str, dict] = {}


def _new_flow() -> dict:
    return {
        "batches": 0, "busy_s": 0.0, "ticks": 0,
        "queue_depth": 0, "queue_depth_peak": 0,
        "busy_frac": 0.0, "busy_frac_sum": 0.0, "workers": 0,
    }


def record_stage_busy(name: str, busy_s: float) -> None:
    """Fold one completed ``process_data`` call into the stage's aggregate."""
    with _FLOW_LOCK:
        agg = _FLOW.setdefault(name, _new_flow())
        agg["batches"] += 1
        agg["busy_s"] += busy_s


def record_stage_flow(
    name: str, *, queue_depth: int, busy_frac: float, workers: int
) -> None:
    """Fold one runner-tick snapshot (input-queue depth, worker busy
    fraction over the tick window, live workers) into the aggregate and
    forward it to the engine's gauges (no-op when the exporter is absent)."""
    with _FLOW_LOCK:
        agg = _FLOW.setdefault(name, _new_flow())
        agg["ticks"] += 1
        agg["queue_depth"] = queue_depth
        agg["queue_depth_peak"] = max(agg["queue_depth_peak"], queue_depth)
        agg["busy_frac"] = busy_frac
        agg["busy_frac_sum"] += busy_frac
        agg["workers"] = workers
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        m = get_metrics()
        m.set_stage_busy(name, busy_frac)
        m.set_pool_state(name, workers, 0, queue_depth)
    except Exception:  # metrics must never take down the runner loop
        pass


def stage_flow_summaries() -> dict[str, dict]:
    """name -> busy/queue aggregate. ``busy_frac_mean`` is the average
    worker-busy fraction across ticks: ≈1 means the stage's workers were
    saturated (the bottleneck); ≈0 with a deep queue downstream means the
    stage is starved or over-provisioned."""
    out: dict[str, dict] = {}
    with _FLOW_LOCK:
        items = {k: dict(v) for k, v in _FLOW.items()}
    for name, agg in items.items():
        out[name] = {
            "batches": agg["batches"],
            "busy_s": round(agg["busy_s"], 4),
            "queue_depth": agg["queue_depth"],
            "queue_depth_peak": agg["queue_depth_peak"],
            "busy_frac": round(agg["busy_frac"], 4),
            "busy_frac_mean": (
                round(agg["busy_frac_sum"] / agg["ticks"], 4) if agg["ticks"] else 0.0
            ),
            "workers": agg["workers"],
            "node": node_id(),
        }
    return out


def reset_stage_flow() -> None:
    with _FLOW_LOCK:
        _FLOW.clear()


# ---------------------------------------------------------------------------
# Caption-engine phase aggregates (pipelines/video/stages/captioning.py et
# al.): per-stage prep / vision-encode / prefill / decode / idle seconds per
# engine drive, plus shared-prefix cache traffic. Bounded per-stage
# aggregates; the caption benchmark and flight recorder read them to
# attribute the caption critical path.
_CAPTION_LOCK = threading.Lock()
_CAPTION: dict[str, dict] = {}

_CAPTION_PHASE_KEYS = (
    "prep_s", "vision_encode_s", "prefill_s", "decode_s", "idle_s", "wall_s",
)
_CAPTION_COUNT_KEYS = (
    "requests", "prefill_tokens", "prefix_cache_hits", "prefix_cache_misses",
    "prefix_tokens_saved", "vision_encodes", "vision_reuses",
    # paged-KV + cross-job deltas (models/vlm/engine.py): shared prefix
    # BLOCK references served copy-free, copy-on-write tail duplications,
    # and decode steps whose active slots spanned 2+ owners
    "prefix_block_refs", "kv_cow_copies", "interleaved_steps",
    # paged-attention deltas (ops/paged_attention.py): decode steps served
    # without a gathered working set + the view bytes never materialized
    "paged_kernel_steps", "kv_gather_bytes_avoided",
    "decode_tokens",
)
# absolute occupancy gauges riding each drive record: totals overwrite,
# peaks take the max across drives
_CAPTION_GAUGE_KEYS = ("kv_blocks_total", "kv_blocks_used")
_CAPTION_PEAK_KEYS = ("kv_blocks_peak",)


def _new_caption() -> dict:
    agg = {k: 0.0 for k in _CAPTION_PHASE_KEYS}
    agg.update({k: 0 for k in _CAPTION_COUNT_KEYS})
    agg.update({k: 0 for k in _CAPTION_GAUGE_KEYS + _CAPTION_PEAK_KEYS})
    agg["drives"] = 0
    agg["owners"] = {}
    return agg


def record_caption_phases(name: str, phases: dict) -> None:
    """Fold one engine drive's phase/cache deltas into the stage's
    aggregate and forward them to the engine's metrics exporter (no-op when
    absent). ``idle_s`` is wall minus device phases (prefill + decode):
    the engine-stall signal the prep/decode overlap exists to shrink. A
    drive carrying an ``owner`` tag also folds into the per-owner
    sub-aggregate — the run report's cross-job accounting."""
    with _CAPTION_LOCK:
        agg = _CAPTION.setdefault(name, _new_caption())
        agg["drives"] += 1
        for k in _CAPTION_PHASE_KEYS:
            agg[k] += float(phases.get(k, 0.0))
        for k in _CAPTION_COUNT_KEYS:
            agg[k] += int(phases.get(k, 0))
        for k in _CAPTION_GAUGE_KEYS:
            if k in phases:
                agg[k] = int(phases[k])
        for k in _CAPTION_PEAK_KEYS:
            if k in phases:
                agg[k] = max(agg[k], int(phases[k]))
        owner = phases.get("owner")
        if owner:
            sub = agg["owners"].setdefault(
                str(owner), {"drives": 0, "requests": 0, "decode_tokens": 0}
            )
            sub["drives"] += 1
            sub["requests"] += int(phases.get("requests", 0))
            sub["decode_tokens"] += int(phases.get("decode_tokens", 0))
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_caption_phases(name, phases)
    except Exception:  # metrics must never take down the caption path
        pass


def caption_phase_summaries() -> dict[str, dict]:
    """name -> caption phase aggregate. ``idle_frac`` is engine idle over
    wall for the stage's drives: ≈0 means the engine was prefilling or
    decoding for the whole window (prep fully hidden); large values mean
    the stage starved the engine between batches. ``owners`` carries the
    per-owner sub-aggregates (cross-job accounting)."""
    out: dict[str, dict] = {}
    with _CAPTION_LOCK:
        items = {
            k: {**v, "owners": {o: dict(s) for o, s in v["owners"].items()}}
            for k, v in _CAPTION.items()
        }
    for name, agg in items.items():
        wall = agg["wall_s"]
        out[name] = {
            **{k: round(agg[k], 4) for k in _CAPTION_PHASE_KEYS},
            **{k: agg[k] for k in _CAPTION_COUNT_KEYS},
            **{k: agg[k] for k in _CAPTION_GAUGE_KEYS + _CAPTION_PEAK_KEYS},
            "drives": agg["drives"],
            "owners": agg["owners"],
            "idle_frac": round(agg["idle_s"] / wall, 4) if wall > 0 else 0.0,
        }
    return out


def reset_caption_phases() -> None:
    with _CAPTION_LOCK:
        _CAPTION.clear()


# ---------------------------------------------------------------------------
# Corpus-index aggregates (dedup/corpus_index.py + the writer's in-pipeline
# fragment appends): vectors added, query batches, probe fan-out, and the
# wall time each side cost. Bounded per-name aggregates like the rest of
# this module; the ``pipeline_index_*`` prometheus counters carry the
# stream and the flight recorder snapshots the summary into run_report.
_INDEX_LOCK = threading.Lock()
_INDEX: dict[str, dict] = {}

INDEX_OP_KEYS = (
    "adds", "add_s", "queries", "query_s", "probes", "duplicates",
    "skipped_random",
)


def _new_index_agg() -> dict:
    return {k: 0.0 for k in INDEX_OP_KEYS}


def record_index_ops(name: str, **deltas: float) -> None:
    """Fold corpus-index operation deltas (any subset of INDEX_OP_KEYS)
    into ``name``'s aggregate and forward them to the engine's
    ``pipeline_index_*`` counters (no-op without an exporter)."""
    with _INDEX_LOCK:
        agg = _INDEX.setdefault(name, _new_index_agg())
        for k, v in deltas.items():
            if k in INDEX_OP_KEYS:
                agg[k] += float(v)
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_index(name, deltas)
    except Exception:  # metrics must never take down an index operation
        pass


def index_op_summaries() -> dict[str, dict]:
    """name -> index aggregate. ``probe_fanout_mean`` is non-empty probed
    shards per query vector (≈ the effective nprobe) — the knob-vs-recall
    signal (raise nprobe, pay more shard matmuls); ``queries_per_sec`` is
    the headline the bench row carries."""
    out: dict[str, dict] = {}
    with _INDEX_LOCK:
        items = {k: dict(v) for k, v in _INDEX.items()}
    for name, agg in items.items():
        out[name] = {
            "adds": int(agg["adds"]),
            "add_s": round(agg["add_s"], 4),
            "queries": int(agg["queries"]),
            "query_s": round(agg["query_s"], 4),
            "probes": int(agg["probes"]),
            "duplicates": int(agg["duplicates"]),
            "skipped_random": int(agg["skipped_random"]),
            "probe_fanout_mean": (
                round(agg["probes"] / agg["queries"], 4) if agg["queries"] else 0.0
            ),
            "queries_per_sec": (
                round(agg["queries"] / agg["query_s"], 2) if agg["query_s"] > 0 else 0.0
            ),
            "node": node_id(),
        }
    return out


def reset_index_ops() -> None:
    with _INDEX_LOCK:
        _INDEX.clear()


# ---------------------------------------------------------------------------
# Search-serving aggregates (dedup/index_server.py + service /v1/search):
# request counts, latency percentiles (bounded reservoir), warm-shard-cache
# byte traffic, and compaction generations. The SLO surface of the
# index-server read path: p50/p99 land in run_report.json and BENCH rows;
# the ``search_latency_seconds`` prometheus histogram carries the stream.
_SEARCH_LOCK = threading.Lock()
_SEARCH: dict[str, dict] = {}
_SEARCH_LATENCY_CAP = 4096

SEARCH_KEYS = (
    "searches", "queries", "search_s", "batches", "batched_requests",
    "cache_hit_bytes", "cache_miss_bytes", "cache_evicted_bytes",
    "compactions", "compaction_s", "generations_adopted", "shed",
)


def _new_search_agg() -> dict:
    return {**{k: 0.0 for k in SEARCH_KEYS}, "generation": 0, "latencies": []}


def record_search(
    name: str, *, latency_s: float | None = None, mode: str = "clip",
    generation: int | None = None, **deltas: float,
) -> None:
    """Fold search-serving deltas (any subset of SEARCH_KEYS) into
    ``name``'s aggregate; ``latency_s`` lands in a bounded reservoir
    (random replacement once full, so percentiles stay an unbiased sample
    of the whole run, not the first N requests). Forwards to the
    ``search_*`` prometheus series (no-op without an exporter)."""
    with _SEARCH_LOCK:
        agg = _SEARCH.setdefault(name, _new_search_agg())
        for k, v in deltas.items():
            if k in SEARCH_KEYS:
                agg[k] += float(v)
        if generation is not None:
            agg["generation"] = max(agg["generation"], int(generation))
        if latency_s is not None:
            res = agg["latencies"]
            if len(res) < _SEARCH_LATENCY_CAP:
                res.append(float(latency_s))
            else:
                import random

                res[random.randrange(_SEARCH_LATENCY_CAP)] = float(latency_s)
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_search(name, mode, latency_s, deltas)
    except Exception:  # metrics must never take down the read path
        pass


def search_summaries() -> dict[str, dict]:
    """name -> search aggregate with the SLO headline: ``latency_p50_ms``
    / ``latency_p99_ms`` over the reservoir, ``qps`` (requests over summed
    serving-loop BUSY seconds — ``search_s`` is recorded per micro-batch,
    so many concurrent requests amortize one batch's wall and qps exceeds
    1/latency), and ``cache_hit_ratio`` by bytes (hot path served from
    resident shards)."""
    import numpy as _np

    out: dict[str, dict] = {}
    with _SEARCH_LOCK:
        items = {
            k: {**v, "latencies": list(v["latencies"])} for k, v in _SEARCH.items()
        }
    for name, agg in items.items():
        lat = agg.pop("latencies")
        hit = agg["cache_hit_bytes"]
        touched = hit + agg["cache_miss_bytes"]
        out[name] = {
            **{k: (round(agg[k], 4) if k.endswith("_s") else int(agg[k])) for k in SEARCH_KEYS},
            "generation": int(agg["generation"]),
            "latency_p50_ms": round(float(_np.percentile(lat, 50)) * 1e3, 3) if lat else 0.0,
            "latency_p99_ms": round(float(_np.percentile(lat, 99)) * 1e3, 3) if lat else 0.0,
            "qps": round(agg["searches"] / agg["search_s"], 2) if agg["search_s"] > 0 else 0.0,
            "cache_hit_ratio": round(hit / touched, 4) if touched > 0 else 0.0,
            "node": node_id(),
        }
    return out


def reset_search() -> None:
    with _SEARCH_LOCK:
        _SEARCH.clear()


# ---------------------------------------------------------------------------
# Anomaly aggregates (observability/anomaly.py): the stall/anomaly
# detector's verdicts, folded per (stage, kind) with a bounded tail of
# recent structured events. Same contract as the rest of this module —
# bounded aggregates, never a log; the ``pipeline_anomalies_total``
# counters carry the stream and the flight recorder snapshots the summary
# into run_report.json's ``anomalies`` section.
_ANOMALY_LOCK = threading.Lock()
_ANOMALY_COUNTS: dict[tuple[str, str], int] = {}
_ANOMALY_RECENT: "deque" = None  # created lazily (collections import below)
_ANOMALY_RECENT_CAP = 64


def record_anomaly(event: dict) -> None:
    """Fold one detector verdict (``{"kind", "stage", ...}``) into the
    per-(stage, kind) counts + the bounded recent-events tail, and forward
    it to the ``pipeline_anomalies_total`` counter (no-op without an
    exporter)."""
    global _ANOMALY_RECENT
    kind = str(event.get("kind") or "unknown")
    stage = str(event.get("stage") or "_run")
    with _ANOMALY_LOCK:
        if _ANOMALY_RECENT is None:
            from collections import deque as _deque

            _ANOMALY_RECENT = _deque(maxlen=_ANOMALY_RECENT_CAP)
        _ANOMALY_COUNTS[(stage, kind)] = _ANOMALY_COUNTS.get((stage, kind), 0) + 1
        _ANOMALY_RECENT.append(dict(event))
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_anomaly(stage, kind)
    except Exception:  # metrics must never take down the watchdog
        pass


def anomaly_summaries() -> dict:
    """``{"total", "counts": {"<stage>/<kind>": n}, "recent": [...]}`` —
    what the flight recorder writes as run_report.json's ``anomalies``
    section and live snapshots embed as detector verdicts."""
    with _ANOMALY_LOCK:
        counts = {f"{s}/{k}": n for (s, k), n in _ANOMALY_COUNTS.items()}
        recent = list(_ANOMALY_RECENT or ())
    if not counts:
        return {}
    return {"total": sum(counts.values()), "counts": counts, "recent": recent}


def reset_anomalies() -> None:
    with _ANOMALY_LOCK:
        _ANOMALY_COUNTS.clear()
        if _ANOMALY_RECENT is not None:
            _ANOMALY_RECENT.clear()


# ---------------------------------------------------------------------------
# Object-plane transfer aggregates (engine/object_channel.py consumers): how
# many bytes crossed hosts, how long consumers WAITED for them, and whether
# push-ahead prefetch hid the transfer behind compute. Bounded per-process
# aggregates; node agents relay theirs to the driver over the control link
# (remote_plane.AgentStats), which folds them per node here.
_OP_LOCK = threading.Lock()
_OP: dict[str, float] = {}
# driver-side fold of AgentStats deltas: node_id -> aggregate
_OP_NODES: dict[str, dict] = {}

OBJECT_PLANE_KEYS = (
    # demand fetches: the consumer BLOCKED on the transfer (wait == transfer)
    "fetches", "fetch_bytes", "fetch_wait_s",
    # push-ahead transfers: moved in the background while compute ran
    "prefetches", "prefetch_bytes", "prefetch_transfer_s",
    # consumer-side cache outcomes: a hit's wait is ~0 (the bytes were
    # already local); prefetch working == hits > 0 and
    # prefetch_hit_wait_s << prefetch_transfer_s
    "prefetch_hits", "prefetch_hit_wait_s", "prefetch_misses",
    # local store reads on the worker fetch pool (shm, not network)
    "store_reads", "store_read_bytes", "store_read_wait_s",
)


def _new_op() -> dict:
    return {k: 0.0 for k in OBJECT_PLANE_KEYS}


def record_object_plane(**deltas: float) -> None:
    """Fold object-plane deltas (any subset of OBJECT_PLANE_KEYS) into this
    process's aggregate and forward them to the prometheus counters under
    this process's node id (no-op without an exporter)."""
    with _OP_LOCK:
        for k, v in deltas.items():
            if k in OBJECT_PLANE_KEYS:
                _OP[k] = _OP.get(k, 0.0) + float(v)
    # a CPU worker may record store_reads without ever dispatching to a
    # device — it still owes the parent a dump at exit
    _maybe_register_dump()
    _forward_object_plane(node_id(), deltas)


def record_node_object_plane(node: str, deltas: dict) -> None:
    """Driver-side fold of one agent's relayed object-plane DELTAS."""
    with _OP_LOCK:
        agg = _OP_NODES.setdefault(node, _new_op())
        for k in OBJECT_PLANE_KEYS:
            agg[k] += float(deltas.get(k, 0.0))
    _forward_object_plane(node, deltas)


def _forward_object_plane(node: str, deltas: dict) -> None:
    try:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics().observe_object_plane(node, deltas)
    except Exception:  # metrics must never take down a transfer path
        pass


def object_plane_summaries() -> dict[str, dict]:
    """node_id -> object-plane aggregate: this process's own traffic under
    its node id, plus every agent's relayed aggregate. Integer-valued
    counters render as ints for readability."""
    out: dict[str, dict] = {}
    with _OP_LOCK:
        own = dict(_OP)
        nodes = {n: dict(a) for n, a in _OP_NODES.items()}
    if any(own.get(k) for k in OBJECT_PLANE_KEYS):
        nodes.setdefault(node_id(), _new_op())
        for k in OBJECT_PLANE_KEYS:
            nodes[node_id()][k] += own.get(k, 0.0)
    for node, agg in nodes.items():
        out[node] = {
            k: round(agg[k], 4) if k.endswith("_s") else int(agg[k])
            for k in OBJECT_PLANE_KEYS
        }
    return out


def object_plane_snapshot_delta(prev: dict | None) -> tuple[dict, dict]:
    """(current_totals, delta_since_prev) of this process's own aggregate —
    what a node agent ships in each AgentStats frame (deltas, so driver-side
    folding is idempotent across reconnects)."""
    with _OP_LOCK:
        cur = {k: _OP.get(k, 0.0) for k in OBJECT_PLANE_KEYS}
    prev = prev or {}
    delta = {k: cur[k] - float(prev.get(k, 0.0)) for k in OBJECT_PLANE_KEYS}
    return cur, {k: v for k, v in delta.items() if v}


def reset_object_plane() -> None:
    with _OP_LOCK:
        _OP.clear()
        _OP_NODES.clear()


def dispatch_summaries() -> dict[str, dict]:
    """name -> aggregate per-dispatch timings, including aggregates merged
    in from worker dump files. ``gap_frac`` is device idle over total
    device-relevant wall (gap + compute): < 0.2 means the host kept the
    device fed for >80% of the stage's device window."""
    with _DISPATCH_LOCK:
        items = {k: dict(v) for k, v in _DISPATCH.items()}
        for name, agg in _FOLDED.items():
            _fold(items.setdefault(name, _new_agg()), agg)
    return _summarize(items)


def _summarize(items: dict[str, dict]) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for name, agg in items.items():
        busy = agg["gap_s"] + agg["compute_s"]
        out[name] = {
            "dispatches": agg["dispatches"],
            "rows": agg["rows"],
            "padded_rows": agg["padded_rows"],
            "h2d_s": round(agg["h2d_s"], 4),
            "compute_s": round(agg["compute_s"], 4),
            "d2h_s": round(agg["d2h_s"], 4),
            "gap_s": round(agg["gap_s"], 4),
            "gap_frac": round(agg["gap_s"] / busy, 4) if busy > 0 else 0.0,
            # merged multi-node reports attribute dispatch gaps per node,
            # not just per stage — dumps from an agent's workers carry the
            # agent's node id (NODE_ID_ENV rides StartWorker env)
            "node": agg.get("node") or node_id(),
        }
    return out
