"""Per-task stage timing statistics.

Equivalent capability of the reference's ``StageTimer``
(cosmos_curate/core/utils/infra/performance_utils.py — per-task wall/idle
stats behind ``--perf-profile``, feeding the summary and spans).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import numpy as np


@dataclass
class StageTimer:
    stage_name: str
    samples_s: list[float] = field(default_factory=list)
    idle_s: float = 0.0
    _last_end: float | None = None

    @contextlib.contextmanager
    def time_process(self):
        start = time.monotonic()
        if self._last_end is not None:
            self.idle_s += start - self._last_end
        try:
            yield
        finally:
            end = time.monotonic()
            self.samples_s.append(end - start)
            self._last_end = end

    def summary(self) -> dict:
        arr = np.asarray(self.samples_s)
        if arr.size == 0:
            return {"stage": self.stage_name, "count": 0}
        return {
            "stage": self.stage_name,
            "count": int(arr.size),
            "total_s": float(arr.sum()),
            "mean_s": float(arr.mean()),
            "p50_s": float(np.percentile(arr, 50)),
            "p90_s": float(np.percentile(arr, 90)),
            "max_s": float(arr.max()),
            "idle_s": self.idle_s,
        }
