"""Per-stage profiling with zero stage-code changes.

Equivalent capability of the reference's profiling layer
(cosmos_curate/core/utils/infra/profiling.py — CPU/memory/GPU backends
injected by dynamic subclassing via ``profiling_wrapper``:1129 and driven by
``profiling_scope``:1301). Backends here: cProfile (stdlib; pyinstrument is
not in this image) for CPU, tracemalloc for memory, and ``jax.profiler``
traces for device stages (the TPU answer to torch.profiler). Artifacts land
under ``<output>/profile/{cpu,memory,device}/``.
"""

from __future__ import annotations

import cProfile
import io
import os
import pstats
import tracemalloc
from dataclasses import dataclass, field
from typing import Any

from cosmos_curate_tpu.core.stage import Stage
from cosmos_curate_tpu.storage.client import write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class ProfilingConfig:
    cpu: bool = False
    memory: bool = False
    device: bool = False  # jax.profiler trace around process_data
    output_path: str = "/tmp/curate_profile"
    top_n: int = 50


def profiling_wrapper(stage: Stage, config: ProfilingConfig) -> Stage:
    """Wrap a stage instance so its hot methods are profiled; the stage's
    class is subclassed dynamically (the reference's trick) so isinstance
    checks and all behavior survive."""
    cls = type(stage)

    class ProfiledStage(cls):  # type: ignore[misc, valid-type]
        def process_data(self, tasks):  # noqa: D102
            return _profiled_call(self, cls.process_data, config, tasks)

        def destroy(self):  # noqa: D102
            _flush_profiles(self, config)
            cls.destroy(self)

    # stage.name resolves _display_name set by any earlier wrapper, so the
    # user-visible stage name survives stacked dynamic subclassing
    display = stage.name
    stage.__class__ = ProfiledStage
    stage._profile_state = _ProfileState()  # type: ignore[attr-defined]
    stage._profile_name = display  # type: ignore[attr-defined]
    stage._display_name = display  # type: ignore[attr-defined]
    return stage


@dataclass
class _ProfileState:
    profiler: cProfile.Profile | None = None
    calls: int = 0
    mem_snapshots: list[str] = field(default_factory=list)


def _profiled_call(stage: Any, fn, config: ProfilingConfig, tasks):
    state: _ProfileState = stage._profile_state
    state.calls += 1
    ctx_device = None
    if config.device:
        import jax

        trace_dir = f"{config.output_path}/device/{stage._profile_name}"
        os.makedirs(trace_dir, exist_ok=True)
        ctx_device = jax.profiler.trace(trace_dir)
        ctx_device.__enter__()
    if config.memory and not tracemalloc.is_tracing():
        tracemalloc.start()
    if config.cpu:
        if state.profiler is None:
            state.profiler = cProfile.Profile()
        state.profiler.enable()
    try:
        return fn(stage, tasks)
    finally:
        if config.cpu and state.profiler is not None:
            state.profiler.disable()
        if config.memory:
            current, peak = tracemalloc.get_traced_memory()
            state.mem_snapshots.append(f"call {state.calls}: current={current} peak={peak}")
            tracemalloc.reset_peak()
        if ctx_device is not None:
            ctx_device.__exit__(None, None, None)


def _flush_profiles(stage: Any, config: ProfilingConfig) -> None:
    state: _ProfileState = getattr(stage, "_profile_state", None)
    if state is None:
        return
    name = getattr(stage, "_profile_name", type(stage).__name__)
    pid = os.getpid()
    if config.cpu and state.profiler is not None:
        buf = io.StringIO()
        pstats.Stats(state.profiler, stream=buf).sort_stats("cumulative").print_stats(
            config.top_n
        )
        write_bytes(f"{config.output_path}/cpu/{name}-{pid}.txt", buf.getvalue().encode())
    if config.memory and state.mem_snapshots:
        write_bytes(
            f"{config.output_path}/memory/{name}-{pid}.txt",
            "\n".join(state.mem_snapshots).encode(),
        )
    logger.info("profiling artifacts flushed for %s (%d calls)", name, state.calls)
