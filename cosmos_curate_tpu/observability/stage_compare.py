"""Golden-output comparison: recursive diff of task trees with tolerances.

Equivalent capability of the reference's stage compare harness
(cosmos_curate/core/utils/misc/stage_compare.py:40-376 — comparator
registry, recursive attrs/array diff with atol, ``CompareReport``,
pass-rate threshold; used by ``--stage-compare`` for golden regression
runs).
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any

import numpy as np


@dataclass
class Mismatch:
    path: str
    reason: str


@dataclass
class CompareReport:
    total: int = 0
    passed: int = 0
    mismatches: list[Mismatch] = field(default_factory=list)

    @property
    def pass_rate(self) -> float:
        return self.passed / self.total if self.total else 1.0

    def ok(self, threshold: float = 1.0) -> bool:
        return self.pass_rate >= threshold

    def summary(self) -> str:
        lines = [f"compare: {self.passed}/{self.total} passed ({self.pass_rate:.1%})"]
        lines += [f"  {m.path}: {m.reason}" for m in self.mismatches[:20]]
        if len(self.mismatches) > 20:
            lines.append(f"  … {len(self.mismatches) - 20} more")
        return "\n".join(lines)


def _diff(a: Any, b: Any, path: str, out: list[Mismatch], atol: float, ignore: set[str]) -> None:
    if type(a) is not type(b) and not (
        isinstance(a, (int, float)) and isinstance(b, (int, float))
    ):
        out.append(Mismatch(path, f"type {type(a).__name__} != {type(b).__name__}"))
        return
    if isinstance(a, np.ndarray):
        if a.shape != b.shape:
            out.append(Mismatch(path, f"shape {a.shape} != {b.shape}"))
        elif a.dtype.kind in "fc" or b.dtype.kind in "fc":
            if not np.allclose(a, b, atol=atol, equal_nan=True):
                out.append(Mismatch(path, f"max |Δ| {np.abs(a - b).max():.3e} > atol {atol}"))
        elif not np.array_equal(a, b):
            out.append(Mismatch(path, "arrays differ"))
        return
    if isinstance(a, float):
        if abs(a - b) > atol and not (np.isnan(a) and np.isnan(b)):
            out.append(Mismatch(path, f"{a} != {b} (atol {atol})"))
        return
    if isinstance(a, (int, str, bytes, bool, type(None))):
        if a != b:
            out.append(Mismatch(path, f"{a!r} != {b!r}"))
        return
    if isinstance(a, dict):
        for k in sorted(set(a) | set(b), key=str):
            if str(k) in ignore:
                continue
            if k not in a or k not in b:
                out.append(Mismatch(f"{path}.{k}", "missing on one side"))
            else:
                _diff(a[k], b[k], f"{path}.{k}", out, atol, ignore)
        return
    if isinstance(a, (list, tuple)):
        if len(a) != len(b):
            out.append(Mismatch(path, f"length {len(a)} != {len(b)}"))
            return
        for i, (x, y) in enumerate(zip(a, b)):
            _diff(x, y, f"{path}[{i}]", out, atol, ignore)
        return
    if is_dataclass(a):
        for f in fields(a):
            if f.name in ignore:
                continue
            _diff(getattr(a, f.name), getattr(b, f.name), f"{path}.{f.name}", out, atol, ignore)
        return
    if hasattr(a, "__dict__"):
        _diff(vars(a), vars(b), path, out, atol, ignore)
        return
    if a != b:
        out.append(Mismatch(path, f"{a!r} != {b!r}"))


def compare_tasks(
    actual: list,
    golden: list,
    *,
    atol: float = 1e-5,
    ignore_fields: tuple[str, ...] = ("stage_perf",),
) -> CompareReport:
    """Compare two task lists item-by-item; an item passes if it produced
    zero mismatches."""
    report = CompareReport()
    if len(actual) != len(golden):
        report.total = max(len(actual), len(golden))
        report.mismatches.append(
            Mismatch("$", f"task count {len(actual)} != {len(golden)}")
        )
        return report
    ignore = set(ignore_fields)
    for i, (a, g) in enumerate(zip(actual, golden)):
        found: list[Mismatch] = []
        _diff(a, g, f"task[{i}]", found, atol, ignore)
        report.total += 1
        if found:
            report.mismatches.extend(found)
        else:
            report.passed += 1
    return report
