"""Two-layer tracing: a dependency-light span API + pluggable backends.

Equivalent capability of the reference's tracing design
(cosmos_curate/core/utils/infra/tracing.py:326-770 public API — TracedSpan /
traced_span / @traced, no-ops when disabled — and tracing_hook.py's
per-worker NDJSON export). Spans are recorded to one NDJSON file per process
(collectable post-run) and, when an OTLP endpoint is configured
(``OTEL_EXPORTER_OTLP_ENDPOINT`` / ``CURATE_OTLP_ENDPOINT``), exported to a
real collector over OTLP/HTTP JSON — encoded directly against the public
OTLP schema, no opentelemetry SDK needed. Disabled = zero-cost: every call
path short-circuits on one boolean.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

_enabled = False
_backends: list = []
_local = threading.local()


@dataclass
class TracedSpan:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_s: float
    attributes: dict[str, Any] = field(default_factory=dict)
    end_s: float | None = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.time()) - self.start_s


class _NdjsonBackend:
    """Buffers span records and flushes through the storage layer, so a
    remote output root (s3://, gs://) receives traces like every other
    artifact instead of a bogus local directory."""

    FLUSH_EVERY = 200

    def __init__(self, path: str) -> None:
        self.path = path
        self._lines: list[str] = []
        self._lock = threading.Lock()

    def export(self, span: TracedSpan) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_s": span.start_s,
            "end_s": span.end_s,
            "duration_s": span.duration_s,
            "attributes": span.attributes,
            "pid": os.getpid(),
        }
        with self._lock:
            self._lines.append(json.dumps(record))
            if len(self._lines) % self.FLUSH_EVERY == 0:
                self._flush_locked()

    def _flush_locked(self) -> None:
        from cosmos_curate_tpu.storage.client import write_bytes

        write_bytes(self.path, ("\n".join(self._lines) + "\n").encode())

    def close(self) -> None:
        with self._lock:
            if self._lines:
                self._flush_locked()


class _OtlpHttpBackend:
    """OTLP/HTTP JSON trace exporter (opentelemetry-proto trace service
    schema, JSON encoding) — POSTs span batches to ``{endpoint}/v1/traces``
    with stdlib urllib; errors are logged once and never break the pipeline.
    """

    BATCH = 100
    MAX_QUEUED_BATCHES = 8

    def __init__(self, endpoint: str, service_name: str = "cosmos-curate-tpu") -> None:
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self._spans: list[TracedSpan] = []
        self._lock = threading.Lock()
        self._warned = False
        # posts happen on a background thread so a blackholed collector can
        # never stall traced application threads; full queue = drop batch
        import queue as queue_mod

        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.MAX_QUEUED_BATCHES)
        self._sender = threading.Thread(target=self._send_loop, daemon=True)
        self._sender.start()

    def _send_loop(self) -> None:
        while True:
            batch = self._q.get()
            if batch is None:
                return
            self._post(batch)

    @staticmethod
    def _attr(key: str, value: Any) -> dict[str, Any]:
        if isinstance(value, bool):
            v: dict[str, Any] = {"boolValue": value}
        elif isinstance(value, int):
            v = {"intValue": str(value)}
        elif isinstance(value, float):
            v = {"doubleValue": value}
        else:
            v = {"stringValue": str(value)}
        return {"key": key, "value": v}

    def _encode(self, spans: list[TracedSpan]) -> bytes:
        otlp_spans = []
        for s in spans:
            rec = {
                "traceId": s.trace_id.ljust(32, "0"),
                "spanId": s.span_id,
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int(s.start_s * 1e9)),
                "endTimeUnixNano": str(int((s.end_s or s.start_s) * 1e9)),
                "attributes": [self._attr(k, v) for k, v in s.attributes.items()],
                "status": (
                    {"code": 2, "message": str(s.attributes["error"])}
                    if "error" in s.attributes
                    else {"code": 1}
                ),
            }
            if s.parent_id:
                rec["parentSpanId"] = s.parent_id
            otlp_spans.append(rec)
        payload = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            self._attr("service.name", self.service_name),
                            self._attr("process.pid", os.getpid()),
                        ]
                    },
                    "scopeSpans": [
                        {"scope": {"name": "cosmos_curate_tpu.tracing"}, "spans": otlp_spans}
                    ],
                }
            ]
        }
        return json.dumps(payload).encode()

    def export(self, span: TracedSpan) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) >= self.BATCH:
                batch, self._spans = self._spans, []
            else:
                return
        try:
            self._q.put_nowait(batch)
        except Exception:
            if not self._warned:
                self._warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "OTLP export queue full; dropping span batches (collector at %s "
                    "unreachable or slow)", self.url,
                )

    def _post(self, batch: list[TracedSpan]) -> None:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.url,
            data=self._encode(batch),
            headers={"content-type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10):
                pass
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
            if not self._warned:
                self._warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "OTLP trace export to %s failing (%s); further errors suppressed",
                    self.url,
                    e,
                )

    def close(self) -> None:
        with self._lock:
            batch, self._spans = self._spans, []
        if batch:
            try:
                self._q.put_nowait(batch)
            except Exception:
                pass
        try:
            self._q.put_nowait(None)
        except Exception:
            return  # queue jammed by a dead collector; daemon thread dies with us
        self._sender.join(timeout=15)


def otlp_endpoint_from_env() -> str | None:
    return os.environ.get("CURATE_OTLP_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )


def default_staging_dir() -> str:
    """Per-run staging dir: concurrent pipelines on one host must not sweep
    each other's artifacts. The run id is the coordinator pid, which the
    engine already propagates to workers as CURATE_STORE_OWNER."""
    run = os.environ.get("CURATE_STORE_OWNER", str(os.getpid()))
    return os.environ.get("CURATE_TRACE_DIR", f"/tmp/curate_traces/run-{run}")


def enable_tracing(
    output_path: str | None = None, *, otlp_endpoint: str | None = None
) -> str:
    """Turn tracing on for this process; returns the NDJSON path. An OTLP
    collector endpoint (argument or env) adds a second export backend."""
    global _enabled, _backends
    path = output_path or os.environ.get(
        "CURATE_TRACE_PATH", f"{default_staging_dir()}/trace-{os.getpid()}.ndjson"
    )
    for b in _backends:  # re-enable must not drop buffered spans
        b.close()
    _backends = [_NdjsonBackend(path)]
    endpoint = otlp_endpoint or otlp_endpoint_from_env()
    if endpoint:
        _backends.append(_OtlpHttpBackend(endpoint))
    _enabled = True
    return path


def disable_tracing() -> None:
    global _enabled, _backends
    _enabled = False
    for b in _backends:
        b.close()
    _backends = []


def tracing_enabled() -> bool:
    return _enabled


def _current_stack() -> list[TracedSpan]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


@contextlib.contextmanager
def traced_span(name: str, **attributes: Any) -> Iterator[TracedSpan]:
    """Context manager span; cheap no-op (yields a dummy) when disabled."""
    if not _enabled:
        yield _NOOP_SPAN
        return
    stack = _current_stack()
    parent = stack[-1] if stack else None
    span = TracedSpan(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
        parent_id=parent.span_id if parent else None,
        start_s=time.time(),
        attributes=dict(attributes),
    )
    stack.append(span)
    try:
        yield span
    except Exception as e:
        span.attributes["error"] = repr(e)
        raise
    finally:
        span.end_s = time.time()
        stack.pop()
        for b in _backends:
            b.export(span)


class _NoopSpan(TracedSpan):
    def set_attribute(self, key: str, value: Any) -> None:
        pass  # shared module-global: must not accumulate state


_NOOP_SPAN = _NoopSpan("noop", "0", "0", None, 0.0)


def traced(fn: Callable | None = None, *, name: str | None = None):
    """Decorator form of ``traced_span``."""

    def deco(f: Callable) -> Callable:
        span_name = name or f"{f.__module__}.{f.__qualname__}"

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return f(*args, **kwargs)
            with traced_span(span_name):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def setup_tracing_from_env() -> None:
    """Worker startup hook (reference tracing_hook.setup_tracing): enables
    tracing when CURATE_TRACING=1 is in the environment."""
    if os.environ.get("CURATE_TRACING") == "1":
        enable_tracing()
