"""Two-layer tracing: a dependency-light span API + pluggable backends.

Equivalent capability of the reference's tracing design
(cosmos_curate/core/utils/infra/tracing.py:326-770 public API — TracedSpan /
traced_span / @traced, no-ops when disabled — and tracing_hook.py's
per-worker NDJSON export). Spans are recorded to one NDJSON file per process
(collectable post-run) and, when an OTLP endpoint is configured
(``OTEL_EXPORTER_OTLP_ENDPOINT`` / ``CURATE_OTLP_ENDPOINT``), exported to a
real collector over OTLP/HTTP JSON — encoded directly against the public
OTLP schema, no opentelemetry SDK needed. Disabled = zero-cost: every call
path short-circuits on one boolean.

Cross-boundary propagation uses the W3C trace-context wire format
(``00-<32 hex trace_id>-<16 hex span_id>-01``):

- ``format_traceparent()`` / ``parse_traceparent()`` — the header itself;
- ``traced_span(..., traceparent=...)`` — restore an incoming context as
  the span's parent (how a worker's per-batch span parents onto the
  driver's stage span across a ``SubmitBatch`` frame);
- ``attach_traceparent()`` — process-level base parent (how a SPAWNED
  worker's setup spans parent onto the driver's run span: the driver
  stamps ``CURATE_TRACEPARENT`` into the worker env, and
  ``setup_tracing_from_env`` attaches it);
- the active-span stack lives in a ``contextvars.ContextVar`` of
  immutable tuples, so ``contextvars.copy_context()`` carries it across
  thread-pool hops (the pipelined runner's worker threads), which
  ``threading.local`` cannot.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

_enabled = False
_backends: list = []
# Innermost-last active spans for the CURRENT context. Immutable tuple:
# copied contexts (thread hops) must never share a mutable stack.
_stack: "contextvars.ContextVar[tuple]" = contextvars.ContextVar(
    "curate_trace_stack", default=()
)
# True while exporting/flushing spans: a storage.write span created by the
# NDJSON backend's own flush would deadlock on the backend lock (and spam
# the trace with self-referential spans).
_suppress: "contextvars.ContextVar[bool]" = contextvars.ContextVar(
    "curate_trace_suppress", default=False
)
# Process-level base parent (trace_id, span_id) restored from an incoming
# traceparent: spans opened with an empty stack parent onto it, so every
# span a spawned worker emits joins the driver's trace.
_process_parent: tuple[str, str] | None = None


@dataclass
class TracedSpan:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_s: float
    attributes: dict[str, Any] = field(default_factory=dict)
    end_s: float | None = None
    # timestamped point events attached to this span (W3C span events):
    # the anomaly detector stamps its verdicts here so a stuck batch shows
    # up INSIDE the pipeline.run span instead of as a detached fragment
    events: list = field(default_factory=list)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append(
            {"name": name, "ts": time.time(), "attributes": dict(attributes)}
        )

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.time()) - self.start_s


class _NdjsonBackend:
    """Buffers span records and flushes through the storage layer, so a
    remote output root (s3://, gs://) receives traces like every other
    artifact instead of a bogus local directory.

    Storage backends cannot append, so each flush writes its buffered
    chunk to a NEW part file (``t.ndjson``, ``t.part1.ndjson``, ...) and
    drops the buffer: memory stays bounded at FLUSH_EVERY records and
    every byte is uploaded once, instead of rewriting an ever-growing
    file per flush. Consumers (flight recorder, artifact collector,
    e2e tests) glob ``*.ndjson``, so part files are collected the same
    as the base file; traces under FLUSH_EVERY spans stay single-file."""

    FLUSH_EVERY = 200

    def __init__(self, path: str) -> None:
        self.path = path
        self._lines: list[str] = []
        self._parts = 0
        self._flush_errors = 0
        self._lock = threading.Lock()

    def export(self, span: TracedSpan) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_s": span.start_s,
            "end_s": span.end_s,
            "duration_s": span.duration_s,
            "attributes": span.attributes,
            "pid": os.getpid(),
        }
        if span.events:
            record["events"] = span.events
        with self._lock:
            self._lines.append(json.dumps(record))
            if len(self._lines) >= self.FLUSH_EVERY:
                self._flush_locked()

    def _part_path(self) -> str:
        if self._parts == 0:
            return self.path
        if self.path.endswith(".ndjson"):
            return f"{self.path[:-len('.ndjson')]}.part{self._parts}.ndjson"
        return f"{self.path}.part{self._parts}"

    def _flush_locked(self) -> None:
        from cosmos_curate_tpu.storage.client import write_bytes

        # the storage layer is itself traced: exporting a span for THIS
        # write would re-enter export() under self._lock
        try:
            with suppress_tracing():
                write_bytes(
                    self._part_path(), ("\n".join(self._lines) + "\n").encode()
                )
        except Exception as e:
            # a flush failure must never surface inside the instrumented
            # operation (export() runs in end_span, inside the caller's
            # try/finally — raising here would fail/dead-letter real work
            # over trace IO, and disable_tracing()'s close() would fail the
            # run AFTER its output was written). Drop the chunk so memory
            # stays bounded when storage stays down; the OTLP backend
            # swallows its errors the same way.
            self._flush_errors += 1
            if self._flush_errors == 1:
                from cosmos_curate_tpu.utils.logging import get_logger

                get_logger(__name__).warning(
                    "trace flush to %s failed (%r); dropping %d span(s) "
                    "(further flush failures logged at close)",
                    self._part_path(), e, len(self._lines),
                )
        self._parts += 1
        self._lines = []

    def close(self) -> None:
        with self._lock:
            if self._lines:
                self._flush_locked()
            if self._flush_errors > 1:
                from cosmos_curate_tpu.utils.logging import get_logger

                get_logger(__name__).warning(
                    "trace backend for %s dropped spans on %d failed flushes",
                    self.path, self._flush_errors,
                )


class _OtlpHttpBackend:
    """OTLP/HTTP JSON trace exporter (opentelemetry-proto trace service
    schema, JSON encoding) — POSTs span batches to ``{endpoint}/v1/traces``
    with stdlib urllib; errors are logged once and never break the pipeline.
    """

    BATCH = 100
    MAX_QUEUED_BATCHES = 8

    def __init__(self, endpoint: str, service_name: str = "cosmos-curate-tpu") -> None:
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self._spans: list[TracedSpan] = []
        self._lock = threading.Lock()
        self._warned = False
        # posts happen on a background thread so a blackholed collector can
        # never stall traced application threads; full queue = drop batch
        import queue as queue_mod

        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.MAX_QUEUED_BATCHES)
        self._sender = threading.Thread(target=self._send_loop, daemon=True)
        self._sender.start()

    def _send_loop(self) -> None:
        while True:
            batch = self._q.get()
            if batch is None:
                return
            self._post(batch)

    @staticmethod
    def _attr(key: str, value: Any) -> dict[str, Any]:
        if isinstance(value, bool):
            v: dict[str, Any] = {"boolValue": value}
        elif isinstance(value, int):
            v = {"intValue": str(value)}
        elif isinstance(value, float):
            v = {"doubleValue": value}
        else:
            v = {"stringValue": str(value)}
        return {"key": key, "value": v}

    def _encode(self, spans: list[TracedSpan]) -> bytes:
        otlp_spans = []
        for s in spans:
            rec = {
                "traceId": s.trace_id.ljust(32, "0"),
                "spanId": s.span_id,
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int(s.start_s * 1e9)),
                "endTimeUnixNano": str(int((s.end_s or s.start_s) * 1e9)),
                "attributes": [self._attr(k, v) for k, v in s.attributes.items()],
                "status": (
                    {"code": 2, "message": str(s.attributes["error"])}
                    if "error" in s.attributes
                    else {"code": 1}
                ),
            }
            if s.parent_id:
                rec["parentSpanId"] = s.parent_id
            otlp_spans.append(rec)
        payload = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            self._attr("service.name", self.service_name),
                            self._attr("process.pid", os.getpid()),
                        ]
                    },
                    "scopeSpans": [
                        {"scope": {"name": "cosmos_curate_tpu.tracing"}, "spans": otlp_spans}
                    ],
                }
            ]
        }
        return json.dumps(payload).encode()

    def export(self, span: TracedSpan) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) >= self.BATCH:
                batch, self._spans = self._spans, []
            else:
                return
        try:
            self._q.put_nowait(batch)
        except Exception:
            if not self._warned:
                self._warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "OTLP export queue full; dropping span batches (collector at %s "
                    "unreachable or slow)", self.url,
                )

    def _post(self, batch: list[TracedSpan]) -> None:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.url,
            data=self._encode(batch),
            headers={"content-type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10):
                pass
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
            if not self._warned:
                self._warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "OTLP trace export to %s failing (%s); further errors suppressed",
                    self.url,
                    e,
                )

    def close(self) -> None:
        with self._lock:
            batch, self._spans = self._spans, []
        if batch:
            try:
                self._q.put_nowait(batch)
            except Exception:
                pass
        try:
            self._q.put_nowait(None)
        except Exception:
            return  # queue jammed by a dead collector; daemon thread dies with us
        self._sender.join(timeout=15)


def otlp_endpoint_from_env() -> str | None:
    return os.environ.get("CURATE_OTLP_ENDPOINT") or os.environ.get(
        "OTEL_EXPORTER_OTLP_ENDPOINT"
    )


def default_staging_dir() -> str:
    """Per-run staging dir: concurrent pipelines on one host must not sweep
    each other's artifacts. The run id is the coordinator pid, which the
    engine already propagates to workers as CURATE_STORE_OWNER."""
    run = os.environ.get("CURATE_STORE_OWNER", str(os.getpid()))
    return os.environ.get("CURATE_TRACE_DIR", f"/tmp/curate_traces/run-{run}")


_ATEXIT_REGISTERED = False


def _flush_backends_at_exit() -> None:
    """Close (flush) whatever backends are live when the process exits.
    Spawned workers never call disable_tracing(), and the NDJSON backend
    buffers — without this, a worker emitting fewer spans than the flush
    threshold would lose its entire trace file."""
    for b in _backends:
        try:
            b.close()
        except Exception:  # a failed flush must never break process exit
            pass


def enable_tracing(
    output_path: str | None = None, *, otlp_endpoint: str | None = None
) -> str:
    """Turn tracing on for this process; returns the NDJSON path. An OTLP
    collector endpoint (argument or env) adds a second export backend."""
    global _enabled, _backends, _ATEXIT_REGISTERED
    path = output_path or os.environ.get(
        "CURATE_TRACE_PATH", f"{default_staging_dir()}/trace-{os.getpid()}.ndjson"
    )
    for b in _backends:  # re-enable must not drop buffered spans
        b.close()
    _backends = [_NdjsonBackend(path)]
    endpoint = otlp_endpoint or otlp_endpoint_from_env()
    if endpoint:
        _backends.append(_OtlpHttpBackend(endpoint))
    if not _ATEXIT_REGISTERED:
        import atexit

        atexit.register(_flush_backends_at_exit)
        _ATEXIT_REGISTERED = True
    _enabled = True
    return path


def disable_tracing() -> None:
    global _enabled, _backends, _process_parent
    _enabled = False
    _process_parent = None
    for b in _backends:
        b.close()
    _backends = []


def tracing_enabled() -> bool:
    return _enabled


# -- W3C trace-context propagation ------------------------------------------

_TRACEPARENT_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """``00-<trace_id>-<span_id>-<flags>`` -> (trace_id, span_id), or None
    for anything malformed (including the all-zero ids W3C forbids)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(span: "TracedSpan | None" = None) -> str:
    """The W3C traceparent of ``span`` (default: the current innermost span,
    falling back to the process-level parent). '' when tracing is disabled
    or there is no active context — callers stamp it into frames verbatim,
    so disabled tracing costs one boolean and an empty field."""
    if not _enabled:
        return ""
    if span is None:
        stack = _stack.get()
        if stack:
            span = stack[-1]
        elif _process_parent is not None:
            return f"00-{_process_parent[0]}-{_process_parent[1]}-01"
        else:
            return ""
    if span is _NOOP_SPAN:
        return ""
    return f"00-{span.trace_id}-{span.span_id}-01"


def attach_traceparent(header: str | None) -> bool:
    """Adopt an incoming traceparent as this PROCESS's base parent: spans
    opened with no enclosing span parent onto it. Returns True when a valid
    header was attached. Spawned workers call this at startup with the
    driver-stamped ``CURATE_TRACEPARENT``."""
    global _process_parent
    parsed = parse_traceparent(header)
    if parsed is None:
        return False
    _process_parent = parsed
    return True


def current_span() -> "TracedSpan | None":
    """The innermost active span of this context, or None (disabled,
    suppressed, or empty). Lets helpers deep in a call tree (e.g. the
    storage retry loop) annotate the span their caller opened without
    threading it through."""
    if not _enabled or _suppress.get():
        return None
    stack = _stack.get()
    return stack[-1] if stack else None


def current_trace_id() -> str | None:
    """Trace id of the current context (innermost span, else the process
    parent), or None. The DLQ stamps this into dead-batch metadata."""
    if not _enabled:
        return None
    stack = _stack.get()
    if stack:
        return stack[-1].trace_id
    return _process_parent[0] if _process_parent is not None else None


@contextlib.contextmanager
def suppress_tracing() -> Iterator[None]:
    """No spans are recorded inside this block (export paths use it to keep
    their own storage writes out of the trace — and out of deadlocks)."""
    token = _suppress.set(True)
    try:
        yield
    finally:
        _suppress.reset(token)


# -- span lifecycle ----------------------------------------------------------


def start_span(
    name: str, *, traceparent: str | None = None, **attributes: Any
) -> TracedSpan:
    """Manually-managed span (exported by :func:`end_span`); the noop span
    when disabled. Does NOT alter the ambient context — for long-lived
    driver spans (per-stage spans in the streaming runner) whose lifetime
    crosses loop iterations. Parent resolution: explicit ``traceparent`` >
    current stack > process-level parent > fresh trace."""
    if not _enabled or _suppress.get():
        return _NOOP_SPAN
    parent_ctx = parse_traceparent(traceparent) if traceparent else None
    if parent_ctx is None:
        stack = _stack.get()
        if stack:
            parent_ctx = (stack[-1].trace_id, stack[-1].span_id)
        elif _process_parent is not None:
            parent_ctx = _process_parent
    if parent_ctx is not None:
        trace_id, parent_id = parent_ctx
    else:
        trace_id, parent_id = uuid.uuid4().hex, None
    return TracedSpan(
        name=name,
        trace_id=trace_id,
        span_id=uuid.uuid4().hex[:16],
        parent_id=parent_id,
        start_s=time.time(),
        attributes=dict(attributes),
    )


def end_span(span: TracedSpan) -> None:
    """Finish and export a :func:`start_span` span (noop spans pass through)."""
    if span is _NOOP_SPAN or not _enabled:
        return
    if span.end_s is None:
        span.end_s = time.time()
    for b in _backends:
        b.export(span)


@contextlib.contextmanager
def traced_span(
    name: str, *, traceparent: str | None = None, **attributes: Any
) -> Iterator[TracedSpan]:
    """Context manager span; cheap no-op (yields a dummy) when disabled.

    ``traceparent`` restores an incoming W3C context as the parent — the
    cross-process hop. Without it the span parents onto the contextvar
    stack (surviving ``contextvars.copy_context()`` thread hops), then the
    process-level parent."""
    if not _enabled or _suppress.get():
        yield _NOOP_SPAN
        return
    span = start_span(name, traceparent=traceparent, **attributes)
    token = _stack.set(_stack.get() + (span,))
    try:
        yield span
    except Exception as e:
        span.attributes["error"] = repr(e)
        raise
    finally:
        _stack.reset(token)
        end_span(span)


class _NoopSpan(TracedSpan):
    def set_attribute(self, key: str, value: Any) -> None:
        pass  # shared module-global: must not accumulate state

    def add_event(self, name: str, **attributes: Any) -> None:
        pass


_NOOP_SPAN = _NoopSpan("noop", "0", "0", None, 0.0)


def add_span_event(name: str, **attributes: Any) -> bool:
    """Attach a timestamped event to the innermost active span (the live
    ops plane's anomaly verdicts ride the ambient pipeline.run span this
    way). With no active span but tracing on, an instant zero-duration span
    is exported instead, so the event still lands in the trace. Returns
    False (and does nothing) when tracing is off."""
    if not _enabled or _suppress.get():
        return False
    stack = _stack.get()
    if stack:
        stack[-1].add_event(name, **attributes)
        return True
    span = start_span(name, **attributes)
    end_span(span)
    return True


def traced(fn: Callable | None = None, *, name: str | None = None):
    """Decorator form of ``traced_span``."""

    def deco(f: Callable) -> Callable:
        span_name = name or f"{f.__module__}.{f.__qualname__}"

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return f(*args, **kwargs)
            with traced_span(span_name):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


TRACEPARENT_ENV = "CURATE_TRACEPARENT"


def setup_tracing_from_env() -> None:
    """Worker startup hook (reference tracing_hook.setup_tracing): enables
    tracing when CURATE_TRACING=1 is in the environment, and adopts the
    driver-stamped ``CURATE_TRACEPARENT`` so this process's spans join the
    driver's trace instead of starting fragments of their own."""
    if os.environ.get("CURATE_TRACING") == "1":
        enable_tracing()
        attach_traceparent(os.environ.get(TRACEPARENT_ENV))
