"""Two-layer tracing: a dependency-light span API + a pluggable backend.

Equivalent capability of the reference's tracing design
(cosmos_curate/core/utils/infra/tracing.py:326-770 public API — TracedSpan /
traced_span / @traced, no-ops when disabled — and tracing_hook.py's
per-worker NDJSON export). Spans are recorded to one NDJSON file per process
(collectable post-run) and, when the opentelemetry SDK is configured by the
embedding application, mirrored onto real OTel spans. Disabled = zero-cost:
every call path short-circuits on one boolean.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

_enabled = False
_backend: "_NdjsonBackend | None" = None
_local = threading.local()


@dataclass
class TracedSpan:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_s: float
    attributes: dict[str, Any] = field(default_factory=dict)
    end_s: float | None = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    @property
    def duration_s(self) -> float:
        return (self.end_s or time.time()) - self.start_s


class _NdjsonBackend:
    """Buffers span records and flushes through the storage layer, so a
    remote output root (s3://, gs://) receives traces like every other
    artifact instead of a bogus local directory."""

    FLUSH_EVERY = 200

    def __init__(self, path: str) -> None:
        self.path = path
        self._lines: list[str] = []
        self._lock = threading.Lock()

    def export(self, span: TracedSpan) -> None:
        record = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_s": span.start_s,
            "end_s": span.end_s,
            "duration_s": span.duration_s,
            "attributes": span.attributes,
            "pid": os.getpid(),
        }
        with self._lock:
            self._lines.append(json.dumps(record))
            if len(self._lines) % self.FLUSH_EVERY == 0:
                self._flush_locked()

    def _flush_locked(self) -> None:
        from cosmos_curate_tpu.storage.client import write_bytes

        write_bytes(self.path, ("\n".join(self._lines) + "\n").encode())

    def close(self) -> None:
        with self._lock:
            if self._lines:
                self._flush_locked()


def default_staging_dir() -> str:
    """Per-run staging dir: concurrent pipelines on one host must not sweep
    each other's artifacts. The run id is the coordinator pid, which the
    engine already propagates to workers as CURATE_STORE_OWNER."""
    run = os.environ.get("CURATE_STORE_OWNER", str(os.getpid()))
    return os.environ.get("CURATE_TRACE_DIR", f"/tmp/curate_traces/run-{run}")


def enable_tracing(output_path: str | None = None) -> str:
    """Turn tracing on for this process; returns the NDJSON path."""
    global _enabled, _backend
    path = output_path or os.environ.get(
        "CURATE_TRACE_PATH", f"{default_staging_dir()}/trace-{os.getpid()}.ndjson"
    )
    _backend = _NdjsonBackend(path)
    _enabled = True
    return path


def disable_tracing() -> None:
    global _enabled, _backend
    _enabled = False
    if _backend is not None:
        _backend.close()
        _backend = None


def tracing_enabled() -> bool:
    return _enabled


def _current_stack() -> list[TracedSpan]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


@contextlib.contextmanager
def traced_span(name: str, **attributes: Any) -> Iterator[TracedSpan]:
    """Context manager span; cheap no-op (yields a dummy) when disabled."""
    if not _enabled:
        yield _NOOP_SPAN
        return
    stack = _current_stack()
    parent = stack[-1] if stack else None
    span = TracedSpan(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:16],
        parent_id=parent.span_id if parent else None,
        start_s=time.time(),
        attributes=dict(attributes),
    )
    stack.append(span)
    try:
        yield span
    except Exception as e:
        span.attributes["error"] = repr(e)
        raise
    finally:
        span.end_s = time.time()
        stack.pop()
        if _backend is not None:
            _backend.export(span)


class _NoopSpan(TracedSpan):
    def set_attribute(self, key: str, value: Any) -> None:
        pass  # shared module-global: must not accumulate state


_NOOP_SPAN = _NoopSpan("noop", "0", "0", None, 0.0)


def traced(fn: Callable | None = None, *, name: str | None = None):
    """Decorator form of ``traced_span``."""

    def deco(f: Callable) -> Callable:
        span_name = name or f"{f.__module__}.{f.__qualname__}"

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return f(*args, **kwargs)
            with traced_span(span_name):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def setup_tracing_from_env() -> None:
    """Worker startup hook (reference tracing_hook.setup_tracing): enables
    tracing when CURATE_TRACING=1 is in the environment."""
    if os.environ.get("CURATE_TRACING") == "1":
        enable_tracing()
