"""Stage save / replay: record real per-stage inputs, re-run one stage
offline.

Equivalent capability of the reference's stage replay tooling
(cosmos_curate/core/utils/misc/stage_replay.py — ``StageSaveConfig``:303,
pickle task serializer:182, ``run_stage_replay``:639, ``stage_save_wrapper``
:710; workflow doc docs/curator/guides/STAGE_REPLAY.md): debugging a stage
against production data without re-running the whole pipeline.
"""

from __future__ import annotations

import pickle
import random
import time
from dataclasses import dataclass
from pathlib import Path

from cosmos_curate_tpu.core.stage import NodeInfo, Stage, WorkerMetadata
from cosmos_curate_tpu.storage.client import write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class StageSaveConfig:
    output_path: str
    sample_rate: float = 0.1  # fraction of process_data batches recorded
    stages: tuple[str, ...] = ()  # () = all stages
    seed: int = 0


def stage_save_wrapper(stage: Stage, config: StageSaveConfig) -> Stage:
    """Dynamic subclass recording sampled ``process_data`` inputs."""
    if config.stages and stage.name not in config.stages:
        return stage
    cls = type(stage)
    rng = random.Random(config.seed)
    display = stage.name

    class SavingStage(cls):  # type: ignore[misc, valid-type]
        def process_data(self, tasks):
            if rng.random() < config.sample_rate:
                stamp = time.time_ns()
                path = (
                    f"{config.output_path.rstrip('/')}/stage_inputs/"
                    f"{display}/batch-{stamp}.pkl"
                )
                try:
                    write_bytes(path, pickle.dumps(tasks, protocol=5))
                except Exception:
                    logger.exception("stage-save failed for %s", display)
            return cls.process_data(self, tasks)

    stage.__class__ = SavingStage
    stage._display_name = display  # type: ignore[attr-defined]
    return stage


def load_saved_batches(saved_root: str, stage_name: str) -> list[list]:
    root = Path(saved_root) / "stage_inputs" / stage_name
    batches = []
    for p in sorted(root.glob("batch-*.pkl")):
        batches.append(pickle.loads(p.read_bytes()))
    return batches


def run_stage_replay(stage: Stage, saved_root: str) -> list[list]:
    """Run one stage directly over its recorded inputs (DirectExecutor
    semantics: setup -> process each batch -> destroy). Returns the list of
    output batches."""
    batches = load_saved_batches(saved_root, stage.name)
    if not batches:
        raise FileNotFoundError(
            f"no saved batches for stage {stage.name} under {saved_root}"
        )
    node = NodeInfo(node_id="replay")
    meta = WorkerMetadata(worker_id="replay-0", stage_name=stage.name, node=node)
    stage.setup_on_node(node, meta)
    stage.setup(meta)
    outputs = []
    try:
        for batch in batches:
            outputs.append(stage.process_data(batch))
    finally:
        stage.destroy()
    logger.info("replayed %d batches through %s", len(batches), stage.name)
    return outputs
