"""Per-stage worker pools.

Two pool kinds behind one interface:

- ``ProcessPool`` — CPU stages: spawned worker processes (engine/worker.py)
  with per-worker control queues and a pool-shared result queue.
- ``InProcessPool`` — TPU stages: a thread inside the engine process, which
  is the sole owner of the host's chips (package docstring). One worker —
  batch aggregation, not device sharing, is how TPU stages scale per host.

Both consume/produce ``ObjectRef``s so the orchestration loop has a single
data path.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any

import cloudpickle

from cosmos_curate_tpu.core.stage import NodeInfo, StageSpec, WorkerMetadata
from cosmos_curate_tpu.engine import object_store
from cosmos_curate_tpu.engine.worker import (
    ProcessMsg,
    ReadyMsg,
    ResultMsg,
    SetupMsg,
    ShutdownMsg,
    worker_main,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MP = mp.get_context("spawn")


@dataclass
class WorkerHandle:
    worker_id: str
    in_q: object
    proc: object | None = None  # mp.Process for ProcessPool
    ready: bool = False
    busy_batch: int | None = None
    started_at: float = field(default_factory=time.monotonic)
    batches_done: int = 0
    recycle_requested: bool = False


class BasePool:
    """Shared bookkeeping for both pool kinds.

    ``pool_id`` (the stage index) namespaces worker ids — the same stage
    class may appear at several pipeline positions, and result routing is
    by worker id, so ids must be unique across pools."""

    def __init__(self, spec: StageSpec, node: NodeInfo, pool_id: int = 0) -> None:
        self.spec = spec
        self.pool_id = pool_id
        self.stage = spec.stage
        self.node = node
        self.workers: dict[str, WorkerHandle] = {}
        # W3C traceparent of the driver-side span submitted batches parent
        # onto (the runner sets it per stage); '' = tracing off
        self.trace_context: str = ""
        self._next_id = 0
        # recent (finish_time, process_time_s, node_id) samples for the
        # autoscaler; node_id '' = locally placed worker (driver node)
        self.samples: list[tuple[float, float, str]] = []
        # workers told to shut down, awaiting reap (never blocks the loop)
        self.draining: list[tuple[WorkerHandle, float]] = []
        # workers that died before ever becoming ready (setup-crash guard)
        self.setup_deaths: int = 0

    @property
    def name(self) -> str:
        return self.spec.name

    def idle_workers(self) -> list[WorkerHandle]:
        return [w for w in self.workers.values() if w.ready and w.busy_batch is None]

    def num_workers(self) -> int:
        return len(self.workers)

    @staticmethod
    def worker_node(w: WorkerHandle) -> str:
        """'' for locally placed workers, else the owning agent's node id
        (remote handles carry _RemoteProc with an ``_agent``)."""
        agent = getattr(w.proc, "_agent", None)
        return agent.node_id if agent is not None else ""

    def workers_by_node(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for w in self.workers.values():
            node = self.worker_node(w)
            counts[node] = counts.get(node, 0) + 1
        return counts

    def record_sample(self, process_time_s: float, node_id: str = "") -> None:
        now = time.monotonic()
        self.samples.append((now, process_time_s, node_id))
        cutoff = now - 600.0
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.pop(0)

    def throughput_per_worker(self, window_s: float) -> float | None:
        """Batches/sec one worker achieves, from recent samples."""
        now = time.monotonic()
        recent = [p for (t, p, _n) in self.samples if t >= now - window_s]
        if not recent:
            return None
        mean_t = sum(recent) / len(recent)
        return 1.0 / mean_t if mean_t > 0 else None

    def node_throughputs(self, window_s: float) -> dict[str, float]:
        """Per-node batches/sec one worker achieves — the per-node planner
        biases CPU fan-out toward nodes that measurably process this stage
        faster (e.g. less-contended cores, faster local disks)."""
        now = time.monotonic()
        by_node: dict[str, list[float]] = {}
        for t, p, node in self.samples:
            if t >= now - window_s:
                by_node.setdefault(node, []).append(p)
        out: dict[str, float] = {}
        for node, ps in by_node.items():
            mean_t = sum(ps) / len(ps)
            if mean_t > 0:
                out[node] = 1.0 / mean_t
        return out

    def lifetime_expired(self, w: WorkerHandle) -> bool:
        lim = self.spec.worker_max_lifetime_m or 0
        return lim > 0 and (time.monotonic() - w.started_at) > lim * 60

    # subclass API. ``node_id`` is the per-node planner's placement pin:
    # None = legacy least-loaded placement, '' = the driver node, anything
    # else = that agent (falling back when it died since the plan).
    def start_worker(self, node_id: str | None = None) -> WorkerHandle:  # pragma: no cover - abstract
        raise NotImplementedError

    def stop_worker(self, w: WorkerHandle) -> None:  # pragma: no cover
        raise NotImplementedError

    def note_worker_gone(self, w: WorkerHandle) -> None:
        """Called when the runner reaps a DEAD worker (stop_worker never
        ran): release any placement accounting."""

    def submit(self, w: WorkerHandle, batch_id: int, refs: list) -> None:
        w.busy_batch = batch_id
        w.in_q.put(
            ProcessMsg(
                batch_id=batch_id,
                refs=refs,
                timeout_s=self.spec.batch_timeout_s or 0.0,
                traceparent=self.trace_context,
            )
        )

    def reap_draining(self, *, force_after_s: float = 5.0) -> None:
        """Non-blocking cleanup of workers previously told to stop."""
        still = []
        now = time.monotonic()
        for w, since in self.draining:
            proc = w.proc
            if proc is None or not proc.is_alive():
                if proc is not None:
                    proc.join(timeout=0)
                continue
            if now - since > force_after_s:
                proc.terminate()
                continue
            still.append((w, since))
        self.draining = still

    def shutdown(self) -> None:
        for w in list(self.workers.values()):
            self.stop_worker(w)
        # final shutdown may block briefly; not on the orchestration path
        deadline = time.monotonic() + 5.0
        for w, _ in self.draining:
            proc = w.proc
            if proc is None:
                continue
            proc.join(timeout=max(0.0, deadline - time.monotonic()))
            if proc.is_alive():
                proc.terminate()
        self.draining.clear()


def _base_worker_env() -> dict[str, str]:
    import os

    env = {
        "JAX_PLATFORMS": "cpu",  # CPU workers must never claim the TPU
        "OPENCV_FFMPEG_LOGLEVEL": "-8",
        # segments a worker creates are owned by this coordinator process
        # (see object_store.put): recycled workers leave live data behind
        "CURATE_STORE_OWNER": os.environ.get("CURATE_STORE_OWNER", str(os.getpid())),
    }
    from cosmos_curate_tpu.observability.tracing import (
        TRACEPARENT_ENV,
        format_traceparent,
        tracing_enabled,
    )

    if tracing_enabled() or os.environ.get("CURATE_TRACING") == "1":
        env["CURATE_TRACING"] = "1"
        # the driver's ambient span (the run root, when workers start from
        # the orchestration loop) becomes the worker's process-level parent,
        # so its setup/idle spans join this trace too
        tp = format_traceparent() or os.environ.get(TRACEPARENT_ENV, "")
        if tp:
            env[TRACEPARENT_ENV] = tp
    from cosmos_curate_tpu import chaos

    if os.environ.get(chaos.CHAOS_ENV):
        # fault plans follow workers: chaos tests arm crash/hang sites that
        # live inside the spawned worker's task loop
        env[chaos.CHAOS_ENV] = os.environ[chaos.CHAOS_ENV]
    return env


class PrewarmPool:
    """Warm spares: generic worker processes spawned ahead of need.

    Worker processes are stage-agnostic until their SetupMsg arrives, so the
    expensive part of a cold start (interpreter spawn + imports, ~3-5 s) can
    be prepaid. Autoscale-up adopts a spare and pays only stage setup; a
    replacement spare is spawned in the background after each adoption
    (addresses the engine's known scale-up cold-start cost)."""

    def __init__(self, results_q, size: int = 0) -> None:
        self.results_q = results_q
        self.size = size
        self._spares: list[tuple[Any, Any]] = []  # (in_q, proc)
        self._lock = threading.Lock()
        self._closed = False
        for _ in range(size):
            self._spawn()

    def _spawn(self) -> None:
        with self._lock:
            if self._closed:
                return
        in_q = _MP.Queue()
        env = dict(_base_worker_env(), CURATE_WORKER_ID="prewarm-spare")
        proc = _MP.Process(
            target=worker_main, args=(in_q, self.results_q, env), daemon=True,
            name="prewarm-spare",
        )
        proc.start()
        with self._lock:
            if self._closed:  # shutdown raced the spawn: stop the newborn
                try:
                    in_q.put(ShutdownMsg())
                except Exception:
                    proc.terminate()
                return
            self._spares.append((in_q, proc))

    def take(self):
        """-> (in_q, proc) of a live spare, or None. Replenishes async —
        one replacement per pop, so crashed spares don't shrink the pool."""
        replacements = 0
        taken = None
        with self._lock:
            while self._spares and taken is None:
                in_q, proc = self._spares.pop()
                replacements += 1
                if proc.is_alive():
                    taken = (in_q, proc)
                else:
                    proc.join(timeout=0)  # reap the dead spare
        for _ in range(replacements):
            threading.Thread(target=self._spawn, daemon=True).start()
        return taken

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            spares, self._spares = self._spares, []
        for in_q, proc in spares:
            try:
                in_q.put(ShutdownMsg())
            except Exception:
                proc.terminate()


class ProcessPool(BasePool):
    def __init__(
        self, spec: StageSpec, node: NodeInfo, results_q, pool_id: int = 0,
        prewarm: "PrewarmPool | None" = None, remote_mgr=None,
    ) -> None:
        super().__init__(spec, node, pool_id)
        self.results_q = results_q  # mp queue shared by all pools' processes
        self.prewarm = prewarm
        # cross-node plane (engine/remote_plane.py): when set, start_worker
        # may place a worker on a connected node agent once local CPUs fill
        self.remote_mgr = remote_mgr
        self._stage_pickle = cloudpickle.dumps(spec.stage)

    @property
    def _cpu_cost(self) -> float:
        return self.stage.resources.cpus

    def start_worker(self, node_id: str | None = None) -> WorkerHandle:
        wid = f"s{self.pool_id}-{self.name}-p{self._next_id}"
        self._next_id += 1
        env = dict(_base_worker_env(), CURATE_WORKER_ID=wid)
        if self.remote_mgr is None:
            agent = None
        elif node_id is None:
            agent = self.remote_mgr.place(self._cpu_cost)
        else:
            agent = self.remote_mgr.place_for(node_id, self._cpu_cost)
        if agent is not None:
            meta = WorkerMetadata(
                worker_id=wid,
                stage_name=self.name,
                node=NodeInfo(node_id=agent.node_id, num_cpus=agent.num_cpus, num_tpu_chips=0),
                allocation=self.stage.resources,
            )
            in_q, proc = self.remote_mgr.start_remote_worker(
                agent, wid, self._stage_pickle, cloudpickle.dumps(meta), env,
                cpu_cost=self._cpu_cost,
            )
            handle = WorkerHandle(worker_id=wid, in_q=in_q, proc=proc)
            self.workers[wid] = handle
            return handle
        adopted = self.prewarm.take() if self.prewarm is not None else None
        if adopted is not None:
            in_q, proc = adopted
            setup_env = env  # applied by the worker before loading the stage
        else:
            in_q = _MP.Queue()
            proc = _MP.Process(
                target=worker_main, args=(in_q, self.results_q, env), daemon=True, name=wid
            )
            proc.start()
            setup_env = None
        meta = WorkerMetadata(
            worker_id=wid, stage_name=self.name, node=self.node, allocation=self.stage.resources
        )
        in_q.put(SetupMsg(self._stage_pickle, cloudpickle.dumps(meta), env=setup_env))
        handle = WorkerHandle(worker_id=wid, in_q=in_q, proc=proc)
        self.workers[wid] = handle
        if self.remote_mgr is not None:
            self.remote_mgr.note_local_start(self._cpu_cost)
        return handle

    def stop_worker(self, w: WorkerHandle) -> None:
        """Request shutdown; never blocks (reap_draining finishes the job)."""
        try:
            w.in_q.put(ShutdownMsg())
        except Exception:
            pass
        self.workers.pop(w.worker_id, None)
        if self.remote_mgr is not None and not hasattr(w.proc, "_agent"):
            # locally placed worker (remote handles carry _RemoteProc; their
            # cost is released by the manager's StopWorker path)
            self.remote_mgr.note_local_stop(self._cpu_cost)
        if w.proc is not None:
            self.draining.append((w, time.monotonic()))

    def note_worker_gone(self, w: WorkerHandle) -> None:
        """Dead-worker reap: release placement accounting (stop_worker did
        not run, so the counters would drift otherwise)."""
        if self.remote_mgr is None:
            return
        if hasattr(w.proc, "_agent"):
            self.remote_mgr.note_remote_gone(w.proc)
        else:
            self.remote_mgr.note_local_stop(self._cpu_cost)


class InProcessPool(BasePool):
    """TPU stages: worker threads in the engine process (chip owner)."""

    def __init__(
        self, spec: StageSpec, node: NodeInfo, results_q: queue.Queue, pool_id: int = 0
    ) -> None:
        super().__init__(spec, node, pool_id)
        self.results_q = results_q
        self._lock = threading.Lock()  # device stages run one batch at a time

    def start_worker(self, node_id: str | None = None) -> WorkerHandle:  # noqa: ARG002 - TPU workers are always driver-local
        if self.workers:
            # One in-process worker per TPU stage: threads would share the
            # same stage instance (double setup, destroy-while-in-use).
            raise RuntimeError(
                f"TPU stage {self.name} supports exactly one in-process "
                f"worker; scale by batch aggregation, not worker count"
            )
        wid = f"s{self.pool_id}-{self.name}-t{self._next_id}"
        self._next_id += 1
        in_q: queue.Queue = queue.Queue()
        handle = WorkerHandle(worker_id=wid, in_q=in_q)
        self.workers[wid] = handle
        threading.Thread(
            target=self._thread_main, args=(handle,), daemon=True, name=wid
        ).start()
        return handle

    def _thread_main(self, handle: WorkerHandle) -> None:
        import concurrent.futures

        from cosmos_curate_tpu.engine.worker import _fetch_batch

        stage = self.stage
        meta = WorkerMetadata(
            worker_id=handle.worker_id,
            stage_name=self.name,
            node=self.node,
            allocation=stage.resources,
        )
        # same bounded concurrent input fetch the spawned workers use —
        # device stages take the largest batches, so sequential ref-by-ref
        # deserialization is the worst here; owned (and shut down) by this
        # worker thread
        fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"{handle.worker_id}-fetch"
        )
        try:
            with self._lock:
                stage.setup_on_node(self.node, meta)
                stage.setup(meta)
            self.results_q.put(ReadyMsg(worker_id=handle.worker_id))
        except Exception:
            self.results_q.put(
                ReadyMsg(worker_id=handle.worker_id, error=traceback.format_exc())
            )
            return
        while True:
            msg = handle.in_q.get()
            if isinstance(msg, ShutdownMsg):
                break
            t0 = time.monotonic()
            try:
                from cosmos_curate_tpu.observability.tracing import traced_span

                tasks = _fetch_batch(msg.refs, fetch_pool)
                dt = time.monotonic() - t0
                # span OUTSIDE the lock: exiting a span can flush 200
                # buffered records through the storage backend — doing that
                # while holding the pool-wide lock would stall every other
                # in-process worker on trace IO. The span therefore includes
                # lock wait, matching process_time_s (also t0-based)
                with traced_span(
                    f"stage.{self.name}.process",
                    traceparent=getattr(msg, "traceparent", "") or None,
                    batch_size=len(tasks),
                    worker_id=handle.worker_id,
                ), self._lock:
                    result = stage.process_data(tasks)
                if result is not None and not isinstance(result, list):
                    raise TypeError(
                        f"stage {self.name}.process_data must return list or None"
                    )
                out_refs = [object_store.put(t) for t in (result or [])]
                self.results_q.put(
                    ResultMsg(
                        msg.batch_id,
                        out_refs=out_refs,
                        process_time_s=time.monotonic() - t0 - dt,
                        deserialize_time_s=dt,
                        worker_id=handle.worker_id,
                    )
                )
            except Exception:
                self.results_q.put(
                    ResultMsg(
                        msg.batch_id,
                        error=traceback.format_exc(),
                        process_time_s=time.monotonic() - t0,
                        worker_id=handle.worker_id,
                    )
                )
        fetch_pool.shutdown(wait=False)
        try:
            stage.destroy()
        except Exception:
            pass

    def stop_worker(self, w: WorkerHandle) -> None:
        w.in_q.put(ShutdownMsg())
        self.workers.pop(w.worker_id, None)


def make_pool(
    spec: StageSpec, node: NodeInfo, mp_results_q, thread_results_q, pool_id: int = 0,
    prewarm: PrewarmPool | None = None, remote_mgr=None,
):
    if spec.stage.resources.uses_tpu:
        # TPU stages never place remotely: each host's chips belong to that
        # host's engine process
        return InProcessPool(spec, node, thread_results_q, pool_id)
    return ProcessPool(
        spec, node, mp_results_q, pool_id, prewarm=prewarm, remote_mgr=remote_mgr
    )
