"""Stage worker process: the engine's unit of execution.

Mirrors the reference's per-actor lifecycle (SURVEY.md §3.2): setup_on_node →
setup → process_data loop → destroy, with the 3-step mini-pipeline (fetch
ref → deserialize → process) hiding data-movement latency behind compute
(ARCHITECTURE.md:70-77) via a prefetch thread.

Workers are spawned (never forked — a forked JAX/TPU runtime is undefined)
and CPU workers pin ``JAX_PLATFORMS=cpu`` so they can never grab the host's
TPU chips, which belong exclusively to the engine process.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any

import cloudpickle

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.engine import object_store


@dataclass
class SetupMsg:
    stage_pickle: bytes
    worker_meta_pickle: bytes
    # set when a prewarmed (generic) worker is adopted by a pool: applied
    # before the stage loads so worker id/tracing reflect the adopter
    env: dict[str, str] | None = None


@dataclass
class ProcessMsg:
    batch_id: int
    refs: list[object_store.ObjectRef]
    # per-batch execution deadline (StageSpec.batch_timeout_s); 0 = none.
    # Enforced by whoever can kill the worker — the runner locally, the
    # node agent's watchdog remotely — never by the worker itself (a hung
    # worker can't run its own timer).
    timeout_s: float = 0.0
    # W3C trace context of the driver-side stage span this batch belongs
    # to: the worker's process span parents onto it, so one trace spans
    # driver -> (agent ->) worker. '' when tracing is off.
    traceparent: str = ""


@dataclass
class ShutdownMsg:
    pass


@dataclass
class ResultMsg:
    batch_id: int
    out_refs: list[object_store.ObjectRef] = field(default_factory=list)
    error: str | None = None
    process_time_s: float = 0.0
    deserialize_time_s: float = 0.0
    worker_id: str = ""
    # the error is a lost-input condition (object-channel fetch from a dead
    # owner), not user code failing — the runner routes it to lineage
    # reconstruction instead of the num_run_attempts budget. Only the
    # remote path (remote_plane.AgentResult relay) ever sets it.
    input_loss: bool = False


@dataclass
class ReadyMsg:
    worker_id: str
    error: str | None = None


# Bounded fan-out for one batch's input fetches: segments deserialize
# concurrently instead of ref-by-ref, so a 32-task batch's deserialize
# window shrinks toward its largest segment instead of the sum of all.
FETCH_THREADS_ENV = "CURATE_WORKER_FETCH_THREADS"


def _fetch_batch(refs: list, pool) -> list[Any]:
    """Deserialize a batch's refs through the bounded pool (order
    preserved), recording bytes/latency for the object-plane accounting.
    Single-ref batches skip the pool hop."""
    from cosmos_curate_tpu.observability.stage_timer import record_object_plane

    t0 = time.monotonic()
    if pool is None or len(refs) <= 1:
        tasks = [object_store.get(r) for r in refs]
    else:
        tasks = list(pool.map(object_store.get, refs))
    record_object_plane(
        store_reads=len(refs),
        store_read_bytes=sum(r.total_size for r in refs),
        store_read_wait_s=time.monotonic() - t0,
    )
    return tasks


def worker_main(in_q, out_q, env: dict[str, str]) -> None:
    """Entry point of a spawned worker process."""
    os.environ.update(env)
    from cosmos_curate_tpu.observability.tracing import setup_tracing_from_env, traced_span

    setup_tracing_from_env()
    # arm fault injection once at bring-up; per-batch cost while disarmed is
    # a single falsy check inside chaos.fire()
    chaos.install_from_env()
    stage = None
    meta = None
    worker_id = env.get("CURATE_WORKER_ID", "worker-?")
    # prefetch pipeline: control msgs -> deserialized batches
    fetched: queue.Queue[tuple[ProcessMsg, list[Any] | None, str | None, float]] = queue.Queue(
        maxsize=2
    )
    stop = threading.Event()
    import concurrent.futures

    n_fetch = max(1, int(os.environ.get(FETCH_THREADS_ENV, "4")))
    fetch_pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=n_fetch, thread_name_prefix=f"{worker_id}-fetch"
    )

    parent_pid = os.getppid()

    def fetcher() -> None:
        while not stop.is_set():
            try:
                msg = in_q.get(timeout=0.2)
            except queue.Empty:
                if os.getppid() != parent_pid:
                    # orphaned: the coordinator (driver or node agent) died
                    # without cleanup — a SIGKILLed node's workers must not
                    # idle forever as leaked processes
                    fetched.put((ShutdownMsg(), None, None, 0.0))
                    return
                continue
            if isinstance(msg, ShutdownMsg):
                fetched.put((msg, None, None, 0.0))  # type: ignore[arg-type]
                return
            if isinstance(msg, SetupMsg):
                fetched.put((msg, None, None, 0.0))  # type: ignore[arg-type]
                continue
            t0 = time.monotonic()
            try:
                tasks = _fetch_batch(msg.refs, fetch_pool)
                fetched.put((msg, tasks, None, time.monotonic() - t0))
            except Exception:
                fetched.put((msg, None, traceback.format_exc(), time.monotonic() - t0))

    threading.Thread(target=fetcher, daemon=True).start()

    try:
        while True:
            msg, tasks, fetch_err, dt = fetched.get()
            if isinstance(msg, ShutdownMsg):
                break
            if isinstance(msg, SetupMsg):
                try:
                    if msg.env:
                        os.environ.update(msg.env)
                        worker_id = msg.env.get("CURATE_WORKER_ID", worker_id)
                        setup_tracing_from_env()
                        # adopted prewarm spare: the adopter's env may arm
                        # chaos that the generic spare was spawned without
                        chaos.install_from_env()
                    stage = cloudpickle.loads(msg.stage_pickle)
                    meta = cloudpickle.loads(msg.worker_meta_pickle)
                    stage.setup_on_node(meta.node, meta)
                    stage.setup(meta)
                    out_q.put(ReadyMsg(worker_id=worker_id))
                except Exception:
                    out_q.put(ReadyMsg(worker_id=worker_id, error=traceback.format_exc()))
                continue
            # ProcessMsg
            if fetch_err is not None:
                out_q.put(
                    ResultMsg(msg.batch_id, error=fetch_err, worker_id=worker_id)
                )
                continue
            t0 = time.monotonic()
            try:
                chaos.fire(chaos.SITE_WORKER_CRASH)  # kind=crash: os._exit
                chaos.fire(chaos.SITE_WORKER_HANG)  # kind=hang: stuck batch
                # Stage.name, not type(...).__name__: observability wrappers
                # subclass dynamically, and the flight recorder attributes
                # time by span name — every wrapped stage collapsing to
                # "ProfiledStage" would merge them all into one bucket
                with traced_span(
                    f"stage.{getattr(stage, 'name', type(stage).__name__)}.process",
                    traceparent=msg.traceparent or None,
                    batch_size=len(tasks),
                    worker_id=worker_id,
                ):
                    result = stage.process_data(tasks)
                if result is not None and not isinstance(result, list):
                    raise TypeError(
                        f"stage {type(stage).__name__}.process_data must return "
                        f"list or None, got {type(result).__name__}"
                    )
                out_refs = [object_store.put(t) for t in (result or [])]
                out_q.put(
                    ResultMsg(
                        msg.batch_id,
                        out_refs=out_refs,
                        process_time_s=time.monotonic() - t0,
                        deserialize_time_s=dt,
                        worker_id=worker_id,
                    )
                )
            except Exception:
                out_q.put(
                    ResultMsg(
                        msg.batch_id,
                        error=traceback.format_exc(),
                        process_time_s=time.monotonic() - t0,
                        worker_id=worker_id,
                    )
                )
    finally:
        stop.set()
        fetch_pool.shutdown(wait=False)
        if stage is not None:
            try:
                stage.destroy()
            except Exception:
                pass
