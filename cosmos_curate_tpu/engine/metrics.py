"""Prometheus gauges for the engine.

Equivalent of xenna's runtime gauges (reference
docs/curator/guides/OBSERVABILITY.md:286-330, ``ray_pipeline_*``): same
panel semantics under a ``pipeline_*`` prefix so the reference's Grafana
dashboard ports with a find/replace. No-op when prometheus_client is absent
or the exporter port is disabled.
"""

from __future__ import annotations

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


_SINGLETON: "EngineMetrics | None" = None


def get_metrics(port: int | None = None) -> "EngineMetrics":
    """Process-wide singleton: prometheus collectors register globally, so a
    second EngineMetrics in the same process would collide."""
    global _SINGLETON
    if _SINGLETON is None:
        _SINGLETON = EngineMetrics(port)
    return _SINGLETON


class EngineMetrics:
    def __init__(self, port: int | None = None) -> None:
        self.enabled = False
        try:
            from prometheus_client import Counter, Gauge, start_http_server
        except ImportError:
            return
        labels = ["stage"]
        self.actor_count = Gauge("pipeline_actor_count", "workers per stage", labels + ["state"])
        self.input_queue_size = Gauge("pipeline_input_queue_size", "queued tasks", labels)
        self.process_time_total = Counter(
            "pipeline_stage_process_time_total", "sum of process seconds", labels
        )
        self.deserialize_time_total = Counter(
            "pipeline_stage_deserialize_time_total", "sum of deserialize seconds", labels
        )
        self.tasks_total = Counter("pipeline_tasks_processed_total", "tasks out", labels)
        self.errors_total = Counter("pipeline_task_errors_total", "batch errors", labels)
        self.store_bytes = Gauge("pipeline_object_store_bytes", "object store usage", [])
        if port is not None:
            try:
                start_http_server(port)
                logger.info("prometheus metrics on :%d", port)
            except OSError as e:
                logger.warning("metrics server failed to start: %s", e)
        self.enabled = True

    def observe_result(self, stage: str, process_s: float, deser_s: float, n_out: int) -> None:
        if not self.enabled:
            return
        self.process_time_total.labels(stage).inc(process_s)
        self.deserialize_time_total.labels(stage).inc(deser_s)
        self.tasks_total.labels(stage).inc(n_out)

    def observe_error(self, stage: str) -> None:
        if self.enabled:
            self.errors_total.labels(stage).inc()

    def set_pool_state(self, stage: str, ready: int, pending: int, queued: int) -> None:
        if not self.enabled:
            return
        self.actor_count.labels(stage, "ready").set(ready)
        self.actor_count.labels(stage, "pending").set(pending)
        self.input_queue_size.labels(stage).set(queued)

    def set_store_bytes(self, used: int) -> None:
        if self.enabled:
            self.store_bytes.set(used)
