"""Prometheus gauges for the engine.

Equivalent of xenna's runtime gauges (reference
docs/curator/guides/OBSERVABILITY.md:286-330, ``ray_pipeline_*``): same
panel semantics under a ``pipeline_*`` prefix so the reference's Grafana
dashboard ports with a find/replace. No-op when prometheus_client is absent
or the exporter port is disabled.
"""

from __future__ import annotations

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


_SINGLETON: "EngineMetrics | None" = None


def get_metrics(port: int | None = None) -> "EngineMetrics":
    """Process-wide singleton: prometheus collectors register globally, so a
    second EngineMetrics in the same process would collide. A port passed
    after the singleton exists still starts the exporter — the device
    pipeline may record dispatches (creating the singleton portless)
    before the runner asks for the HTTP server."""
    global _SINGLETON
    if _SINGLETON is None:
        _SINGLETON = EngineMetrics(port)
    elif port is not None:
        _SINGLETON.ensure_server(port)
    return _SINGLETON


class EngineMetrics:
    def __init__(self, port: int | None = None) -> None:
        self.enabled = False
        try:
            from prometheus_client import Counter, Gauge, Histogram
        except ImportError:
            return
        labels = ["stage"]
        self.actor_count = Gauge("pipeline_actor_count", "workers per stage", labels + ["state"])
        self.input_queue_size = Gauge("pipeline_input_queue_size", "queued tasks", labels)
        self.process_time_total = Counter(
            "pipeline_stage_process_time_total", "sum of process seconds", labels
        )
        self.deserialize_time_total = Counter(
            "pipeline_stage_deserialize_time_total", "sum of deserialize seconds", labels
        )
        self.tasks_total = Counter("pipeline_tasks_processed_total", "tasks out", labels)
        self.errors_total = Counter("pipeline_task_errors_total", "batch errors", labels)
        self.store_bytes = Gauge("pipeline_object_store_bytes", "object store usage", [])
        # Per-dispatch device-pipeline signal (models/device_pipeline.py):
        # gap = device idle between micro-batches. The autoscaler's tuning
        # target is gap ≈ 0 (host prep keeps the device fed); a rising
        # gap/compute ratio on a stage means it needs more CPU prep workers,
        # not more device workers.
        self.dispatches_total = Counter(
            "pipeline_device_dispatches_total", "device micro-batch dispatches", labels
        )
        self.dispatch_gap_total = Counter(
            "pipeline_device_dispatch_gap_seconds_total",
            "device idle between micro-batches", labels,
        )
        self.dispatch_compute_total = Counter(
            "pipeline_device_compute_seconds_total", "device busy seconds", labels
        )
        self.dispatch_h2d_total = Counter(
            "pipeline_device_h2d_seconds_total", "host->device transfer seconds", labels
        )
        self.dispatch_d2h_total = Counter(
            "pipeline_device_d2h_seconds_total", "device->host readback seconds", labels
        )
        # Pipelined-runner flow signal (core/pipelined_runner.py): fraction
        # of the sampling window a stage's worker threads spent inside
        # process_data. ≈1 marks the bottleneck stage (give it workers);
        # ≈0 with a deep input queue downstream means starved/over-
        # provisioned. Queue depth rides the existing
        # pipeline_input_queue_size gauge.
        self.stage_busy_frac = Gauge(
            "pipeline_stage_busy_fraction",
            "worker busy fraction over the last sampling window", labels,
        )
        # Stage-overlap headline (core/pipelined_runner.py): fraction of
        # summed host-stage work hidden behind other stages over the LAST
        # run — 0 = lockstep, →1-max/sum = perfect overlap. Was a
        # bench-only log line; now a scrapeable gauge.
        self.overlap_frac = Gauge(
            "pipeline_overlap_frac",
            "fraction of summed stage busy time hidden by stage overlap "
            "(last completed run)", [],
        )
        # Caption-engine phase breakdown (models/vlm/engine.py via
        # stage_timer.record_caption_phases): seconds per phase per caption
        # stage, plus shared-prefix KV cache traffic. idle rising against
        # prefill+decode means the stage is starving the engine between
        # batches; hits/(hits+misses) ≈ 1 means the prefix cache is doing
        # its job (every request after the first skips the prefix prefill).
        self.caption_phase_total = Counter(
            "caption_phase_seconds_total",
            "caption engine seconds by phase", labels + ["phase"],
        )
        self.caption_prefix_hits = Counter(
            "caption_prefix_cache_hits_total", "shared-prefix KV cache hits", labels
        )
        self.caption_prefix_misses = Counter(
            "caption_prefix_cache_misses_total",
            "shared-prefix KV cache misses (builds)", labels,
        )
        self.caption_prefix_saved = Counter(
            "caption_prefix_tokens_saved_total",
            "prefill tokens skipped via shared-prefix hits", labels,
        )
        # Paged-KV + cross-job signals (models/vlm/engine.py block pool):
        # pool occupancy vs capacity is the admission headroom;
        # prefix_block_refs climbing with cow_copies ~0 means prefixes are
        # block-aligned and served copy-free; interleaved_steps > 0 means
        # several owners (stages/jobs) are decoding in ONE batch.
        self.caption_kv_blocks_used = Gauge(
            "caption_kv_blocks_used", "KV pool blocks in use", labels
        )
        self.caption_kv_blocks_total = Gauge(
            "caption_kv_blocks_total", "KV pool block capacity", labels
        )
        self.caption_prefix_block_refs = Counter(
            "caption_prefix_block_refs_total",
            "shared-prefix blocks referenced copy-free by admitted requests",
            labels,
        )
        self.caption_kv_cow = Counter(
            "caption_kv_cow_copies_total",
            "copy-on-write duplications of shared prefix tail blocks", labels,
        )
        self.caption_interleaved_steps = Counter(
            "caption_interleaved_steps_total",
            "decode steps whose active slots spanned 2+ owners", labels,
        )
        # Paged-attention path signals (ops/paged_attention.py): decode
        # steps served without a gathered KV working set, and the bytes of
        # contiguous view the gather programs would have materialized for
        # the same calls. kernel_steps == 0 on an engine configured
        # paged_attention="kernel" means the path regressed to gather.
        self.caption_paged_kernel_steps = Counter(
            "caption_paged_kernel_steps_total",
            "decode steps served by the paged-attention programs", labels,
        )
        self.caption_kv_gather_bytes_avoided = Counter(
            "caption_kv_gather_bytes_avoided_total",
            "KV working-set bytes not materialized thanks to paged attention",
            labels,
        )
        # per-owner queue/in-flight gauges for the SHARED engine: which
        # job/stage is occupying or starving the continuous batch
        self.caption_owner_queue = Gauge(
            "caption_owner_queue",
            "caption engine requests per owner by state",
            ["owner", "state"],
        )
        # Cross-host object-plane signal (engine/object_channel.py via
        # stage_timer.record_object_plane): bytes moved between nodes, how
        # long consumers waited for them, and whether push-ahead prefetch
        # hid the transfer. Healthy cross-host pipelining reads as
        # prefetch hits ≈ transfers and wait_seconds{kind="prefetch_hit"}
        # ≈ 0 while bytes_total keeps climbing — transfers overlap compute
        # instead of serializing against it.
        node_labels = ["node"]
        self.object_plane_transfers = Counter(
            "pipeline_object_plane_transfers_total",
            "cross-node segment transfers", node_labels + ["kind"],
        )
        self.object_plane_bytes = Counter(
            "pipeline_object_plane_bytes_total",
            "cross-node bytes moved", node_labels + ["kind"],
        )
        self.object_plane_wait = Counter(
            "pipeline_object_plane_wait_seconds_total",
            "seconds consumers waited on object-plane transfers",
            node_labels + ["kind"],
        )
        self.object_plane_prefetch_hits = Counter(
            "pipeline_object_plane_prefetch_hits_total",
            "batch inputs already local when demanded (push-ahead worked)",
            node_labels,
        )
        self.object_plane_prefetch_misses = Counter(
            "pipeline_object_plane_prefetch_misses_total",
            "batch inputs demand-fetched (no prefetch landed first)",
            node_labels,
        )
        # Corpus-index signal (dedup/corpus_index.py via
        # stage_timer.record_index_ops): vectors entering the persistent
        # index, query traffic, probe fan-out, and time spent on each side.
        # Healthy incremental dedup reads as queries tracking clip flow with
        # query_seconds << what a full re-cluster would cost; probes rising
        # against queries means nprobe (recall) is being bought with extra
        # shard matmuls. skipped_random > 0 flags a run whose embeddings
        # were refused for random-weight provenance.
        self.index_adds = Counter(
            "pipeline_index_adds_total", "vectors added to the corpus index", labels
        )
        self.index_add_seconds = Counter(
            "pipeline_index_add_seconds_total",
            "seconds spent appending/consolidating index fragments", labels,
        )
        self.index_queries = Counter(
            "pipeline_index_queries_total", "index query vectors", labels
        )
        self.index_query_seconds = Counter(
            "pipeline_index_query_seconds_total",
            "seconds spent in index query batches", labels,
        )
        self.index_probes = Counter(
            "pipeline_index_probes_total", "cluster shards probed by queries", labels
        )
        self.index_duplicates = Counter(
            "pipeline_index_duplicates_total",
            "query vectors flagged duplicate of an indexed neighbor", labels,
        )
        self.index_skipped_random = Counter(
            "pipeline_index_skipped_random_total",
            "vectors refused for random-weight provenance", labels,
        )
        # Index-server read path (dedup/index_server.py + /v1/search): the
        # latency SLO histogram (p50/p99 from the buckets), warm-shard-cache
        # byte traffic (hit ratio by BYTES — a fat shard miss hurts more
        # than a tiny one), compaction generations, and search sheds.
        # Healthy serving reads as p99 inside the interactive bucket range,
        # hit bytes >> miss bytes after warmup, and the generation gauge
        # ticking up while latency stays flat (compaction never stalls
        # reads — that is what the snapshots are for).
        self.search_latency = Histogram(
            "search_latency_seconds",
            "similarity-search request latency (submit to results)",
            labels + ["mode"],
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0),
        )
        self.search_requests = Counter(
            "search_requests_total", "similarity-search requests served",
            labels + ["mode"],
        )
        self.search_shed = Counter(
            "search_shed_total",
            "search requests shed with 429 (admission lane over capacity)",
            labels + ["reason"],
        )
        self.index_cache_hit_bytes = Counter(
            "index_cache_hit_bytes_total",
            "shard bytes served from the warm cache", labels,
        )
        self.index_cache_miss_bytes = Counter(
            "index_cache_miss_bytes_total",
            "shard bytes faulted in from storage", labels,
        )
        self.index_cache_evicted_bytes = Counter(
            "index_cache_evicted_bytes_total",
            "shard bytes evicted under the byte budget", labels,
        )
        self.index_compactions = Counter(
            "index_compactions_total", "compaction passes that published", labels,
        )
        self.index_generation = Gauge(
            "index_generation",
            "manifest generation (published by compaction / served by the "
            "index server)", labels,
        )
        # Per-node flow (engine/runner.py metrics tick): workers placed on
        # and CPU units used per connected node — the per-node counterpart
        # of pipeline_actor_count, so a merged dashboard shows which host
        # is starved instead of one flat pool number.
        self.node_workers = Gauge(
            "pipeline_node_workers", "stage workers placed per node", node_labels
        )
        self.node_cpus_used = Gauge(
            "pipeline_node_cpus_used", "CPU units in use per node", node_labels
        )
        # Node-loss fault tolerance (engine/runner.py + remote_plane.py):
        # declared node deaths (heartbeat deadline or link loss), objects
        # re-materialized through lineage reconstruction, and the wall time
        # those re-runs took. Healthy node churn reads as deaths > 0 with
        # reconstructed > 0 and ZERO dead-lettered batches; deaths with no
        # reconstruction means lineage had already expired (or the budget
        # is too tight) and work is dropping instead of recomputing.
        self.node_deaths = Counter(
            "pipeline_node_deaths_total",
            "agents declared dead (heartbeat deadline or link loss)",
            node_labels,
        )
        self.objects_reconstructed = Counter(
            "pipeline_objects_reconstructed_total",
            "lost objects re-materialized via lineage re-execution", labels,
        )
        self.reconstruction_seconds = Counter(
            "pipeline_reconstruction_seconds_total",
            "wall seconds spent re-executing producer batches", [],
        )
        # Job-service lifecycle (service/app.py): transitions per tenant,
        # current per-state counts, queue wait, and sheds. shed_total rising
        # under `tenant_queue_full` is a noisy tenant hitting its quota
        # (working as intended); rising under `queue_full` means the whole
        # service is over capacity — scale out or raise the dispatcher cap.
        self.service_transitions = Counter(
            "service_jobs_total", "job state transitions", ["tenant", "state"]
        )
        # NB: "service_jobs" itself is taken — prometheus_client registers
        # a Counter's base name (service_jobs_total → service_jobs)
        self.service_jobs_state = Gauge(
            "service_jobs_current", "current jobs per state", ["state"]
        )
        self.service_queue_depth = Gauge(
            "service_queue_depth", "queued jobs per lane", ["lane"]
        )
        self.service_queue_wait = Counter(
            "service_queue_wait_seconds_total",
            "summed pending->running wait", ["lane"],
        )
        self.service_dispatches = Counter(
            "service_dispatches_total",
            "jobs dispatched (divide queue_wait by this for mean wait)", ["lane"],
        )
        self.service_shed = Counter(
            "service_shed_total", "admissions shed with 429", ["tenant", "reason"]
        )
        # Live ops plane (observability/anomaly.py + service SLOs): detector
        # verdicts per stage and kind, and per-tenant SLO breaches. A flat
        # zero anomaly rate on a healthy fleet is the baseline; any nonzero
        # stuck_batch/starved_stage rate is an operator page, and
        # slo_breaches rising for one tenant with flat queue depth means
        # that tenant's target is mis-sized, not the service.
        self.anomalies_total = Counter(
            "pipeline_anomalies_total",
            "stall/anomaly detector verdicts", labels + ["kind"],
        )
        self.slo_breaches = Counter(
            "service_slo_breaches_total",
            "per-tenant SLO breaches (queue_wait, run_duration, success_rate)",
            ["tenant", "kind"],
        )
        self._server_started = False
        self.enabled = True
        if port is not None:
            self.ensure_server(port)

    def ensure_server(self, port: int) -> None:
        """Start the exporter once; safe to call after construction."""
        if not self.enabled or self._server_started:
            return
        from prometheus_client import start_http_server

        try:
            start_http_server(port)
            self._server_started = True
            logger.info("prometheus metrics on :%d", port)
        except OSError as e:
            logger.warning("metrics server failed to start: %s", e)

    def observe_result(self, stage: str, process_s: float, deser_s: float, n_out: int) -> None:
        if not self.enabled:
            return
        self.process_time_total.labels(stage).inc(process_s)
        self.deserialize_time_total.labels(stage).inc(deser_s)
        self.tasks_total.labels(stage).inc(n_out)

    def observe_error(self, stage: str) -> None:
        if self.enabled:
            self.errors_total.labels(stage).inc()

    def observe_dispatch(
        self, stage: str, *, gap_s: float, compute_s: float = 0.0,
        h2d_s: float = 0.0, d2h_s: float = 0.0,
    ) -> None:
        if not self.enabled:
            return
        self.dispatches_total.labels(stage).inc()
        self.dispatch_gap_total.labels(stage).inc(max(gap_s, 0.0))
        self.dispatch_compute_total.labels(stage).inc(max(compute_s, 0.0))
        self.dispatch_h2d_total.labels(stage).inc(max(h2d_s, 0.0))
        self.dispatch_d2h_total.labels(stage).inc(max(d2h_s, 0.0))

    def observe_dispatch_aggregate(self, stage: str, agg: dict) -> None:
        """Fold a worker-dumped dispatch AGGREGATE (stage_timer dump schema)
        into the counters — the finalize-time path that completes the
        ``pipeline_device_*`` series for spawned engine workers, which have
        no exporter of their own."""
        if not self.enabled:
            return
        self.dispatches_total.labels(stage).inc(max(0, int(agg.get("dispatches", 0))))
        self.dispatch_gap_total.labels(stage).inc(max(0.0, float(agg.get("gap_s", 0.0))))
        self.dispatch_compute_total.labels(stage).inc(
            max(0.0, float(agg.get("compute_s", 0.0)))
        )
        self.dispatch_h2d_total.labels(stage).inc(max(0.0, float(agg.get("h2d_s", 0.0))))
        self.dispatch_d2h_total.labels(stage).inc(max(0.0, float(agg.get("d2h_s", 0.0))))

    def observe_caption_phases(self, stage: str, phases: dict) -> None:
        """Fold one caption-engine drive's phase/cache deltas (the
        stage_timer.record_caption_phases schema) into the counters."""
        if not self.enabled:
            return
        for phase in ("prep_s", "vision_encode_s", "prefill_s", "decode_s", "idle_s"):
            self.caption_phase_total.labels(stage, phase[:-2]).inc(
                max(0.0, float(phases.get(phase, 0.0)))
            )
        self.caption_prefix_hits.labels(stage).inc(
            max(0, int(phases.get("prefix_cache_hits", 0)))
        )
        self.caption_prefix_misses.labels(stage).inc(
            max(0, int(phases.get("prefix_cache_misses", 0)))
        )
        self.caption_prefix_saved.labels(stage).inc(
            max(0, int(phases.get("prefix_tokens_saved", 0)))
        )
        self.caption_prefix_block_refs.labels(stage).inc(
            max(0, int(phases.get("prefix_block_refs", 0)))
        )
        self.caption_kv_cow.labels(stage).inc(
            max(0, int(phases.get("kv_cow_copies", 0)))
        )
        self.caption_interleaved_steps.labels(stage).inc(
            max(0, int(phases.get("interleaved_steps", 0)))
        )
        self.caption_paged_kernel_steps.labels(stage).inc(
            max(0, int(phases.get("paged_kernel_steps", 0)))
        )
        self.caption_kv_gather_bytes_avoided.labels(stage).inc(
            max(0, int(phases.get("kv_gather_bytes_avoided", 0)))
        )
        if "kv_blocks_used" in phases:
            self.caption_kv_blocks_used.labels(stage).set(
                max(0, int(phases["kv_blocks_used"]))
            )
        if "kv_blocks_total" in phases:
            self.caption_kv_blocks_total.labels(stage).set(
                max(0, int(phases["kv_blocks_total"]))
            )

    def observe_caption_owners(self, owners: dict) -> None:
        """Set the per-owner queue gauges from ``CaptionEngine.owner_stats``
        (cross-job continuous batching: who occupies the shared engine).
        Owners absent from the snapshot have their gauge children REMOVED —
        owner tags are per-stage-instance, so a long-lived service would
        otherwise accumulate stale series forever (and a stage that died
        mid-drive would pin a nonzero ``inflight`` at its last value)."""
        if not self.enabled:
            return
        seen = getattr(self, "_caption_owner_seen", None)
        if seen is None:
            seen = self._caption_owner_seen = set()
        for owner, stats in owners.items():
            seen.add(str(owner))
            for state in ("waiting", "ready", "inflight"):
                self.caption_owner_queue.labels(owner, state).set(
                    max(0, int(stats.get(state, 0)))
                )
        for owner in [o for o in seen if o not in owners]:
            seen.discard(owner)
            for state in ("waiting", "ready", "inflight"):
                try:
                    self.caption_owner_queue.remove(owner, state)
                except KeyError:
                    pass

    def observe_index(self, stage: str, deltas: dict) -> None:
        """Fold one corpus-index operation's deltas (the
        stage_timer.INDEX_OP_KEYS schema) into the counters."""
        if not self.enabled:
            return
        for counter, key in (
            (self.index_adds, "adds"),
            (self.index_add_seconds, "add_s"),
            (self.index_queries, "queries"),
            (self.index_query_seconds, "query_s"),
            (self.index_probes, "probes"),
            (self.index_duplicates, "duplicates"),
            (self.index_skipped_random, "skipped_random"),
        ):
            counter.labels(stage).inc(max(0.0, float(deltas.get(key, 0))))

    def observe_search(
        self, name: str, mode: str, latency_s: float | None, deltas: dict
    ) -> None:
        """Fold one search-serving delta set (stage_timer.SEARCH_KEYS
        schema) into the ``search_*`` / ``index_cache_*`` series."""
        if not self.enabled:
            return
        if latency_s is not None:
            self.search_latency.labels(name, mode).observe(max(0.0, float(latency_s)))
            self.search_requests.labels(name, mode).inc()
        for counter, key in (
            (self.index_cache_hit_bytes, "cache_hit_bytes"),
            (self.index_cache_miss_bytes, "cache_miss_bytes"),
            (self.index_cache_evicted_bytes, "cache_evicted_bytes"),
        ):
            v = float(deltas.get(key, 0))
            if v > 0:
                counter.labels(name).inc(v)

    def observe_search_shed(self, name: str, reason: str) -> None:
        if self.enabled:
            self.search_shed.labels(name, reason).inc()

    def observe_compaction(self, name: str, generation: int) -> None:
        if not self.enabled:
            return
        self.index_compactions.labels(name).inc()
        self.index_generation.labels(name).set(int(generation))

    def set_index_generation(self, name: str, generation: int) -> None:
        if self.enabled:
            self.index_generation.labels(name).set(int(generation))

    def observe_object_plane(self, node: str, deltas: dict) -> None:
        """Fold one object-plane delta set (stage_timer.OBJECT_PLANE_KEYS
        schema) into the counters under ``node``."""
        if not self.enabled:
            return
        for kind, (n_key, b_key, w_key) in {
            "fetch": ("fetches", "fetch_bytes", "fetch_wait_s"),
            "prefetch": ("prefetches", "prefetch_bytes", "prefetch_transfer_s"),
            "store_read": ("store_reads", "store_read_bytes", "store_read_wait_s"),
        }.items():
            self.object_plane_transfers.labels(node, kind).inc(
                max(0.0, float(deltas.get(n_key, 0)))
            )
            self.object_plane_bytes.labels(node, kind).inc(
                max(0.0, float(deltas.get(b_key, 0)))
            )
            self.object_plane_wait.labels(node, kind).inc(
                max(0.0, float(deltas.get(w_key, 0.0)))
            )
        self.object_plane_wait.labels(node, "prefetch_hit").inc(
            max(0.0, float(deltas.get("prefetch_hit_wait_s", 0.0)))
        )
        self.object_plane_prefetch_hits.labels(node).inc(
            max(0.0, float(deltas.get("prefetch_hits", 0)))
        )
        self.object_plane_prefetch_misses.labels(node).inc(
            max(0.0, float(deltas.get("prefetch_misses", 0)))
        )

    def set_node_state(self, node: str, workers: int, cpus_used: float) -> None:
        if self.enabled:
            self.node_workers.labels(node).set(workers)
            self.node_cpus_used.labels(node).set(cpus_used)

    def observe_node_death(self, node: str) -> None:
        if self.enabled:
            self.node_deaths.labels(node).inc()

    def observe_reconstruction(self, stage: str, objects: int, seconds: float) -> None:
        if not self.enabled:
            return
        self.objects_reconstructed.labels(stage).inc(max(0, int(objects)))
        self.reconstruction_seconds.inc(max(0.0, float(seconds)))

    def set_overlap_frac(self, frac: float) -> None:
        if self.enabled:
            self.overlap_frac.set(min(max(frac, 0.0), 1.0))

    def set_stage_busy(self, stage: str, frac: float) -> None:
        if self.enabled:
            self.stage_busy_frac.labels(stage).set(min(max(frac, 0.0), 1.0))

    def set_pool_state(self, stage: str, ready: int, pending: int, queued: int) -> None:
        if not self.enabled:
            return
        self.actor_count.labels(stage, "ready").set(ready)
        self.actor_count.labels(stage, "pending").set(pending)
        self.input_queue_size.labels(stage).set(queued)

    def set_store_bytes(self, used: int) -> None:
        if self.enabled:
            self.store_bytes.set(used)

    def observe_service_transition(self, tenant: str, state: str) -> None:
        if self.enabled:
            self.service_transitions.labels(tenant, state).inc()

    def set_service_states(self, counts: dict) -> None:
        """``counts``: state -> current job count (all known states, so a
        state that empties out reads 0 instead of its stale last value)."""
        if not self.enabled:
            return
        for state, n in counts.items():
            self.service_jobs_state.labels(state).set(int(n))

    def set_service_queue_depth(self, lane: str, depth: int) -> None:
        if self.enabled:
            self.service_queue_depth.labels(lane).set(int(depth))

    def observe_service_dispatch(self, lane: str, wait_s: float) -> None:
        if not self.enabled:
            return
        self.service_dispatches.labels(lane).inc()
        self.service_queue_wait.labels(lane).inc(max(0.0, wait_s))

    def observe_service_shed(self, tenant: str, reason: str) -> None:
        if self.enabled:
            self.service_shed.labels(tenant, reason).inc()

    def observe_anomaly(self, stage: str, kind: str) -> None:
        if self.enabled:
            self.anomalies_total.labels(stage, kind).inc()

    def observe_slo_breach(self, tenant: str, kind: str) -> None:
        if self.enabled:
            self.slo_breaches.labels(tenant, kind).inc()
