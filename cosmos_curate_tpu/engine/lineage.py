"""Bounded object lineage for mid-run node-loss reconstruction.

Equivalent capability of Ray's lineage-based object reconstruction (the
ownership model the reference engine inherits — Wang et al. NSDI'21): when
a node dies mid-run, every object it owned is re-derivable from *how it was
produced* instead of being data loss. The runner records, per live
intermediate ref, the ``(stage, input_refs)`` that produced it; when a
fetch fails because the owner node is dead, the producing batch is
re-enqueued at its stage (recursively, up to a depth/budget) and the
waiting batch re-enters dispatch once its inputs re-materialize.

The tracker is deliberately BOUNDED, not a run-long log:

- a record exists only while at least one of its output refs is still
  referenced by queued/in-flight downstream work — every record entry
  drops at ``store.release`` of its output;
- a record *holds* its input refs: their **physical** delete (the shm
  unlink / ReleaseObjects to the owner) is deferred until the record dies,
  so re-execution always has real inputs to read. Ledger accounting is
  NOT deferred — ``StoreBudget.release`` unaccounts immediately, so input
  seeding/backpressure behave exactly as before; the cost is one extra
  *generation* of segments resident per stage edge.

Non-deterministic stages are fine: reconstruction has reference semantics
(the regenerated outputs replace the lost refs positionally — same clips
out, possibly different bytes), matching Ray's semantics for
non-deterministic tasks.

The tracker is also the runner's location-aware deleter: it wraps the
inner deleter (``RemoteWorkerManager.release_data``) and decides per
release whether the physical delete proceeds now or is deferred.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# knobs (read by the runner, documented in docs/FAULT_TOLERANCE.md):
# how many producer generations a reconstruction may walk back, and how
# many producing batches one run may re-enqueue before giving up (the
# batch then dead-letters with the lost chain).
RECONSTRUCT_DEPTH_ENV = "CURATE_RECONSTRUCT_DEPTH"
RECONSTRUCT_BUDGET_ENV = "CURATE_RECONSTRUCT_BUDGET"
DEFAULT_RECONSTRUCT_DEPTH = 8
DEFAULT_RECONSTRUCT_BUDGET = 256


@dataclass
class LineageRecord:
    """One producing batch: which stage ran it, the exact input refs it
    consumed (held — physically retained until the record dies), and its
    output names in production order (positional identity: regenerated
    output i replaces lost output i)."""

    stage_idx: int
    input_refs: list
    out_names: list
    live: set = field(default_factory=set)
    # batch_id of an in-flight reconstruction re-running this record
    # (dedup: two consumers losing two outputs of one batch re-run it once)
    inflight_batch: int | None = None
    # inputs unheld exactly once, when the record can never be re-run again
    retired: bool = False

    @property
    def stage(self) -> int:
        return self.stage_idx


class LineageTracker:
    """Record/settle lineage and defer held inputs' physical deletes.

    Used as the ``StoreBudget`` deleter: ``__call__(ref)`` settles the
    ref's lineage and either physically deletes it (via the wrapped
    ``deleter``) or defers the delete until no live record holds it."""

    def __init__(self, deleter) -> None:
        self._deleter = deleter
        self._records: dict[str, LineageRecord] = {}  # out name -> record
        self._holds: dict[str, int] = {}  # input name -> live-record count
        self._deferred: dict[str, object] = {}  # released-but-held refs

    # -- recording ------------------------------------------------------
    def record(self, stage_idx: int, input_refs: list, out_refs: list) -> LineageRecord:
        """Register a completed batch's lineage: every output becomes
        re-derivable from ``input_refs`` at ``stage_idx``; the inputs are
        held (physical delete deferred) until every output releases."""
        rec = LineageRecord(
            stage_idx=stage_idx,
            input_refs=list(input_refs),
            out_names=[r.shm_name for r in out_refs],
            live={r.shm_name for r in out_refs},
        )
        for r in out_refs:
            self._records[r.shm_name] = rec
        for r in input_refs:
            self._holds[r.shm_name] = self._holds.get(r.shm_name, 0) + 1
        return rec

    def producer(self, name: str) -> LineageRecord | None:
        return self._records.get(name)

    def is_held(self, name: str) -> bool:
        return bool(self._holds.get(name))

    @property
    def num_records(self) -> int:
        """Distinct live records (bounded-memory observability)."""
        return len({id(r) for r in self._records.values()})

    # -- release (the StoreBudget deleter) ------------------------------
    def __call__(self, ref) -> None:
        if self.release(ref):
            self._delete(ref)

    def release(self, ref) -> bool:
        """Settle ``ref``'s lineage on store release. Returns True when the
        physical delete should proceed now; False when it is deferred
        because a live record still holds the ref as a reconstruction
        input — in that case the ref's own producer record survives too,
        so a DEEP loss (the held bytes died with their node) can walk one
        more generation up."""
        name = ref.shm_name
        rec = self._records.get(name)
        if rec is not None:
            rec.live.discard(name)
            self._maybe_retire(rec)
        if self._holds.get(name):
            # still a reconstruction input of a live record: bytes AND
            # lineage entry survive (depth > 1 needs the producer lookup)
            self._deferred[name] = ref
            return False
        self._records.pop(name, None)
        return True

    def _maybe_retire(self, rec: LineageRecord) -> None:
        """Unhold a record's inputs once NOTHING can re-run it again: every
        output released AND no output still held as a downstream record's
        input (a deferred output still needs its producer re-runnable)."""
        if rec.retired or rec.live:
            return
        if any(self._holds.get(n) for n in rec.out_names):
            return
        rec.retired = True
        for ir in rec.input_refs:
            self._unhold(ir)

    def _unhold(self, ref) -> None:
        name = ref.shm_name
        n = self._holds.get(name, 0) - 1
        if n > 0:
            self._holds[name] = n
            return
        self._holds.pop(name, None)
        dead = self._deferred.pop(name, None)
        if dead is not None:
            self._delete(dead)
        rec = self._records.get(name)
        if rec is not None and name not in rec.live:
            # released and no longer held: the lineage entry is dead, and
            # its producer may now retire too (upstream cascade)
            self._records.pop(name, None)
            self._maybe_retire(rec)

    def _delete(self, ref) -> None:
        try:
            self._deleter(ref)
        except Exception:  # a failed unlink must never break the loop
            logger.debug("lineage delete failed for %s", ref.shm_name, exc_info=True)

    # -- introspection --------------------------------------------------
    def chain(self, name: str, stage_names: list | None = None, depth: int = 8) -> list:
        """Human-readable producer chain for one lost name (DLQ metadata:
        what reconstruction would have walked). Each entry names the
        producing stage and its input refs; the walk follows the first
        input that itself has a record."""
        out: list = []
        seen: set[int] = set()
        cur: str | None = name
        while cur is not None and len(out) < depth:
            rec = self._records.get(cur)
            if rec is None or id(rec) in seen:
                break
            seen.add(id(rec))
            stage = (
                stage_names[rec.stage_idx]
                if stage_names is not None and 0 <= rec.stage_idx < len(stage_names)
                else f"stage[{rec.stage_idx}]"
            )
            out.append(
                {
                    "ref": cur,
                    "produced_by_stage": stage,
                    "inputs": [r.shm_name for r in rec.input_refs],
                }
            )
            cur = next(
                (r.shm_name for r in rec.input_refs if r.shm_name in self._records),
                None,
            )
        return out

    def drain(self) -> int:
        """Run-end cleanup: physically delete every still-deferred ref and
        clear all state. Returns how many deferred refs were flushed."""
        dead = list(self._deferred.values())
        self._records.clear()
        self._holds.clear()
        self._deferred.clear()
        for ref in dead:
            self._delete(ref)
        return len(dead)
