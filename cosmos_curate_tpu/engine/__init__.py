"""The streaming execution engine: the TPU-native equivalent of cosmos-xenna.

The reference delegates execution to cosmos-xenna over Ray actor pools
(SURVEY.md §1). Neither is available here, so this package implements the
same execution semantics from scratch:

- one worker pool per stage, autoscaled by measured throughput
- a shared-memory object store moving payloads between processes without
  re-serialization of large buffers (plasma-lite, PEP-574 zero-copy)
- a central orchestration loop that moves *refs*, never data
- backpressure: per-stage input queues bounded at max(16, 1.5 x pool size)
- dynamic chunking (a stage may emit any number of tasks)
- STREAMING (all stages live) and BATCH (stage-by-stage) modes
- worker recycling, per-stage retries, prometheus `pipeline_*` gauges
- cross-host: a per-node water-filling planner places CPU stages across
  connected node agents (remote_agent.py), a stage-affinity router keeps
  stage k's outputs on stage k+1's node, and push-ahead prefetch moves
  the remaining inter-node bytes behind compute (docs/PERFORMANCE.md,
  "Cross-host scheduling")

Device ownership (TPU-first): chips belong to ONE process per host — the
engine process — so stages with TPU resources execute on an in-process
executor there, while CPU stages fan out to spawned worker processes pinned
to JAX_PLATFORMS=cpu. This replaces the reference's fractional-GPU actor
packing with batch aggregation into the chip-owning process (SURVEY.md §7).
"""

from cosmos_curate_tpu.engine.runner import StreamingRunner

__all__ = ["StreamingRunner"]
