"""StreamingRunner: the central orchestration loop.

Equivalent capability of xenna's engine (reference ARCHITECTURE.md:20-110):
refs — never payloads — move between per-stage pools; input queues are
bounded (backpressure, max(lower_bound, multiplier x pool size)); stages may
emit any number of tasks (dynamic chunking); batches retry per
``num_run_attempts``; dead workers are detected and their batch re-queued;
workers recycle after ``worker_max_lifetime_m``; a throughput autoscaler
re-plans pool sizes on a cadence. STREAMING keeps all pools live; BATCH
runs stage-by-stage, letting each use the whole budget.
"""

from __future__ import annotations

import contextvars
import multiprocessing as mp
import os
import queue
import time
from collections import deque
from dataclasses import dataclass, field

from cosmos_curate_tpu.core.pipeline import ExecutionMode, PipelineSpec
from cosmos_curate_tpu.core.runner import RunnerInterface
from cosmos_curate_tpu.core.stage import NodeInfo, StageSpec
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.engine import object_store
from cosmos_curate_tpu.engine.autoscaler import (
    Budget,
    NodeBudget,
    StageScaleState,
    plan_allocation,
    plan_node_allocation,
)
from cosmos_curate_tpu.engine.metrics import get_metrics
from cosmos_curate_tpu.engine.pool import BasePool, ProcessPool, make_pool
from cosmos_curate_tpu.engine.worker import ReadyMsg, ResultMsg
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class _Batch:
    batch_id: int
    stage_idx: int
    refs: list[object_store.ObjectRef]
    attempts: int = 0
    # worker deaths are infrastructure failures, budgeted separately from
    # user-code exceptions (the reference's num_run_attempts_python counts
    # exceptions only, stage_interface.py:197; Ray reschedules on actor
    # death). A cap still bounds poison batches that kill workers.
    worker_deaths: int = 0
    # whole-NODE deaths budget separately again: one flaky node churning
    # through a run must not exhaust a batch's poison-batch guard — losing
    # a node is the cluster's fault, never the batch's
    node_deaths: int = 0
    # set at dispatch: which worker holds the batch, and (when the stage
    # declares batch_timeout_s) the monotonic instant after which that
    # worker is presumed hung and killed
    worker_id: str = ""
    deadline: float | None = None
    # monotonic dispatch instant — the live ops plane reports in-flight
    # batch AGES from it, and the stall detector compares those ages
    # against the stage's recent batch-duration percentiles
    dispatched_at: float | None = None


# A batch survives this many worker deaths before being dropped
# (poison-batch guard: e.g. an input that OOM-kills every worker that
# touches it must not respawn workers forever).
MAX_WORKER_DEATHS_PER_BATCH = 3
# ... and this many whole-node deaths (separate budget: node churn is
# infrastructure weather, not evidence the batch is poison)
MAX_NODE_DEATHS_PER_BATCH = 3

# driver-side prefetch-ahead: how many agent-owned segments may stream
# toward the driver concurrently while their consumer batch is still queued
# (bounded so prefetch can never monopolize the fetch pool or /dev/shm)
PREFETCH_INFLIGHT_LIMIT = 6


@dataclass
class _StageState:
    spec: StageSpec
    pool: BasePool
    in_queue: deque = field(default_factory=deque)  # ObjectRefs of tasks
    retry_queue: deque = field(default_factory=deque)  # _Batch objects
    dispatched: int = 0
    completed: int = 0
    errored_batches: int = 0
    dead_lettered: int = 0  # dropped batches persisted to the DLQ

    def queue_limit(self, lower: int, mult: float) -> int:
        return max(lower, int(mult * max(1, self.pool.num_workers())))


class StreamingRunner(RunnerInterface):
    def __init__(self, *, metrics_port: int | None = None, poll_interval_s: float = 0.02) -> None:
        self.metrics = get_metrics(metrics_port)
        self.poll_interval_s = poll_interval_s
        self._remote_mgr = None
        self._fetch_pool = None
        self._final_fetches: list = []
        # run-scoped dead-letter queue (engine/dead_letter.py); created per
        # run() so batch mode's stage-by-stage sub-runs share one run dir
        self.dlq = None
        # stage name -> summed worker busy seconds (MFU accounting; the
        # sequential runner exposes the same attribute with wall time)
        self.stage_times: dict[str, float] = {}
        # per-node planner state (cross-host runs): preferred node per
        # stage (the router's affinity key) and the last emitted
        # stage -> {node -> workers} plan, exposed for tests/reports
        self._pref_node: list[str] | None = None
        self.node_plan: dict[str, dict[str, int]] = {}
        # driver-side prefetch-ahead bookkeeping (remote-owned segments
        # whose consumer stage runs on the driver): remote shm_name ->
        # LOCAL accounted copy, plus what's still streaming in
        self._prefetched: dict[str, object] = {}
        self._prefetch_inflight: set[str] = set()
        # (target_node, shm_name) push-ahead requests already issued
        self._pushed: set[tuple[str, str]] = set()
        # -- node-loss fault tolerance (cross-host runs only) ----------
        # lineage tracker (engine/lineage.py): per live intermediate ref,
        # the (stage, input_refs) that produced it — None on single-host
        # runs, where no node can die out from under the store
        self._tracker = None
        self._stage_names: list[str] = []
        # recon batch_id (negative, never colliding with the dispatch
        # counter) -> LineageRecord being re-executed; start times feed
        # pipeline_reconstruction_seconds_total
        self._recon: dict[int, object] = {}
        self._recon_started: dict[int, float] = {}
        self._recon_seq = 0
        self._recon_spent = 0
        self._recon_depth = 0
        self._recon_budget = 0
        # batches parked off every queue while their lost inputs
        # re-materialize: batch_id -> (stage_idx, batch, missing names)
        self._lost_waiters: dict[int, tuple[int, _Batch, set]] = {}
        # lost name -> regenerated ref nobody was waiting for yet (an
        # in-flight batch dispatched before the swap adopts it on failure)
        self._renamed: dict[str, object_store.ObjectRef] = {}
        # run receipts for the flight recorder's node_events section
        self.node_events: list[dict] = []
        self.objects_reconstructed = 0
        self.reconstruction_seconds = 0.0

    # ------------------------------------------------------------------
    def run(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        if not spec.stages:
            return list(spec.input_data) if spec.config.return_last_stage_outputs else None
        from cosmos_curate_tpu.engine.dead_letter import DeadLetterQueue
        from cosmos_curate_tpu.observability.tracing import traced_span

        self.dlq = DeadLetterQueue()  # lazy: writes nothing unless a drop happens
        try:
            with traced_span(
                "pipeline.run", runner="streaming", stages=len(spec.stages)
            ):
                if spec.config.execution_mode is ExecutionMode.BATCH:
                    return self._run_batch(spec)
                return self._run_streaming(spec, spec.stages)
        finally:
            # workers only surface dispatch aggregates via their at-exit
            # dump: fold whatever landed during pool shutdown into THIS
            # process's aggregates + prometheus counters, so engine runs
            # report complete pipeline_device_* series
            self._merge_worker_dispatch_stats()

    @staticmethod
    def _merge_worker_dispatch_stats() -> None:
        from cosmos_curate_tpu.observability.stage_timer import (
            DISPATCH_DUMP_DIR_ENV,
            merge_new_dumped_summaries,
        )

        path = os.environ.get(DISPATCH_DUMP_DIR_ENV)
        if path:
            merge_new_dumped_summaries(path)

    # ------------------------------------------------------------------
    def _run_batch(self, spec: PipelineSpec) -> list[PipelineTask] | None:
        """Stage-by-stage: each stage is a one-stage streaming run.

        Intermediate stages must always return their outputs (they feed the
        next stage) regardless of ``return_last_stage_outputs``, which only
        governs the final stage."""
        from dataclasses import replace as dc_replace

        tasks: list[PipelineTask] = list(spec.input_data)
        inner_cfg = dc_replace(spec.config, return_last_stage_outputs=True)
        for i, stage_spec in enumerate(spec.stages):
            last = i == len(spec.stages) - 1
            cfg = spec.config if last else inner_cfg
            sub = PipelineSpec(input_data=tasks, stages=[stage_spec], config=cfg)
            tasks = self._run_streaming(sub, [stage_spec]) or []
        return tasks if spec.config.return_last_stage_outputs else None

    # ------------------------------------------------------------------
    def _run_streaming(
        self, spec: PipelineSpec, stage_specs: list[StageSpec]
    ) -> list[PipelineTask] | None:
        cfg = spec.config
        object_store.cleanup_stale_segments()
        node = NodeInfo(
            node_id="local",
            num_cpus=cfg.num_cpus or float(os.cpu_count() or 1),
            num_tpu_chips=self._discover_tpus(cfg, stage_specs),
        )
        mp_results: mp.Queue = mp.get_context("spawn").Queue()
        thread_results: queue.Queue = queue.Queue()
        # cross-node data plane (engine/remote_plane.py): active when
        # CURATE_ENGINE_DRIVER_PORT is set — connected node agents' CPUs
        # join the budget and CPU-stage pools place workers on them
        from cosmos_curate_tpu.engine.remote_plane import maybe_create_manager

        remote_mgr = maybe_create_manager(
            thread_results, local_cpu_budget=node.num_cpus
        )
        budget = Budget(
            cpus=node.num_cpus + (remote_mgr.remote_cpus() if remote_mgr else 0.0),
            tpus=float(node.num_tpu_chips),
        )
        # warm spares prepay worker spawn+import (~3-5 s) so autoscale-up is
        # stage-setup-bound only; CURATE_PREWARM=0 disables
        from cosmos_curate_tpu.engine.pool import PrewarmPool

        n_prewarm = int(os.environ.get("CURATE_PREWARM", "2"))
        any_process_stage = any(not s.stage.resources.uses_tpu for s in stage_specs)
        prewarm = (
            PrewarmPool(mp_results, size=n_prewarm)
            if n_prewarm > 0 and any_process_stage
            else None
        )
        states = [
            _StageState(
                spec=s,
                pool=make_pool(
                    s, node, mp_results, thread_results, pool_id=i,
                    prewarm=prewarm, remote_mgr=remote_mgr,
                ),
            )
            for i, s in enumerate(stage_specs)
        ]
        self._remote_mgr = remote_mgr
        # node-loss lineage (cross-host only): the tracker wraps the
        # location-aware deleter — a release settles the ref's lineage and
        # may DEFER the physical delete while a live record still needs the
        # ref as a reconstruction input (one extra generation of segments
        # resident; ledger accounting is never deferred)
        self._tracker = None
        if remote_mgr is not None:
            from cosmos_curate_tpu.engine.lineage import (
                DEFAULT_RECONSTRUCT_BUDGET,
                DEFAULT_RECONSTRUCT_DEPTH,
                RECONSTRUCT_BUDGET_ENV,
                RECONSTRUCT_DEPTH_ENV,
                LineageTracker,
            )

            self._tracker = LineageTracker(remote_mgr.release_data)
            self._recon_depth = int(
                os.environ.get(RECONSTRUCT_DEPTH_ENV, DEFAULT_RECONSTRUCT_DEPTH)
            )
            self._recon_budget = int(
                os.environ.get(RECONSTRUCT_BUDGET_ENV, DEFAULT_RECONSTRUCT_BUDGET)
            )
        store = object_store.StoreBudget(
            capacity_bytes=int(_host_memory_bytes() * cfg.streaming.object_store_fraction),
            # location-aware deletion: agent-owned segments release at their
            # owner over the control link, local ones unlink here (lineage
            # tracker in front when cross-host reconstruction is live)
            deleter=(
                self._tracker
                if self._tracker is not None
                else (remote_mgr.release_data if remote_mgr is not None else None)
            ),
        )
        # network transfers NEVER run on the orchestration loop (the same
        # property _RemoteInQ documents for sends): localizing agent-owned
        # inputs for local workers and materializing remote final outputs
        # happen on this executor, with completions drained like results
        import concurrent.futures

        self._fetch_pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="obj-fetch"
            )
            if remote_mgr is not None
            else None
        )
        localize_done: queue.Queue = queue.Queue()
        # batch_id -> _Batch while on the fetch pool: these are in neither
        # `batches` nor any queue, so exception-exit cleanup must walk this
        localizing: dict[int, _Batch] = {}
        # prefetch-ahead completions: (remote_ref, local_ref|None, err, s)
        prefetch_done: queue.Queue = queue.Queue()
        self._prefetch_done = prefetch_done
        # per-run reset: batch mode reuses this runner for stage-by-stage
        # sub-runs, and stale push-ahead dedup would suppress real pushes
        self._prefetched.clear()
        self._prefetch_inflight.clear()
        self._pushed.clear()
        self._pref_node = None
        # node-loss state is run-scoped too
        self._stage_names = [s.name for s in stage_specs]
        self._recon = {}
        self._recon_started = {}
        self._recon_seq = 0
        self._recon_spent = 0
        self._lost_waiters = {}
        self._renamed = {}
        self.node_events = []
        self.objects_reconstructed = 0
        self.reconstruction_seconds = 0.0
        # (stage_state, batch, Future[list-of-values]): final-stage batches
        # whose remote outputs are streaming in; inputs stay held until the
        # future lands (failure re-executes the batch)
        self._final_fetches: list = []
        # Segments created by this run (and its workers) carry this pid.
        os.environ.setdefault("CURATE_STORE_OWNER", str(os.getpid()))

        # live ops plane: periodic atomic status snapshots + stall
        # detection when CURATE_LIVE_STATUS_DIR is exported (run_split
        # derives it from the output root). Per-stage batch-duration
        # windows feed the detector's p99 baseline.
        from cosmos_curate_tpu.observability.live_status import LiveStatusPublisher

        publisher = LiveStatusPublisher.from_env(runner="streaming")
        self._stage_durs: list[deque] = [deque(maxlen=128) for _ in states]

        # Inputs are seeded lazily inside the loop, gated on store headroom
        # and the first stage's queue bound — a huge input list must not
        # fill /dev/shm upfront.
        pending_inputs = iter(spec.input_data)
        inputs_exhausted = not spec.input_data

        # initial allocation (per-node plan when agents are connected)
        self._apply_allocation(states, budget, cfg, remote_mgr=remote_mgr, local_node=node)

        batches: dict[int, _Batch] = {}
        next_batch_id = 0
        outputs: list[PipelineTask] = []  # final-stage results, already materialized
        last_autoscale = time.monotonic()
        pending_setup_errors: list[str] = []

        # one driver-side span per stage (child of the ambient pipeline.run
        # span); every batch this stage dispatches — local process worker,
        # in-process TPU thread, or a worker on a remote agent — carries its
        # traceparent, so worker spans parent onto the driver's stage span.
        # Created immediately before the try whose finally ends them: a
        # setup failure in between would leak never-exported spans, and the
        # collected trace would blame propagation for a setup error
        from cosmos_curate_tpu.observability import tracing

        stage_spans = []
        for st in states:
            span = tracing.start_span(f"stage.{st.spec.name}", stage=st.spec.name)
            stage_spans.append(span)
            st.pool.trace_context = tracing.format_traceparent(span)

        try:
            while True:
                progressed = False
                # 0. seed more inputs while the store has headroom
                if not inputs_exhausted:
                    limit0 = states[0].queue_limit(
                        cfg.streaming.max_queued_lower_bound,
                        cfg.streaming.max_queued_multiplier,
                    )
                    while len(states[0].in_queue) < limit0 and store.has_headroom():
                        task = next(pending_inputs, None)
                        if task is None:
                            inputs_exhausted = True
                            break
                        ref = object_store.put(task)
                        store.account(ref)
                        states[0].in_queue.append(ref)
                        # push seeded inputs toward stage 0's planned node
                        # while earlier batches still process there
                        self._maybe_prefetch(0, [ref], store)
                        progressed = True
                # 1. drain results
                for msg in self._drain(mp_results, thread_results):
                    progressed = True
                    if isinstance(msg, ReadyMsg):
                        self._on_ready(states, msg, pending_setup_errors)
                        continue
                    self._on_result(states, batches, msg, outputs, store, cfg)
                # 1b. drain finished localizations
                while True:
                    try:
                        lb, err = localize_done.get_nowait()
                    except queue.Empty:
                        break
                    progressed = True
                    localizing.pop(lb.batch_id, None)
                    stx = states[lb.stage_idx]
                    if err is None:
                        # inputs are local now: dispatch with priority
                        stx.retry_queue.appendleft(lb)
                    else:
                        self._on_lost_or_failed_inputs(
                            states, stx, lb, store,
                            f"localizing inputs failed: {err}",
                        )
                # 1c. drain finished prefetch-aheads into the local cache
                while True:
                    try:
                        pref_ref, local_ref, perr, transfer_s = prefetch_done.get_nowait()
                    except queue.Empty:
                        break
                    progressed = True
                    self._prefetch_inflight.discard(pref_ref.shm_name)
                    if perr is not None or local_ref is None:
                        # advisory: the demand localize path still works;
                        # losing the race to a release is a normal outcome
                        logger.debug("prefetch of %s failed: %s", pref_ref.shm_name, perr)
                        continue
                    store.account(local_ref)
                    self._prefetched[pref_ref.shm_name] = local_ref
                    self._record_object_plane(
                        prefetches=1,
                        prefetch_bytes=pref_ref.total_size,
                        prefetch_transfer_s=transfer_s,
                    )
                    # oldest-first eviction: a copy whose batch got routed
                    # to the owner node instead is never adopted — it must
                    # not pin store budget for the rest of the run
                    while len(self._prefetched) > 64:
                        store.release(
                            self._prefetched.pop(next(iter(self._prefetched)))
                        )
                if pending_setup_errors:
                    raise RuntimeError(
                        "stage worker setup failed:\n" + "\n".join(pending_setup_errors)
                    )
                # 2. failure detector + live replan: sweep per-agent
                # heartbeat deadlines; a newly-declared node death replans
                # placement IMMEDIATELY (not next autoscale tick), so
                # orphaned queued batches re-route via the locality router
                # while the reap below requeues the dead node's in-flight
                # work. Then detect dead workers; reap draining ones.
                # 2a first kills workers whose batch blew its deadline, so
                # the very next reap pass requeues the batch.
                if remote_mgr is not None:
                    dead_events = remote_mgr.poll_node_deaths()
                    if dead_events:
                        progressed = True
                        for ev in dead_events:
                            self.metrics.observe_node_death(ev["node"])
                            # stale push-ahead dedup for the dead node: a
                            # rejoining agent starts with an empty prefetch
                            # cache, so suppressed re-pushes would be misses
                            self._pushed = {
                                k for k in self._pushed if k[0] != ev["node"]
                            }
                        self.node_events.extend(dead_events)
                        self._apply_allocation(
                            states, budget, cfg,
                            remote_mgr=remote_mgr, local_node=node,
                        )
                        last_autoscale = time.monotonic()
                progressed |= self._expire_hung_batches(states, batches)
                progressed |= self._reap_dead_workers(states, batches, store)
                for st in states:
                    if isinstance(st.pool, ProcessPool):
                        st.pool.reap_draining()
                # 3. dispatch
                for i, st in enumerate(states):
                    limit_next = (
                        states[i + 1].queue_limit(
                            cfg.streaming.max_queued_lower_bound, cfg.streaming.max_queued_multiplier
                        )
                        if i + 1 < len(states)
                        else None
                    )
                    if limit_next is not None and len(states[i + 1].in_queue) >= limit_next:
                        continue  # backpressure: downstream full
                    bs = max(1, st.spec.stage.batch_size)
                    idle = []
                    for w in st.pool.idle_workers():
                        if st.pool.lifetime_expired(w) and w.busy_batch is None:
                            # recycle in place: the replacement inherits the
                            # expiring worker's node, or the per-node plan
                            # and reality drift apart and the next replan
                            # pays a stop/start churn to reconcile them
                            node_id = st.pool.worker_node(w)
                            st.pool.stop_worker(w)
                            st.pool.start_worker(node_id=node_id)
                            continue
                        idle.append(w)
                    while idle:
                        if st.retry_queue:  # failed batches keep their identity
                            batch = st.retry_queue.popleft()
                        elif st.in_queue:
                            refs = [
                                st.in_queue.popleft()
                                for _ in range(min(bs, len(st.in_queue)))
                            ]
                            batch = _Batch(next_batch_id, i, refs)
                            next_batch_id += 1
                        else:
                            break
                        # stage-affinity routing: of the idle workers,
                        # prefer the one whose node already holds the most
                        # input bytes
                        # (reference ARCHITECTURE.md:70-81 — node-local
                        # deserialization preferred), with a tiebreak
                        # toward the NEXT stage's planned node so this
                        # batch's outputs land where their consumer's
                        # workers live
                        next_pref = (
                            self._pref_node[i + 1]
                            if self._pref_node is not None and i + 1 < len(self._pref_node)
                            else None
                        )
                        w = self._pick_worker(idle, batch.refs, remote_mgr, next_pref)
                        idle.remove(w)
                        if remote_mgr is not None and not self._worker_node(w):
                            # prefetch-ahead already copied some (or all) of
                            # these inputs into the driver store: adopt the
                            # local copies before deciding to localize
                            self._adopt_prefetched(batch, store)
                        if (
                            remote_mgr is not None
                            and not self._worker_node(w)
                            and any(remote_mgr.owner_node(r) for r in batch.refs)
                        ):
                            # a LOCAL consumer needs agent-owned bytes: pull
                            # them on the fetch pool, never this loop; the
                            # batch re-enters dispatch when done (1b above).
                            # copy_context: the fetch spans must parent onto
                            # the ambient run span, not fragment the trace
                            localizing[batch.batch_id] = batch
                            self._fetch_pool.submit(
                                contextvars.copy_context().run,
                                self._localize_batch,
                                batch, store, remote_mgr, localize_done,
                            )
                            progressed = True
                            continue
                        batch.worker_id = w.worker_id
                        timeout = st.spec.batch_timeout_s
                        batch.dispatched_at = time.monotonic()
                        batch.deadline = (
                            batch.dispatched_at + timeout if timeout else None
                        )
                        batches[batch.batch_id] = batch
                        st.pool.submit(w, batch.batch_id, batch.refs)
                        if batch.batch_id >= 0:
                            # reconstruction re-runs (negative ids) settle
                            # into waiters, never into completed/errored —
                            # counting them would break the invariant that
                            # completed + errored covers every dispatch
                            st.dispatched += 1
                        progressed = True
                # 4. autoscale. The per-node path re-derives its NodeBudget
                # list from the live agents each replan, so a dead agent's
                # capacity stops being planned for (and a late joiner's
                # starts being used) without re-basing a flat budget.
                now = time.monotonic()
                if now - last_autoscale >= cfg.streaming.autoscale_interval_s:
                    self._apply_allocation(
                        states, budget, cfg, remote_mgr=remote_mgr, local_node=node
                    )
                    last_autoscale = now
                    if remote_mgr is not None:
                        for nid, s in remote_mgr.stats().items():
                            self.metrics.set_node_state(
                                nid, s["workers"], s["cpus_used"]
                            )
                        # the driver is a node too: without this the
                        # per-node panels omit every driver-placed worker
                        # (always the TPU stages) and hide driver
                        # saturation
                        driver_workers = sum(
                            st.pool.workers_by_node().get("", 0)
                            for st in states
                        )
                        self.metrics.set_node_state(
                            "driver", driver_workers, remote_mgr.local_cpus_used
                        )
                # 5. metrics + completion
                for st in states:
                    ready = len([w for w in st.pool.workers.values() if w.ready])
                    pending = st.pool.num_workers() - ready
                    self.metrics.set_pool_state(st.spec.name, ready, pending, len(st.in_queue))
                self.metrics.set_store_bytes(store.used)
                if publisher is not None:
                    publisher.maybe_publish(
                        lambda: self._build_live_snapshot(
                            states, batches, store, remote_mgr
                        )
                    )
                # 5b. settle finished final-output fetches: success frees
                # the batch's held inputs; failure re-executes the batch
                # (its outputs died with their owner)
                if self._final_fetches:
                    pending = []
                    for stx, fb, f_refs, fut in self._final_fetches:
                        if not fut.done():
                            pending.append((stx, fb, f_refs, fut))
                            continue
                        progressed = True
                        try:
                            outputs.extend(fut.result())
                        except Exception as e:
                            # outputs that died WITH their node charge the
                            # node-death budget (and stamp the lost node),
                            # not the poison-batch guard
                            lost = [
                                r.shm_name
                                for r in f_refs
                                if remote_mgr is not None and remote_mgr.owner_dead(r)
                            ]
                            _retry_or_drop(
                                stx, fb, store,
                                f"final outputs lost with their owner: {e}",
                                dead_letter=self._dead_letter,
                                node_death=bool(lost),
                                lost_node=self._lost_node(lost),
                            )
                            continue
                        stx.completed += 1  # settled: count the logical batch
                        for r in fb.refs:
                            store.release(r)
                    self._final_fetches = pending
                if (
                    inputs_exhausted
                    and not batches
                    and not localizing
                    and not self._final_fetches
                    and not self._lost_waiters
                    and all(not st.in_queue and not st.retry_queue for st in states)
                ):
                    break
                if not progressed:
                    time.sleep(self.poll_interval_s)
            # per-stage disposition summary: completed counts LOGICAL
            # batches (a re-executed batch settles once), so completed +
            # errored accounts for every dispatched batch exactly once
            self.stage_counts = {
                st.spec.name: {
                    "dispatched": st.dispatched,
                    "completed": st.completed,
                    "errored": st.errored_batches,
                    "dead_lettered": st.dead_lettered,
                }
                for st in states
            }
            for name, c in self.stage_counts.items():
                logger.info(
                    "stage %s: %d dispatched, %d completed, %d errored, "
                    "%d dead-lettered",
                    name, c["dispatched"], c["completed"], c["errored"],
                    c["dead_lettered"],
                )
            if self.dlq is not None and self.dlq.recorded:
                logger.error(
                    "%d dropped batch(es) persisted to the dead-letter queue: "
                    "%s — inspect with `cosmos-curate-tpu dlq list`",
                    self.dlq.recorded, self.dlq.run_dir,
                )
            return outputs if cfg.return_last_stage_outputs else None
        finally:
            if publisher is not None:
                # terminal snapshot (state=finished) so readers can tell
                # 'runner exited' from 'publisher wedged'
                try:
                    publisher.finalize(
                        self._build_live_snapshot(states, batches, store, remote_mgr)
                    )
                except Exception:
                    logger.exception("final live-status publish failed")
            # quiesce the fetch pool FIRST: a still-running _localize_batch
            # mutates batch.refs and releases refs itself — walking
            # `localizing` concurrently would double-release
            if self._fetch_pool is not None:
                self._fetch_pool.shutdown(wait=True)
            # prefetch-ahead copies nobody adopted: completions still on the
            # queue (pool is quiesced, so this drain is final), then the
            # cache itself
            while True:
                try:
                    pref_ref, local_ref, _perr, _s = prefetch_done.get_nowait()
                except queue.Empty:
                    break
                if local_ref is not None:
                    store.release(local_ref)
            for local_ref in self._prefetched.values():
                store.release(local_ref)
            self._prefetched.clear()
            self._prefetch_inflight.clear()
            for batch in batches.values():  # in-flight on exception exit
                for r in batch.refs:
                    store.release(r)
            # batches on (or finished with) the localize fetch pool are in
            # neither `batches` nor any queue — walk them too
            while True:
                try:
                    lb, _err = localize_done.get_nowait()
                except queue.Empty:
                    break
                localizing.setdefault(lb.batch_id, lb)
            for batch in localizing.values():
                for r in batch.refs:
                    store.release(r)
            for _stx, fb, _refs, _fut in self._final_fetches:  # inputs held for fetch
                for r in fb.refs:
                    store.release(r)
            self._final_fetches = []
            # batches parked for reconstruction and regenerated-but-
            # unadopted outputs are in no queue — walk them too
            for _sidx, wb, _missing in self._lost_waiters.values():
                for r in wb.refs:
                    store.release(r)
            self._lost_waiters.clear()
            for ref in self._renamed.values():
                store.release(ref)
            self._renamed.clear()
            for st in states:
                for r in st.in_queue:
                    store.release(r)
                for batch in st.retry_queue:
                    for r in batch.refs:
                        store.release(r)
                st.pool.shutdown()
            if self._tracker is not None:
                # physically delete every still-deferred lineage input
                # BEFORE the manager shutdown below closes the control
                # links its ReleaseObjects frames ride on
                self._tracker.drain()
            if prewarm is not None:
                prewarm.shutdown()
            if remote_mgr is not None:
                self.remote_stats = remote_mgr.stats()
                # shutdown's Bye triggers each agent's FORCED final stats
                # flush and drains it before closing sockets — snapshot the
                # per-node object-plane view after, or the tail window's
                # transfers would be missing from runner.object_plane and
                # the run report
                remote_mgr.shutdown()
                from cosmos_curate_tpu.observability.stage_timer import (
                    object_plane_summaries,
                )

                self.object_plane = object_plane_summaries()
            for st, span in zip(states, stage_spans):
                span.set_attribute("dispatched", st.dispatched)
                span.set_attribute("completed", st.completed)
                span.set_attribute("errored", st.errored_batches)
                tracing.end_span(span)

    # ------------------------------------------------------------------
    def _build_live_snapshot(self, states, batches, store, remote_mgr) -> dict:
        """One live-status snapshot (observability/live_status.py) from the
        orchestration loop's own state: per-stage queues and worker
        occupancy, every in-flight batch with its age and retry/death
        budgets, store occupancy, and per-node heartbeat ages."""
        from cosmos_curate_tpu.observability.live_status import (
            MAX_INFLIGHT_PER_STAGE,
        )

        now = time.monotonic()
        by_stage: dict[int, list] = {}
        for b in batches.values():
            by_stage.setdefault(b.stage_idx, []).append(b)
        stages: dict[str, dict] = {}
        durs_all = getattr(self, "_stage_durs", [])
        for i, st in enumerate(states):
            workers = list(st.pool.workers.values())
            busy = sum(1 for w in workers if w.busy_batch is not None)
            inflight = sorted(
                by_stage.get(i, ()), key=lambda b: b.dispatched_at or now
            )[:MAX_INFLIGHT_PER_STAGE]
            durs = sorted(durs_all[i]) if i < len(durs_all) else []
            stages[st.spec.name] = {
                "queue_depth": len(st.in_queue),
                "retry_queue": len(st.retry_queue),
                "busy_frac": round(busy / max(1, len(workers)), 4),
                "workers": len(workers),
                "dispatched": st.dispatched,
                "completed": st.completed,
                "errored": st.errored_batches,
                "dead_lettered": st.dead_lettered,
                "p50_s": round(durs[len(durs) // 2], 4) if durs else 0.0,
                "p99_s": (
                    round(durs[min(len(durs) - 1, int(len(durs) * 0.99))], 4)
                    if durs
                    else 0.0
                ),
                "inflight": [
                    {
                        "batch_id": b.batch_id,
                        "age_s": round(now - (b.dispatched_at or now), 3),
                        "attempt": b.attempts + 1,
                        "worker_deaths": b.worker_deaths,
                        "node_deaths": b.node_deaths,
                        "worker": b.worker_id,
                        "deadline_in_s": (
                            round(b.deadline - now, 3)
                            if b.deadline is not None
                            else None
                        ),
                    }
                    for b in inflight
                ],
            }
        snap: dict = {"stages": stages, "store_bytes": store.used}
        if remote_mgr is not None:
            snap["nodes"] = remote_mgr.heartbeat_ages()
            if self._recon or self._lost_waiters or self.objects_reconstructed:
                snap["reconstruction"] = {
                    "objects_reconstructed": self.objects_reconstructed,
                    "re_runs_inflight": len(self._recon),
                    "parked_waiters": len(self._lost_waiters),
                }
        return snap

    @staticmethod
    def _worker_node(w) -> str:
        """'' for locally placed workers, else the agent's node id (the
        single implementation lives on BasePool — one place owns the
        remote-handle convention)."""
        return BasePool.worker_node(w)

    def _pick_worker(self, idle, refs, remote_mgr, next_pref: str | None = None):
        """Stage-affinity router. Primary signal: input-byte locality (the
        worker whose node owns the most input bytes moves the least data
        to START the batch). Secondary: a bonus of half the batch's bytes
        for the NEXT stage's planned node — so when input locality doesn't
        clearly favor another node, the batch runs where its outputs will
        be consumed and the inter-stage hop disappears entirely. Inputs
        already prefetched into the driver store count as driver-local."""
        if remote_mgr is None or len(idle) == 1:
            return idle[0]
        owned_bytes: dict[str, int] = {}
        total = 0
        for r in refs:
            node = (
                "" if r.shm_name in self._prefetched else remote_mgr.owner_node(r)
            )
            owned_bytes[node] = owned_bytes.get(node, 0) + r.total_size
            total += r.total_size
        bonus = total // 2 + 1

        def score(w) -> int:
            node = self._worker_node(w)
            s = owned_bytes.get(node, 0)
            if next_pref is not None and node == next_pref:
                s += bonus
            return s

        return max(idle, key=score)

    def _adopt_prefetched(self, batch: _Batch, store) -> None:
        """Swap a local-bound batch's prefetched inputs for their cached
        driver-store copies: the remote originals release at their owner
        and the demand-localize hop is skipped (a prefetch HIT — the
        transfer already happened behind compute)."""
        hits = 0
        for j, r in enumerate(batch.refs):
            local = self._prefetched.pop(r.shm_name, None)
            if local is None:
                continue
            store.release(r)  # routes the delete to the owning agent
            batch.refs[j] = local
            hits += 1
        if hits:
            self._record_object_plane(prefetch_hits=hits)

    def _maybe_prefetch(self, stage_idx: int, refs, store) -> None:
        """Start moving ``refs`` toward the node the planner assigned to
        ``stage_idx`` BEFORE any batch is formed: agent targets get a
        PrefetchObjects push-ahead over the control link; a driver target
        pulls on the fetch pool into the local cache. Bounded, deduped,
        advisory — every skipped prefetch degrades to the demand pull."""
        remote_mgr = self._remote_mgr
        if remote_mgr is None or self._pref_node is None:
            return
        if not 0 <= stage_idx < len(self._pref_node):
            return
        pref = self._pref_node[stage_idx]
        if len(self._pushed) > 65536:
            # dedup memory stays bounded on corpus-scale runs; a pruned
            # entry can at worst cause one redundant advisory push, which
            # the agent's own cache/in-flight dedup absorbs
            self._pushed.clear()
        import contextvars

        to_push: list = []
        for r in refs:
            owner = remote_mgr.owner_node(r)
            if owner == pref:
                continue  # already where the consumer will run
            key = (pref, r.shm_name)
            if key in self._pushed:
                continue
            if pref != "":
                self._pushed.add(key)
                to_push.append(r)  # one control frame for the whole batch
                continue
            # consumer runs on the driver: bounded pull-ahead into the
            # local store, never on this loop
            if (
                len(self._prefetch_inflight) >= PREFETCH_INFLIGHT_LIMIT
                or not store.has_headroom()
                or r.shm_name in self._prefetched
                or r.shm_name in self._prefetch_inflight
            ):
                continue
            self._pushed.add(key)
            self._prefetch_inflight.add(r.shm_name)
            self._fetch_pool.submit(
                contextvars.copy_context().run,
                self._prefetch_local, r, remote_mgr, self._prefetch_done,
            )
        if to_push:
            remote_mgr.push_ahead(to_push, pref)

    @staticmethod
    def _prefetch_local(ref, remote_mgr, done_q) -> None:
        """Fetch-pool job: pull one agent-owned segment into the driver
        store ahead of demand. Completion (or failure — advisory) lands on
        ``done_q`` for the loop to account."""
        t0 = time.monotonic()
        try:
            local = remote_mgr.localize(ref)
            done_q.put((ref, local, None, time.monotonic() - t0))
        except Exception as e:
            done_q.put((ref, None, e, time.monotonic() - t0))

    @staticmethod
    def _record_object_plane(**deltas) -> None:
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        record_object_plane(**deltas)

    @staticmethod
    def _localize_batch(batch, store, remote_mgr, done_q) -> None:
        """Fetch-pool job: pull a batch's agent-owned inputs into the
        driver store (remote workers resolve their own inputs agent-side).
        The batch is invisible to dispatch while here, so mutating its refs
        is race-free. Every pull here is a DEMAND fetch the consumer waits
        on — a prefetch miss in the object-plane accounting."""
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        try:
            for j, r in enumerate(batch.refs):
                if not remote_mgr.owner_node(r):
                    continue
                t0 = time.monotonic()
                local = remote_mgr.localize(r)
                record_object_plane(
                    fetches=1, fetch_bytes=r.total_size,
                    fetch_wait_s=time.monotonic() - t0, prefetch_misses=1,
                )
                store.account(local)
                store.release(r)  # routes the delete to the owning agent
                batch.refs[j] = local
            done_q.put((batch, None))
        except Exception as e:
            done_q.put((batch, e))

    @staticmethod
    def _fetch_final_values(refs, remote_mgr) -> list:
        """Fetch-pool job: materialize one batch's remote final outputs and
        release them at their owner. ALL-OR-NOTHING: any failure raises so
        the loop re-executes the whole batch — returning a partial list
        would duplicate the fetched outputs on the re-run."""
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        values = []
        err: Exception | None = None
        for r in refs:
            try:
                if err is None:
                    t0 = time.monotonic()
                    values.append(remote_mgr.fetch_value_if_remote(r))
                    record_object_plane(
                        fetches=1, fetch_bytes=r.total_size,
                        fetch_wait_s=time.monotonic() - t0,
                    )
            except Exception as e:  # keep releasing the rest
                err = e
            finally:
                remote_mgr.release_data(r)
        if err is not None:
            raise err
        return values

    def _free_ref(self, ref) -> None:
        """Location-aware delete for refs OUTSIDE the store ledger (final
        outputs, late results)."""
        if self._remote_mgr is not None:
            self._remote_mgr.release_data(ref)
        else:
            object_store.delete(ref)

    def _on_ready(self, states, msg: ReadyMsg, errors: list[str]) -> None:
        for st in states:
            w = st.pool.workers.get(msg.worker_id)
            if w is None:
                continue
            if msg.error:
                errors.append(f"[{st.spec.name}/{msg.worker_id}] {msg.error}")
            else:
                w.ready = True
            return

    def _on_result(self, states, batches, msg: ResultMsg, outputs, store, cfg) -> None:
        batch = batches.pop(msg.batch_id, None)
        if batch is None:
            # Late result for a batch the reaper already requeued (worker
            # sent the result then died). At-least-once semantics: the rerun
            # wins; this result's outputs must not leak.
            for r in msg.out_refs:
                self._free_ref(r)
            return
        st = states[batch.stage_idx]
        w = st.pool.workers.get(msg.worker_id)
        if w is not None:
            w.busy_batch = None
            w.batches_done += 1
        if batch.batch_id in self._recon:
            # a reconstruction re-run: its outputs replace lost refs
            # positionally instead of feeding the next stage's queue
            self._handle_recon_result(states, batch, msg, store)
            return
        if msg.error is not None:
            if self._remote_mgr is not None:
                if any(self._remote_mgr.owner_dead(r) for r in batch.refs):
                    # the batch failed FETCHING inputs whose owner died,
                    # not running user code: reconstruct via lineage (or
                    # charge the node-death budget) instead of burning
                    # retries
                    self._on_lost_or_failed_inputs(
                        states, st, batch, store,
                        f"inputs lost to a dead node: {_tail(msg.error, 400)}",
                    )
                    return
                if getattr(msg, "input_loss", False):
                    # fetch infrastructure failed without a provably-dead
                    # owner (transient drop, racing release): infra budget,
                    # never the user-code retry budget — and never a
                    # misleading "dead node" reason
                    _retry_or_drop(
                        st, batch, store,
                        f"input fetch failed: {_tail(msg.error, 400)}",
                        dead_letter=self._dead_letter,
                    )
                    return
            self.metrics.observe_error(st.spec.name)
            batch.attempts += 1
            if batch.attempts < max(1, st.spec.num_run_attempts):
                logger.warning(
                    "stage %s batch %d failed (attempt %d), retrying:\n%s",
                    st.spec.name, batch.batch_id, batch.attempts, _tail(msg.error),
                )
                st.retry_queue.append(batch)
            else:
                logger.error(
                    "stage %s batch %d failed permanently, dropping %d tasks:\n%s",
                    st.spec.name, batch.batch_id, len(batch.refs), _tail(msg.error),
                )
                st.errored_batches += 1
                # persist BEFORE releasing the refs (the payloads die with them)
                self._dead_letter(
                    st, batch,
                    reason=f"num_run_attempts ({st.spec.num_run_attempts}) exhausted",
                    error=msg.error,
                )
                for r in batch.refs:
                    store.release(r)
            return
        # throughput samples count per EXECUTION (the autoscaler sizes pools
        # from them, per node); st.completed counts per logical batch, so it
        # is deferred to fetch-settlement when remote final outputs are
        # pending
        st.pool.record_sample(
            msg.process_time_s,
            node_id=self._worker_node(w) if w is not None else "",
        )
        if batch.stage_idx < len(getattr(self, "_stage_durs", ())):
            # live-status percentile window (bounded deque, loop thread only)
            self._stage_durs[batch.stage_idx].append(msg.process_time_s)
        self.stage_times[st.spec.name] = (
            self.stage_times.get(st.spec.name, 0.0) + msg.process_time_s
        )
        self.metrics.observe_result(
            st.spec.name, msg.process_time_s, msg.deserialize_time_s, len(msg.out_refs)
        )
        nxt = batch.stage_idx + 1
        final_remote: list = []
        forward: list = []
        for r in msg.out_refs:
            if nxt < len(states):
                store.account(r)  # queue bounds + input gating provide backpressure
                states[nxt].in_queue.append(r)
                forward.append(r)
                continue
            # Final-stage outputs must NOT enter the admission ledger: they
            # are only freed at run end, so accounting them would eventually
            # pin ``used`` above capacity, halt input seeding, and livelock
            # the completion condition. Local segments materialize + free
            # here (shm read, no network); agent-owned ones stream on the
            # fetch pool, never this loop.
            if self._remote_mgr is not None and self._remote_mgr.owner_node(r):
                if cfg.return_last_stage_outputs:
                    final_remote.append(r)
                else:
                    self._free_ref(r)
                continue
            if cfg.return_last_stage_outputs:
                outputs.append(object_store.get(r))
            object_store.delete(r)
        if forward:
            if self._tracker is not None:
                # lineage: these outputs are re-derivable from this batch's
                # inputs at this stage; the inputs' physical delete (below)
                # defers until every output releases
                self._tracker.record(batch.stage_idx, list(batch.refs), forward)
            # push-ahead: start moving these outputs toward the node the
            # planner chose for the NEXT stage while this loop keeps
            # orchestrating — by dispatch time the bytes are (mostly) there
            self._maybe_prefetch(nxt, forward, store)
        if final_remote:
            # the batch's INPUTS stay held until its remote outputs are
            # safely fetched: if the owning agent dies first, the loop
            # re-executes the batch instead of losing completed work
            # (found by tests/engine/test_agent_churn.py: 299/300 outputs)
            self._final_fetches.append(
                (
                    st,
                    batch,
                    final_remote,
                    self._fetch_pool.submit(
                        contextvars.copy_context().run,
                        self._fetch_final_values, final_remote, self._remote_mgr,
                    ),
                )
            )
            return
        st.completed += 1
        for r in batch.refs:
            store.release(r)

    _MAX_SETUP_DEATHS = 3

    def _expire_hung_batches(self, states, batches) -> bool:
        """Hung-batch deadlines: a batch past its ``batch_timeout_s`` means
        its worker is presumed deadlocked (stuck decoder, wedged socket) —
        it will never return on its own, so the worker is SIGKILLed and the
        normal dead-worker reap requeues the batch under the worker-death
        budget. Local process workers only: remote ones are killed by their
        node agent's watchdog (the driver can't signal across hosts), and
        in-process TPU worker threads cannot be killed at all."""
        now = time.monotonic()
        progressed = False
        for batch in batches.values():
            if batch.deadline is None or now < batch.deadline:
                continue
            st = states[batch.stage_idx]
            timeout = st.spec.batch_timeout_s or 0.0
            # whatever happens below happens once per dispatch: the retry
            # (if any) re-arms the deadline at its own dispatch time
            batch.deadline = None
            w = st.pool.workers.get(batch.worker_id)
            if w is None or w.busy_batch != batch.batch_id:
                continue  # worker already died/recycled; reap handles it
            proc = w.proc
            if proc is None:
                logger.error(
                    "stage %s batch %d exceeded batch_timeout_s=%.1fs on "
                    "in-process worker %s; threads cannot be killed — waiting",
                    st.spec.name, batch.batch_id, timeout, w.worker_id,
                )
                continue
            if getattr(proc, "_agent", None) is not None:
                continue  # agent watchdog owns remote deadlines
            logger.warning(
                "stage %s batch %d exceeded batch_timeout_s=%.1fs; killing "
                "hung worker %s",
                st.spec.name, batch.batch_id, timeout, w.worker_id,
            )
            self.metrics.observe_error(st.spec.name)
            try:
                proc.kill()  # SIGKILL: a hung worker may ignore SIGTERM
            except (OSError, AttributeError):
                logger.debug("kill failed for %s", w.worker_id, exc_info=True)
            progressed = True
        return progressed

    def _dead_letter(
        self, stx, batch: _Batch, *, reason: str, error: str = "",
        lost_node: str = "", lineage=None,
    ) -> None:
        """Persist a permanently-dropped batch's payloads + metadata to the
        DLQ. Must run BEFORE the batch's refs are released. Never raises —
        DLQ failure degrades to the old log-only drop. Owner-loss drops
        stamp ``lost_node`` and the lineage chain reconstruction gave up
        on, so `dlq show` can separate node churn from poison batches."""
        dlq = self.dlq
        if dlq is None or not dlq.enabled:
            return
        fetch = (
            self._remote_mgr.fetch_value_if_remote
            if self._remote_mgr is not None
            else object_store.get
        )
        tasks, errs = [], []
        for r in batch.refs:
            try:
                tasks.append(fetch(r))
            except Exception as e:  # partial entries beat no entries
                errs.append(f"{r.shm_name}: {e}")
        if dlq.record(
            stage_name=stx.spec.name,
            batch_id=batch.batch_id,
            tasks=tasks,
            attempts=batch.attempts,
            worker_deaths=batch.worker_deaths,
            reason=reason,
            error=error,
            payload_errors=errs or None,
            lost_node=lost_node,
            node_deaths=batch.node_deaths,
            lineage=lineage,
        ):
            stx.dead_lettered += 1

    # -- lineage-based reconstruction ----------------------------------
    def _adopt_renamed(self, batch: _Batch, store) -> int:
        """Swap inputs an earlier reconstruction already regenerated: the
        lost name retires from the ledger, the regenerated ref takes its
        place, and the batch can dispatch without another re-run."""
        n = 0
        for j, r in enumerate(batch.refs):
            new = self._renamed.pop(r.shm_name, None)
            if new is None:
                continue
            store.release(r)
            batch.refs[j] = new
            n += 1
        return n

    def _on_lost_or_failed_inputs(
        self, states, stx, batch: _Batch, store, reason: str
    ) -> None:
        """Disposition for a batch whose input fetch failed: reconstruct
        lost inputs via lineage when possible; otherwise charge the
        node-death budget (owner provably dead) or the generic infra
        budget (transient fetch failure), dead-lettering with the lost
        node + lineage chain when the budget is gone."""
        mgr = self._remote_mgr
        if mgr is not None and self._tracker is not None:
            self._adopt_renamed(batch, store)
            missing = {r.shm_name for r in batch.refs if mgr.owner_dead(r)}
            if missing:
                if self._schedule_reconstruction(states, batch, missing, store):
                    logger.warning(
                        "stage %s batch %d: reconstructing %d lost input(s) "
                        "via lineage (%s)",
                        stx.spec.name, batch.batch_id, len(missing), reason,
                    )
                    return
                _retry_or_drop(
                    stx, batch, store, reason,
                    dead_letter=self._dead_letter, node_death=True,
                    lost_node=self._lost_node(missing),
                    lineage=self._chain_for(missing),
                )
                return
        _retry_or_drop(stx, batch, store, reason, dead_letter=self._dead_letter)

    def _schedule_reconstruction(
        self, states, batch: _Batch, missing: set, store, depth: int = 0
    ) -> bool:
        """Re-enqueue the producing batch of every name in ``missing`` at
        its stage (deduped per record; recursively when the producer's own
        inputs died too, up to CURATE_RECONSTRUCT_DEPTH, charging the
        per-run CURATE_RECONSTRUCT_BUDGET); ``batch`` parks off every
        queue and re-enters dispatch once its inputs re-materialize.
        Returns False when lineage/depth/budget cannot cover the loss —
        the caller then drops the batch with the chain in its DLQ entry.

        Plan-then-commit: the WHOLE transitive producer set is validated
        (lineage present, depth, budget) before any record is marked
        in-flight or any batch enqueued — a partial registration would
        leave records claiming an in-flight re-run that never dispatches,
        parking later waiters forever."""
        tracker = self._tracker
        if tracker is None:
            return False
        # plan: walk the lineage breadth-first, collecting every record
        # that must re-run and which of ITS inputs are lost too
        to_run: dict[int, tuple] = {}  # id(rec) -> (rec, producer_missing)
        frontier = set(missing)
        d = depth
        while frontier:
            if d > self._recon_depth:
                return False
            next_frontier: set = set()
            for name in frontier:
                rec = tracker.producer(name)
                if rec is None:
                    return False  # lineage gone (outputs released): no path back
                if id(rec) in to_run or rec.inflight_batch is not None:
                    continue  # already planned / already re-running
                producer_missing = {
                    r.shm_name
                    for r in rec.input_refs
                    if r.shm_name not in self._renamed
                    and self._remote_mgr.owner_dead(r)
                }
                to_run[id(rec)] = (rec, producer_missing)
                next_frontier |= producer_missing
            frontier = next_frontier
            d += 1
        if self._recon_spent + len(to_run) > self._recon_budget:
            logger.error(
                "reconstruction budget exhausted (%d/%d producer re-runs): "
                "giving up on batch %d",
                self._recon_spent, self._recon_budget, batch.batch_id,
            )
            return False
        # commit: every record gets its re-run batch; batches whose own
        # inputs are lost park as waiters (they dispatch when the deeper
        # regeneration swaps in), the rest enter dispatch immediately
        self._recon_spent += len(to_run)
        for rec, producer_missing in to_run.values():
            self._recon_seq -= 1  # negative ids: never collide with dispatch
            rb = _Batch(self._recon_seq, rec.stage_idx, list(rec.input_refs))
            rec.inflight_batch = rb.batch_id
            self._recon[rb.batch_id] = rec
            self._recon_started[rb.batch_id] = time.monotonic()
            self._adopt_renamed(rb, store)
            if producer_missing:
                self._park_waiter(rb, producer_missing)
            else:
                states[rec.stage_idx].retry_queue.appendleft(rb)
        self._park_waiter(batch, missing)
        return True

    def _park_waiter(self, batch: _Batch, missing: set) -> None:
        batch.deadline = None
        parked = self._lost_waiters.get(batch.batch_id)
        if parked is not None:
            parked[2].update(missing)
        else:
            self._lost_waiters[batch.batch_id] = (batch.stage_idx, batch, set(missing))

    def _handle_recon_result(self, states, batch: _Batch, msg, store) -> None:
        """Settle a reconstruction re-run: regenerated outputs replace the
        lost refs positionally (reference semantics — same items out, new
        segment names) in every parked waiter; waiters whose missing set
        empties re-enter dispatch. Unclaimed regenerations park in the
        rename map (an in-flight batch dispatched before the node died
        adopts them when its own fetch fails)."""
        rec = self._recon.get(batch.batch_id)
        st = states[batch.stage_idx]
        if msg.error is not None:
            self.metrics.observe_error(st.spec.name)
            if self._remote_mgr is not None:
                self._adopt_renamed(batch, store)
                deeper = {
                    r.shm_name for r in batch.refs if self._remote_mgr.owner_dead(r)
                }
                if deeper and self._schedule_reconstruction(
                    states, batch, deeper, store
                ):
                    return
            batch.attempts += 1
            if batch.attempts < max(1, st.spec.num_run_attempts) + 1:
                st.retry_queue.appendleft(batch)
                return
            self._fail_reconstruction(
                states, rec, batch, store,
                f"re-execution failed: {_tail(msg.error, 400)}",
            )
            return
        self._recon.pop(batch.batch_id, None)
        started = self._recon_started.pop(batch.batch_id, None)
        dur = time.monotonic() - started if started is not None else 0.0
        rec.inflight_batch = None
        new_outs = list(msg.out_refs)
        # re-record lineage FIRST, from the inputs that ACTUALLY produced
        # these outputs (renamed adoptions included): the new record's
        # holds must exist before any old ref releases below — retiring
        # the old record otherwise physically deletes the held inputs,
        # and a SECOND node loss would drop data instead of reconstructing
        positional = new_outs[: len(rec.out_names)]
        if positional and self._tracker is not None:
            self._tracker.record(rec.stage_idx, list(batch.refs), positional)
        adopted = 0
        for i, old in enumerate(rec.out_names):
            new_ref = new_outs[i] if i < len(new_outs) else None
            waiter = self._waiter_for(old)
            if waiter is not None:
                wid, sidx, wb, miss = waiter
                if new_ref is None:
                    # the re-run returned fewer outputs than the original
                    # (stage not reference-stable): this waiter is lost
                    del self._lost_waiters[wid]
                    self._fail_waiter(
                        states, sidx, wb, store,
                        f"reconstruction produced no output for {old}",
                    )
                    continue
                for j, r in enumerate(wb.refs):
                    if r.shm_name == old:
                        store.release(r)  # retire the lost ref
                        wb.refs[j] = new_ref
                store.account(new_ref)
                adopted += 1
                miss.discard(old)
                if not miss:
                    del self._lost_waiters[wid]
                    states[sidx].retry_queue.appendleft(wb)
                continue
            if new_ref is None:
                continue
            if old in rec.live:
                # the old name is still referenced somewhere (queued input,
                # in-flight batch): park the regeneration for adoption
                self._renamed[old] = new_ref
                store.account(new_ref)
                adopted += 1
            else:
                # nobody references this output anymore: retire its fresh
                # lineage entry, then free the bytes
                if self._tracker is None or self._tracker.release(new_ref):
                    self._free_ref(new_ref)
        for extra in new_outs[len(rec.out_names):]:
            self._free_ref(extra)
        # inputs this recon batch ADOPTED from earlier reconstructions were
        # ledger-accounted at adoption, and recon batches settle here (never
        # through the normal completion path that releases inputs) — release
        # them now or they pin StoreBudget.used for the rest of the run
        for r in batch.refs:
            if store.tracks(r):
                store.release(r)
        self.objects_reconstructed += adopted
        self.reconstruction_seconds += dur
        if adopted:
            self.metrics.observe_reconstruction(st.spec.name, adopted, dur)
            logger.info(
                "reconstructed %d object(s) at stage %s in %.2fs",
                adopted, st.spec.name, dur,
            )

    def _retry_recon_or_fail(self, states, batch: _Batch, store, reason: str) -> None:
        """Infra-failure disposition for a reconstruction batch: requeue
        under the node-death budget (never the DLQ — its payloads belong
        to the waiters), cascading the give-up to every waiter."""
        batch.node_deaths += 1
        if batch.node_deaths <= MAX_NODE_DEATHS_PER_BATCH:
            states[batch.stage_idx].retry_queue.appendleft(batch)
            return
        self._fail_reconstruction(
            states, self._recon.get(batch.batch_id), batch, store, reason
        )

    def _waiter_for(self, name: str):
        for wid, (sidx, wb, miss) in self._lost_waiters.items():
            if name in miss:
                return wid, sidx, wb, miss
        return None

    def _fail_waiter(
        self, states, sidx: int, wb: _Batch, store, reason: str,
        lost_node: str = "", chain=None,
    ) -> None:
        if wb.batch_id in self._recon:
            # a recon batch was itself waiting on a deeper reconstruction:
            # cascade the failure to everything waiting on ITS outputs
            self._fail_reconstruction(states, self._recon[wb.batch_id], wb, store, reason)
            return
        stx = states[sidx]
        stx.errored_batches += 1
        logger.error(
            "batch %d dropped: %s (%d tasks lost)",
            wb.batch_id, reason, len(wb.refs),
        )
        self._dead_letter(stx, wb, reason=reason, lost_node=lost_node, lineage=chain)
        for r in wb.refs:
            store.release(r)

    def _fail_reconstruction(self, states, rec, batch: _Batch, store, reason: str) -> None:
        """A reconstruction re-run is permanently gone: every batch waiting
        on this record's outputs drops to the DLQ with the lost node and
        the lineage chain reconstruction gave up on."""
        self._recon.pop(batch.batch_id, None)
        self._recon_started.pop(batch.batch_id, None)
        if rec is not None:
            rec.inflight_batch = None
        # adopted-then-failed recon inputs were ledger-accounted: release
        # them here, exactly as the success path does
        for r in batch.refs:
            if store.tracks(r):
                store.release(r)
        names = set(rec.out_names) if rec is not None else set()
        lost_node = self._lost_node(names)
        for wid, (sidx, wb, miss) in list(self._lost_waiters.items()):
            hit = miss & names
            if not hit:
                continue
            del self._lost_waiters[wid]
            self._fail_waiter(
                states, sidx, wb, store,
                f"reconstruction gave up: {reason}",
                lost_node=lost_node, chain=self._chain_for(hit),
            )

    def _lost_node(self, names) -> str:
        """The dead node that owned the first resolvable lost name (DLQ
        ``lost_node`` stamp — operators distinguish 'node died past budget'
        from 'batch is poison')."""
        mgr = self._remote_mgr
        if mgr is None:
            return ""
        for n in names:
            node = mgr.node_of(n)
            if node:
                return node
        return ""

    def _chain_for(self, names) -> list | None:
        if self._tracker is None:
            return None
        chain: list = []
        for n in list(names)[:4]:  # bounded: DLQ meta, not a full dump
            chain.extend(self._tracker.chain(n, self._stage_names))
        return chain or None

    def _reap_dead_workers(self, states, batches, store) -> bool:
        progressed = False
        for st in states:
            if not isinstance(st.pool, ProcessPool):
                continue
            for w in list(st.pool.workers.values()):
                proc = w.proc
                if proc is not None and not proc.is_alive():
                    exitcode = getattr(proc, "exitcode", "remote")
                    logger.warning("worker %s died (exit %s)", w.worker_id, exitcode)
                    st.pool.workers.pop(w.worker_id, None)
                    st.pool.note_worker_gone(w)
                    agent = getattr(proc, "_agent", None)
                    if not w.ready and (agent is None or agent.alive):
                        # died before ReadyMsg with its NODE alive: likely a
                        # setup crash. A cap prevents an infinite respawn
                        # loop when setup is deterministically broken (e.g.
                        # OOM loading weights). A whole-agent death is node
                        # churn, not a setup bug — it must not burn the cap
                        # (found by tests/engine/test_agent_churn.py).
                        st.pool.setup_deaths += 1
                        if st.pool.setup_deaths >= self._MAX_SETUP_DEATHS:
                            raise RuntimeError(
                                f"stage {st.spec.name}: {st.pool.setup_deaths} workers "
                                f"died during setup (last exit {exitcode}); "
                                f"aborting pipeline"
                            )
                    if w.busy_batch is not None and w.busy_batch in batches:
                        batch = batches.pop(w.busy_batch)
                        # a worker lost WITH its whole node is node churn,
                        # charged against the separate node-death budget —
                        # one flaky node must not exhaust the poison-batch
                        # guard for every batch that was in flight on it
                        node_death = agent is not None and not agent.alive
                        if batch.batch_id in self._recon:
                            self._retry_recon_or_fail(
                                states, batch, store,
                                f"worker {w.worker_id} died re-running it",
                            )
                        elif node_death:
                            _retry_or_drop(
                                st, batch, store,
                                f"its node {agent.node_id} died mid-batch",
                                dead_letter=self._dead_letter,
                                node_death=True, lost_node=agent.node_id,
                            )
                        else:
                            _retry_or_drop(
                                st, batch, store,
                                f"worker {w.worker_id} died processing it (poison batch?)",
                                dead_letter=self._dead_letter,
                            )
                    # replace on the dead worker's node (plan-consistent);
                    # place_for falls back to least-loaded when that whole
                    # node died with it
                    st.pool.start_worker(node_id=st.pool.worker_node(w))
                    progressed = True
        return progressed

    def _apply_allocation(
        self, states, budget: Budget, cfg, remote_mgr=None, local_node=None
    ) -> None:
        window = cfg.streaming.speed_estimation_window_s
        scale_states = [
            StageScaleState(
                spec=st.spec,
                current_workers=st.pool.num_workers(),
                throughput_per_worker=st.pool.throughput_per_worker(window),
                queued=len(st.in_queue),
                node_rates=st.pool.node_throughputs(window),
            )
            for st in states
        ]
        if remote_mgr is None:
            targets = plan_allocation(scale_states, budget)
            self._pref_node = [""] * len(states)
            for st, target in zip(states, targets):
                cur = st.pool.num_workers()
                for _ in range(max(0, target - cur)):
                    st.pool.start_worker()
                if target < cur:
                    # scale down idle workers only
                    for w in st.pool.idle_workers()[: cur - target]:
                        st.pool.stop_worker(w)
            return
        # cross-host: one NodeBudget per live host, re-derived every replan
        # so churned agents fall out of the plan and joiners enter it
        nodes = [
            NodeBudget(
                "",
                cpus=local_node.num_cpus if local_node is not None else budget.cpus,
                tpu_chips=(
                    local_node.num_tpu_chips if local_node is not None else 0
                ),
                memory_gb=_host_memory_bytes() / (1 << 30),
            )
        ] + [
            NodeBudget(nid, cpus=cpus, memory_gb=mem)
            for nid, cpus, mem in remote_mgr.node_budgets()
        ]
        plan = plan_node_allocation(scale_states, nodes)
        self._pref_node = plan.preferred_node
        self.node_plan = {
            st.spec.name: dict(pn) for st, pn in zip(states, plan.per_node)
        }
        for st, counts in zip(states, plan.per_node):
            cur = st.pool.workers_by_node()
            for nid, want in counts.items():
                for _ in range(max(0, want - cur.get(nid, 0))):
                    st.pool.start_worker(node_id=nid)
            # scale down idle workers on nodes over their per-node target
            # (a node absent from the plan has target 0 there)
            for nid, have in cur.items():
                want = counts.get(nid, 0)
                if have <= want:
                    continue
                surplus = [
                    w
                    for w in st.pool.idle_workers()
                    if st.pool.worker_node(w) == nid
                ]
                for w in surplus[: have - want]:
                    st.pool.stop_worker(w)

    @staticmethod
    def _drain(mp_q, t_q) -> list:
        out = []
        for q_ in (mp_q, t_q):
            while True:
                try:
                    out.append(q_.get_nowait())
                except queue.Empty:
                    break
                except (OSError, EOFError, ValueError):
                    break  # queue torn down mid-drain (shutdown race)
        return out

    @staticmethod
    def _discover_tpus(cfg, stage_specs: list[StageSpec]) -> int:
        from cosmos_curate_tpu.engine.autoscaler import discover_tpu_chips

        return discover_tpu_chips(cfg, stage_specs)


def _retry_or_drop(
    stx, batch: _Batch, store, reason: str, *,
    dead_letter=None, node_death=False, lost_node="", lineage=None,
) -> None:
    """Infra-failure disposition shared by the localize, final-fetch and
    reaper paths: budget the failure against the batch's worker-death cap —
    or, with ``node_death=True``, the SEPARATE node-death cap, so one flaky
    node can't exhaust the poison-batch guard; requeue under budget, else
    drop LOUDLY — persisting the batch to the dead-letter queue first
    (``dead_letter`` is the runner's recorder; ``lost_node``/``lineage``
    stamp owner-loss drops so operators can tell 'node died past budget'
    from 'batch is poison'), then release the refs."""
    if node_death:
        batch.node_deaths += 1
        count, cap, kind = batch.node_deaths, MAX_NODE_DEATHS_PER_BATCH, "node deaths"
    else:
        batch.worker_deaths += 1
        count, cap, kind = batch.worker_deaths, MAX_WORKER_DEATHS_PER_BATCH, "infra failures"
    if count <= cap:
        logger.warning(
            "batch %d: %s; re-running (%d/%d %s)",
            batch.batch_id, reason, count, cap, kind,
        )
        stx.retry_queue.append(batch)
        return
    logger.error(
        "batch %d dropped after %d %s (%s): %d tasks lost",
        batch.batch_id, count, kind, reason, len(batch.refs),
    )
    stx.errored_batches += 1
    if dead_letter is not None:
        dead_letter(stx, batch, reason=reason, lost_node=lost_node, lineage=lineage)
    for r in batch.refs:
        store.release(r)


def _host_memory_bytes() -> int:
    try:
        import psutil

        return int(psutil.virtual_memory().total)
    except Exception:
        return 8 << 30


def _tail(s: str, n: int = 2000) -> str:
    return s if len(s) <= n else "…" + s[-n:]
