"""Node agent: joins a driver's engine plane and hosts remote stage workers.

Run on every non-driver node (the slurm template and Helm chart wire this
up automatically):

    python -m cosmos_curate_tpu.engine.remote_agent --driver HOST:PORT

The agent spawns the SAME worker processes the driver uses locally
(engine/worker.py ``worker_main`` — spawn, never fork; CPU-pinned JAX) and
relays their control/result queues over the authenticated socket. The
control link carries REFS only: input segments stream in from their owner
(the driver's store or a peer agent) over the object channel
(engine/object_channel.py), segments this node already owns are consumed
in place, and outputs stay here until the driver releases them — the
driver's NIC is not on the data path. Reference match: the per-node Ray
worker processes xenna schedules onto, with refs moving centrally and data
peer-to-peer (ARCHITECTURE.md:70-81).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import multiprocessing as mp
import os
import queue
import socket
import threading
import time

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.engine import object_channel, object_store
from cosmos_curate_tpu.engine.remote_plane import (
    DEFAULT_HEARTBEAT_S,
    HEARTBEAT_S_ENV,
    AgentReady,
    AgentResult,
    AgentStats,
    Bye,
    Hello,
    PrefetchObjects,
    ProtocolSkewError,
    ReleaseObjects,
    StartWorker,
    StopWorker,
    SubmitBatch,
    WorkerDied,
    _token,
    connect_channel,
)
from cosmos_curate_tpu.engine.worker import (
    ProcessMsg,
    ReadyMsg,
    ResultMsg,
    SetupMsg,
    ShutdownMsg,
    worker_main,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MP = mp.get_context("spawn")

# bounded concurrency for object-channel pulls (demand resolution AND
# push-ahead prefetch share the pool, so prefetch can never starve the
# demand path of sockets — it only uses slots the demand path left idle)
FETCH_CONCURRENCY_ENV = "CURATE_OBJECT_FETCH_CONCURRENCY"
# entries the push-ahead cache may hold before evicting oldest-first; each
# entry is a whole segment in /dev/shm, so the cap bounds prefetch memory
PREFETCH_CACHE_ENV = "CURATE_PREFETCH_CACHE_ENTRIES"


def _host_memory_gb() -> float:
    """This host's RAM in GiB for the Hello (0.0 = unknown; the planner
    then fits on CPUs alone)."""
    try:
        import psutil

        return psutil.virtual_memory().total / (1 << 30)
    except Exception:
        return 0.0


def _delete_segments_with_prefix(prefix: str) -> int:
    n = 0
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return 0
    for name in names:
        if name.startswith(prefix) and object_store.valid_segment_name(name):
            try:
                os.unlink(os.path.join("/dev/shm", name))
                n += 1
            except OSError:
                pass
    return n


class NodeAgent:
    def __init__(self, driver: str, *, node_id: str | None = None, num_cpus: float | None = None) -> None:
        host, _, port = driver.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.node_id = node_id or f"{socket.gethostname()}-{os.getpid()}"
        self.num_cpus = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
        self.token = _token()
        # guards `workers` and `inflight`: both are mutated from the recv
        # loop, the result-relay thread and the watchdog thread
        self._lock = threading.Lock()
        self.workers: dict[str, tuple[object, object]] = {}  # key -> (in_q, proc)
        # (worker_key, batch_id) -> input refs this agent FETCHED (local
        # copies of remote segments), deleted once the result is relayed (or
        # the worker dies) so /dev/shm never accumulates. Locally-owned
        # input refs (this node's earlier outputs) are NOT tracked here —
        # the driver releases those via ReleaseObjects.
        self.inflight: dict[tuple[str, int], list] = {}
        # (worker_key, batch_id) -> monotonic deadline (SubmitBatch.timeout_s
        # > 0): the watchdog kills workers whose batch outlives it — hang
        # detection for the driver's batch_timeout_s on REMOTE workers.
        # Guarded by self._lock like inflight.
        self.deadlines: dict[tuple[str, int], float] = {}
        self.results_q: mp.Queue = _MP.Queue()
        self._stop = threading.Event()
        # serves THIS node's segments to the driver and peer agents
        self.object_server = object_channel.ObjectServer(self.token)
        self.driver_object_addr: tuple[str, int] = ("", 0)
        self._last_run_id: bytes | None = None
        # this process and every worker it spawns attribute their dispatch/
        # flow/object-plane aggregates to this node
        os.environ["CURATE_NODE_ID"] = self.node_id
        # batch-level input resolution runs here, NOT on the recv loop: a
        # slow multi-segment fetch must not block StartWorker/StopWorker/
        # Release handling, and resolving batch N+1 while the worker chews
        # batch N is exactly the input-prefetch overlap the engine wants
        self._resolve_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="agent-resolve"
        )
        # segment-level pulls (demand + push-ahead prefetch), bounded
        n_fetch = int(os.environ.get(FETCH_CONCURRENCY_ENV, "4"))
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, n_fetch), thread_name_prefix="agent-fetch"
        )
        # push-ahead cache: shm_name -> local copy ref, insertion-ordered
        # for oldest-first eviction. The condition guards cache + in-flight
        # set (recv loop, resolve pool and fetch pool all touch them) and
        # lets a resolver WAIT on an in-flight prefetch instead of opening
        # a duplicate transfer for the same segment.
        self._cache_cv = threading.Condition()
        self._prefetched: dict[str, object_store.ObjectRef] = {}
        self._prefetching: set[str] = set()
        self._prefetch_cap = max(1, int(os.environ.get(PREFETCH_CACHE_ENV, "64")))
        # last AgentStats snapshot (totals), so each frame ships deltas;
        # relay + watchdog threads both flush, hence the dedicated lock
        self._op_lock = threading.Lock()
        self._op_prev: dict | None = None
        self._last_op_flush = 0.0
        # heartbeat cadence: the watchdog ships an AgentStats frame — empty
        # deltas included — at least this often, so the driver's failure
        # detector (remote_plane.check_heartbeats) can declare a silent
        # agent dead deterministically. Must match the driver's knob.
        self._heartbeat_s = float(
            os.environ.get(HEARTBEAT_S_ENV, str(DEFAULT_HEARTBEAT_S))
        )

    def run(self, *, connect_timeout_s: float = 60.0, reconnect: bool = True) -> int:
        """Serve the driver until it says Bye.

        A lost link (driver restart, transient network) does NOT end the
        agent: workers are torn down and the agent dials again — a
        rejoining agent is just a fresh Hello to the driver, which re-places
        workers on it at the next autoscale tick. Exit paths: an explicit
        Bye, or the driver staying unreachable past the reconnect window
        (a Bye lost to a RST must not pin the slurm allocation forever) —
        both exit 0."""
        reconnect_s = float(os.environ.get("CURATE_AGENT_RECONNECT_S", "300"))
        while True:
            try:
                said_bye = self._serve_once(connect_timeout_s=connect_timeout_s)
            except OSError as e:
                logger.info("driver unreachable (%s); agent exiting", e)
                return 0
            if said_bye or not reconnect:
                return 0
            logger.info("driver link lost; reconnecting")
            connect_timeout_s = reconnect_s

    def _serve_once(self, *, connect_timeout_s: float) -> bool:
        """One connect→serve cycle; True when the driver sent Bye."""
        object_store.cleanup_stale_segments()
        # the previous cycle's in-flight inputs are dead weight now (their
        # workers were terminated): unlink the shm segments — this agent
        # process stays alive, so the stale-segment janitor never would
        for key, batch_id in list(self.inflight):
            self._release_inflight(key, batch_id)
        # the previous session's push-ahead copies are unreferenced too
        self._clear_prefetch_cache()
        # stale worker results must not leak into the NEW session (the
        # driver would see results for workers it never started)
        try:
            while True:
                self.results_q.get_nowait()
        except queue.Empty:
            pass
        # each cycle gets its OWN stop event: a relay thread stuck in a
        # stalled send can never be revived by a later cycle's clear()
        self._stop = threading.Event()
        with self._lock:
            self.workers.clear()
            self.inflight.clear()
            self.deadlines.clear()
        deadline = time.monotonic() + connect_timeout_s
        while True:  # the driver may come up after the agents (srun races)
            try:
                sock = socket.create_connection(self.addr, timeout=10)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
                continue
            # the 10s DIAL timeout must not become a RECV deadline: an agent
            # the driver leaves idle (no StartWorker yet, quiet pipeline)
            # would time out mid-session and reconnect-churn every 10
            # seconds. Frames block indefinitely; driver death surfaces as
            # EOF/RST, and the driver's own failure detector covers the
            # reverse direction.
            sock.settimeout(None)
            # mutual-nonce handshake: both sides contribute fresh randomness
            # to the session id, so no recorded session replays (either
            # direction) into this one (see SecureChannel/connect_channel).
            # The handshake retries inside the dial loop: a DYING driver can
            # accept the dial and drop it before acking (its accept loop
            # races shutdown), which must read as "driver not up yet", not
            # "driver unreachable, exit" — the successor driver is seconds
            # away. Version skew is the exception: a skewed driver answers
            # the same way every time, so fail fast with its clear error.
            try:
                self.chan, ack = connect_channel(
                    sock, self.token,
                    Hello(
                        self.node_id, self.num_cpus,
                        object_port=self.object_server.port,
                        memory_gb=_host_memory_gb(),
                        # pid lets the driver tell a same-process reconnect
                        # (segments survived) from a bounced agent (they
                        # did not)
                        pid=os.getpid(),
                    ),
                )
            except ProtocolSkewError:
                sock.close()
                raise
            except (ConnectionError, OSError):
                sock.close()
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
                continue
            self.sock = sock
            break
        self.driver_object_addr = (self.addr[0], ack.driver_object_port)
        # output segments from a PREVIOUS run are unreferenced dead weight;
        # a transient link blip within the SAME run must keep them — the
        # driver still references them as downstream inputs (run_id tells
        # the two apart)
        if self._last_run_id is not None and ack.run_id != self._last_run_id:
            n = _delete_segments_with_prefix(f"cur{os.getpid()}-")
            if n:
                logger.info("dropped %d output segments from the previous run", n)
        self._last_run_id = ack.run_id
        logger.info(
            "agent %s joined driver %s:%d (%.0f cpus)",
            self.node_id, self.addr[0], self.addr[1], self.num_cpus,
        )
        stop = self._stop
        relay = threading.Thread(target=self._relay_results, args=(stop,), daemon=True)
        relay.start()
        watchdog = threading.Thread(target=self._watchdog, args=(stop,), daemon=True)
        watchdog.start()
        said_bye = False
        try:
            while True:
                # chaos network partition (kind=hang): inbound frames stall
                # here, outbound ones in _send — heartbeats miss, and the
                # driver's failure detector declares this node dead. A
                # single falsy check while disarmed. (agent.kill fires in
                # _relay_results, right after a result lands at the driver —
                # the instant a death actually orphans referenced outputs.)
                chaos.fire(chaos.SITE_AGENT_PARTITION)
                msg = self.chan.recv()
                if isinstance(msg, Bye):
                    said_bye = True
                    break
                try:
                    self._handle(msg)
                except Exception:
                    # one poisoned batch/worker must not sever the link
                    logger.exception("agent failed handling %s", type(msg).__name__)
                    if isinstance(msg, SubmitBatch):
                        import traceback

                        self._send(
                            AgentResult(
                                msg.worker_key, msg.batch_id, error=traceback.format_exc()
                            )
                        )
        except (ConnectionError, OSError) as e:
            logger.warning("driver link lost: %s", e)
        finally:
            self._stop.set()
            # best-effort final flush: a short run can finish inside one
            # watchdog cadence, and its transfers still belong in the
            # driver's per-node accounting
            if said_bye:
                self._flush_op_stats(force=True)
            for key, (in_q, _proc) in list(self.workers.items()):
                try:
                    in_q.put(ShutdownMsg())
                except (OSError, ValueError):  # queue already closed/broken
                    logger.debug("shutdown enqueue failed for %s", key, exc_info=True)
            time.sleep(0.2)
            for key, (_in_q, proc) in list(self.workers.items()):
                if proc.is_alive():
                    proc.terminate()
            try:
                sock.close()
            except OSError:
                pass
        return said_bye

    def _send(self, msg) -> None:
        # kind=hang here stalls outbound frames (results, heartbeats) —
        # one half of the agent.partition site; no-op while disarmed
        chaos.fire(chaos.SITE_AGENT_PARTITION)
        # SecureChannel serializes sends internally (per-frame sequence)
        self.chan.send(msg)

    def _handle(self, msg) -> None:
        if isinstance(msg, StartWorker):
            with self._lock:
                stale = self.workers.pop(msg.worker_key, None)
            if stale is not None:
                # a driver retry re-sent StartWorker while the first process
                # was still setting up: terminate it, or its results would
                # keep relaying under the same key (and the process leak)
                logger.warning(
                    "duplicate StartWorker for %s; terminating the old process",
                    msg.worker_key,
                )
                try:
                    stale[1].terminate()
                except Exception:
                    pass
                # the watchdog only scans self.workers, so the popped
                # process's in-flight input segments must be freed here
                for wkey, batch_id in list(self.inflight):
                    if wkey == msg.worker_key:
                        self._release_inflight(wkey, batch_id)
            in_q = _MP.Queue()
            env = dict(msg.env)
            env["CURATE_WORKER_ID"] = msg.worker_key
            env["CURATE_STORE_OWNER"] = str(os.getpid())  # agent owns segments
            # dispatch/flow dumps from this worker attribute to THIS node
            env["CURATE_NODE_ID"] = self.node_id
            proc = _MP.Process(
                target=worker_main,
                args=(in_q, self.results_q, env),
                daemon=True,
                name=msg.worker_key,
            )
            proc.start()
            in_q.put(SetupMsg(msg.stage_pickle, msg.meta_pickle))
            with self._lock:
                self.workers[msg.worker_key] = (in_q, proc)
        elif isinstance(msg, SubmitBatch):
            with self._lock:
                entry = self.workers.get(msg.worker_key)
            if entry is None:
                self._send(
                    AgentResult(
                        msg.worker_key, msg.batch_id, error="unknown worker on agent"
                    )
                )
                return
            # input resolution runs on the bounded resolve pool, never this
            # recv loop: while the worker processes batch N, batch N+1's
            # refs stream in concurrently (the cross-host analogue of the
            # worker's own fetch/process overlap)
            self._resolve_pool.submit(self._resolve_and_dispatch, msg, entry)
        elif isinstance(msg, PrefetchObjects):
            for spec in msg.refs:
                self._start_prefetch(spec)
        elif isinstance(msg, ReleaseObjects):
            for name in msg.names:
                object_store.delete(object_store.ObjectRef(name, 0, 0))
            # released segments can never be named by a future batch: any
            # push-ahead copies of them are dead weight in the cache
            self._clear_prefetch_cache(msg.names)
        elif isinstance(msg, StopWorker):
            with self._lock:
                entry = self.workers.pop(msg.worker_key, None)
            if entry is not None:
                try:
                    entry[0].put(ShutdownMsg())
                except Exception:
                    entry[1].terminate()

    def _resolve_and_dispatch(self, msg: SubmitBatch, entry) -> None:
        """Resolve-pool job: pull the batch's inputs (bounded concurrency,
        prefetch-cache hits first), then hand the batch to its worker.
        Failures report as AgentResult errors — exactly what the inline
        path used to raise into _serve_once's handler."""
        from cosmos_curate_tpu.observability.tracing import traced_span

        try:
            # the agent's own hop in the trace: input resolution (peer/
            # driver fetches over the object channel) parents onto the
            # driver's stage span via the frame's traceparent. No-op
            # unless the agent runs with CURATE_TRACING=1.
            with traced_span(
                "agent.resolve_inputs",
                traceparent=getattr(msg, "traceparent", "") or None,
                worker=msg.worker_key,
                batch_id=msg.batch_id,
                node=self.node_id,
            ):
                refs, fetched = self._resolve_specs(msg.refs)
        except Exception as e:
            import traceback

            # classify: a fetch that died on the object channel (owner
            # unreachable or hung, segment gone with its node) is an INPUT
            # LOSS — the driver reconstructs via lineage instead of burning
            # the batch's user-code retry budget on a vanished ref. NOT a
            # blanket OSError: a local disk-full/fd-exhaustion writing the
            # fetched segment is this node's problem, not an owner loss.
            input_loss = isinstance(
                e, (ConnectionError, FileNotFoundError, TimeoutError)
            )
            try:
                self._send(
                    AgentResult(
                        msg.worker_key, msg.batch_id,
                        error=traceback.format_exc(),
                        input_loss=input_loss,
                    )
                )
            except OSError:
                logger.debug("result send failed after resolve error", exc_info=True)
            return
        # the fetch above can take seconds: the worker may have died and
        # been reaped by the watchdog meanwhile. Re-check under the same
        # lock hold as the inflight insert — inserting for a reaped key
        # would leak the fetched segments forever (the watchdog already
        # scanned inflight and will never revisit this key).
        with self._lock:
            alive = msg.worker_key in self.workers
            if alive:
                self.inflight[(msg.worker_key, msg.batch_id)] = fetched
                if getattr(msg, "timeout_s", 0.0) > 0:
                    # the deadline starts AFTER the input fetch (which
                    # can take seconds and is not the worker's fault)
                    self.deadlines[(msg.worker_key, msg.batch_id)] = (
                        time.monotonic() + msg.timeout_s
                    )
        if not alive:
            # WorkerDied was already reported; the driver requeues the
            # batch — just free this attempt's local copies
            for r in fetched:
                try:
                    object_store.delete(r)
                except OSError:
                    logger.debug("stale-copy delete failed", exc_info=True)
            return
        entry[0].put(
            ProcessMsg(
                batch_id=msg.batch_id,
                refs=refs,
                traceparent=getattr(msg, "traceparent", ""),
            )
        )

    def _fetch_one(self, s) -> object_store.ObjectRef:
        """One demand pull over the object channel, with wait accounting
        (the consumer is blocked for exactly this long)."""
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        if s.owner_node == "":  # driver-owned: dial the control host
            addr = self.driver_object_addr
        else:
            addr = (s.owner_host, s.owner_port)
        local = object_store.ObjectRef(s.shm_name, s.total_size, s.num_buffers)
        t0 = time.monotonic()
        copy = object_channel.fetch_object(addr, self.token, local)
        record_object_plane(
            fetches=1, fetch_bytes=s.total_size,
            fetch_wait_s=time.monotonic() - t0,
        )
        return copy

    def _resolve_specs(self, specs) -> tuple[list, list]:
        """RefSpecs -> local ObjectRefs. Segments this node already owns
        are used in place (node affinity: zero bytes moved); push-ahead
        cache hits are consumed with ~zero wait; everything else streams
        from its owner — the driver's store or a PEER agent — through the
        bounded fetch pool, never through the driver's control socket and
        never ref-by-ref sequentially. Returns (refs_for_worker,
        fetched_local_copies)."""
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        refs: list = [None] * len(specs)
        fetched: list = []
        futures: list[tuple[int, concurrent.futures.Future]] = []
        deferred: list[tuple[int, object]] = []
        for i, s in enumerate(specs):
            local = object_store.ObjectRef(s.shm_name, s.total_size, s.num_buffers)
            if s.owner_node == self.node_id and os.path.exists(
                object_store.segment_path(s.shm_name)
            ):
                refs[i] = local  # ours already; driver releases it later
                continue
            with self._cache_cv:
                pending = (
                    s.shm_name in self._prefetched or s.shm_name in self._prefetching
                )
            if pending:
                # cached or streaming in: settle AFTER the demand futures
                # are submitted, so waiting on one never delays the others
                deferred.append((i, s))
            else:
                record_object_plane(prefetch_misses=1)
                # copy_context: the fetch spans must parent onto the
                # ambient agent.resolve_inputs span, not fragment the trace
                # from a bare pool thread
                import contextvars

                futures.append(
                    (
                        i,
                        self._fetch_pool.submit(
                            contextvars.copy_context().run, self._fetch_one, s
                        ),
                    )
                )
        err: BaseException | None = None
        for i, s in deferred:
            t0 = time.monotonic()
            hit = self._take_prefetched(s.shm_name)
            if hit is not None:
                record_object_plane(
                    prefetch_hits=1, prefetch_hit_wait_s=time.monotonic() - t0
                )
                refs[i] = hit
                fetched.append(hit)
                continue
            # the in-flight prefetch failed (owner died, segment released):
            # fall back to a demand pull, which reports the real error
            record_object_plane(prefetch_misses=1)
            try:
                copy = self._fetch_one(s)
            except BaseException as e:
                err = err or e
                continue
            refs[i] = copy
            fetched.append(copy)
        for i, fut in futures:
            try:
                copy = fut.result()
            except BaseException as e:  # keep draining: every future must settle
                err = err or e
                continue
            refs[i] = copy
            fetched.append(copy)
        if err is not None:
            # partial failure must not orphan the copies already written
            # (retries would leak a fresh set each attempt)
            for r in fetched:
                try:
                    object_store.delete(r)
                except OSError:
                    logger.debug("cleanup delete failed for %s", r.shm_name, exc_info=True)
            raise err
        return refs, fetched

    # -- push-ahead prefetch -------------------------------------------
    def _take_prefetched(
        self, name: str, wait_s: float = 30.0
    ) -> object_store.ObjectRef | None:
        """Consume a cached push-ahead copy. When the transfer is still IN
        FLIGHT, wait for it rather than racing a duplicate demand fetch —
        the residual wait is strictly shorter than a fresh transfer, and
        the caller's hit-wait accounting captures exactly that residue."""
        deadline = time.monotonic() + wait_s
        with self._cache_cv:
            while True:
                if name in self._prefetched:
                    return self._prefetched.pop(name)
                if name not in self._prefetching:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cache_cv.wait(remaining)

    def _start_prefetch(self, spec) -> None:
        """Begin pulling one pushed-ahead segment into the cache unless it
        is already local, cached, or in flight."""
        if spec.owner_node == self.node_id:
            return
        with self._cache_cv:
            if spec.shm_name in self._prefetched or spec.shm_name in self._prefetching:
                return
            self._prefetching.add(spec.shm_name)
        self._fetch_pool.submit(self._prefetch_one, spec)

    def _prefetch_one(self, spec) -> None:
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane
        from cosmos_curate_tpu.observability.tracing import suppress_tracing

        evicted: list = []
        try:
            addr = (
                self.driver_object_addr
                if spec.owner_node == ""
                else (spec.owner_host, spec.owner_port)
            )
            ref = object_store.ObjectRef(
                spec.shm_name, spec.total_size, spec.num_buffers
            )
            t0 = time.monotonic()
            # background traffic with no batch to parent onto: an untraced
            # pull keeps the run's trace connected (the object-plane
            # counters carry the prefetch signal)
            with suppress_tracing():
                copy = object_channel.fetch_object(addr, self.token, ref)
            record_object_plane(
                prefetches=1, prefetch_bytes=spec.total_size,
                prefetch_transfer_s=time.monotonic() - t0,
            )
            with self._cache_cv:
                self._prefetched[spec.shm_name] = copy
                while len(self._prefetched) > self._prefetch_cap:
                    evicted.append(self._prefetched.pop(next(iter(self._prefetched))))
        except (ConnectionError, OSError, FileNotFoundError) as e:
            # advisory: the demand pull will retry from the owner; a
            # released-before-prefetch segment is a normal race
            logger.debug("prefetch of %s failed: %s", spec.shm_name, e)
        finally:
            with self._cache_cv:
                self._prefetching.discard(spec.shm_name)
                self._cache_cv.notify_all()
        for r in evicted:
            try:
                object_store.delete(r)
            except OSError:
                logger.debug("evicted-prefetch delete failed", exc_info=True)

    def _clear_prefetch_cache(self, names=None) -> None:
        """Drop cached push-ahead copies (all of them, or just ``names`` —
        e.g. segments the driver released, which no future batch can
        name)."""
        with self._cache_cv:
            if names is None:
                dead, self._prefetched = list(self._prefetched.values()), {}
            else:
                dead = [
                    self._prefetched.pop(n)
                    for n in names
                    if n in self._prefetched
                ]
        for r in dead:
            try:
                object_store.delete(r)
            except OSError:
                logger.debug("prefetch-cache delete failed", exc_info=True)

    def _release_inflight(self, worker_key: str, batch_id: int) -> None:
        with self._lock:
            refs = self.inflight.pop((worker_key, batch_id), [])
            self.deadlines.pop((worker_key, batch_id), None)
        for r in refs:
            try:
                object_store.delete(r)
            except OSError:  # segment already unlinked: nothing to release
                pass

    def _flush_op_stats(
        self, *, min_interval_s: float = 1.0, force: bool = False,
        heartbeat: bool = False,
    ) -> None:
        """Ship object-plane DELTAS to the driver, throttled (relay thread
        after results, watchdog on cadence, teardown forced).

        ``heartbeat=True`` (the watchdog's cadence call) sends the frame
        even when the delta is empty: the driver's failure detector keys
        agent liveness on frame arrival, and an idle-but-healthy agent must
        not read as a dead one."""
        from cosmos_curate_tpu.observability.stage_timer import (
            object_plane_snapshot_delta,
        )

        with self._op_lock:
            now = time.monotonic()
            if not force and now - self._last_op_flush < min_interval_s:
                return
            self._last_op_flush = now
            self._op_prev, delta = object_plane_snapshot_delta(self._op_prev)
        if delta or heartbeat:
            try:
                self._send(AgentStats(object_plane=delta))
            except OSError:
                logger.debug("stats flush failed (link down?)", exc_info=True)

    def _relay_results(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                msg = self.results_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if isinstance(msg, ReadyMsg):
                    self._send(AgentReady(msg.worker_id, error=msg.error))
                elif isinstance(msg, ResultMsg):
                    self._release_inflight(msg.worker_id, msg.batch_id)
                    if msg.error is not None:
                        self._send(
                            AgentResult(
                                msg.worker_id,
                                msg.batch_id,
                                error=msg.error,
                                process_time_s=msg.process_time_s,
                            )
                        )
                        continue
                    # outputs STAY in this node's store; only descriptors
                    # ride the control link. Consumers pull the bytes from
                    # our ObjectServer; the driver sends ReleaseObjects when
                    # the last consumer is done.
                    self._send(
                        AgentResult(
                            msg.worker_id,
                            msg.batch_id,
                            out_refs=[
                                (r.shm_name, r.total_size, r.num_buffers)
                                for r in msg.out_refs
                            ],
                            process_time_s=msg.process_time_s,
                            deserialize_time_s=msg.deserialize_time_s,
                        )
                    )
                    # piggyback transfer stats on result traffic so even a
                    # run shorter than the watchdog cadence reports
                    self._flush_op_stats()
                    # chaos: the most hostile node-death instant — the
                    # result (and its output descriptors) just reached the
                    # driver, so downstream batches WILL reference segments
                    # that die with this process. kind=crash (os._exit).
                    chaos.fire(chaos.SITE_AGENT_KILL)
            except OSError:
                return

    def _watchdog(self, stop: threading.Event) -> None:
        """Detect remote worker PROCESS deaths (the driver can only see the
        link): report WorkerDied so the driver's reap requeues the batch,
        and free the dead worker's in-flight input segments. Also enforces
        per-batch deadlines (SubmitBatch.timeout_s): a worker whose batch
        outlives its deadline is presumed hung, killed, and reported
        through the same WorkerDied path as a real death."""
        tick = min(1.0, self._heartbeat_s / 2) if self._heartbeat_s > 0 else 1.0
        while not stop.is_set():
            time.sleep(tick)
            now = time.monotonic()
            # relay object-plane deltas so the driver's per-node counters
            # and run report cover this node's transfers even while no
            # results flow — AND serve as the liveness heartbeat the
            # driver's failure detector deadlines against (empty deltas
            # still send a frame)
            self._flush_op_stats(
                min_interval_s=max(0.2, self._heartbeat_s), heartbeat=True
            )
            with self._lock:
                expired = [k for k, d in self.deadlines.items() if now >= d]
            for key, batch_id in expired:
                with self._lock:
                    entry = self.workers.pop(key, None)
                    self.deadlines.pop((key, batch_id), None)
                if entry is None:
                    continue  # already reaped as a death
                logger.warning(
                    "worker %s batch %d exceeded its deadline on agent; "
                    "killing hung worker", key, batch_id,
                )
                try:
                    entry[1].kill()  # SIGKILL: hung code may ignore SIGTERM
                    entry[1].join(timeout=2.0)
                except (OSError, AttributeError):
                    logger.debug("kill failed for %s", key, exc_info=True)
                for wkey, b_id in list(self.inflight):
                    if wkey == key:
                        self._release_inflight(wkey, b_id)
                try:
                    self._send(WorkerDied(key))
                except OSError:
                    return
            for key, (_in_q, proc) in list(self.workers.items()):
                if proc.is_alive():
                    continue
                with self._lock:
                    self.workers.pop(key, None)
                logger.warning("worker %s died on agent (exit %s)", key, proc.exitcode)
                for wkey, batch_id in list(self.inflight):
                    if wkey == key:
                        self._release_inflight(wkey, batch_id)
                try:
                    self._send(WorkerDied(key))
                except OSError:
                    return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="cosmos-curate-tpu engine node agent")
    ap.add_argument("--driver", required=True, help="driver HOST:PORT")
    ap.add_argument("--node-id", default=None)
    ap.add_argument("--num-cpus", type=float, default=None)
    args = ap.parse_args(argv)
    from cosmos_curate_tpu import chaos
    from cosmos_curate_tpu.observability.tracing import setup_tracing_from_env

    chaos.install_from_env()  # soak rigs arm agent-side faults via env
    setup_tracing_from_env()  # CURATE_TRACING=1 joins the agent to the trace
    return NodeAgent(args.driver, node_id=args.node_id, num_cpus=args.num_cpus).run()


if __name__ == "__main__":
    raise SystemExit(main())
