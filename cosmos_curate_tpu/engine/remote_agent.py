"""Node agent: joins a driver's engine plane and hosts remote stage workers.

Run on every non-driver node (the slurm template and Helm chart wire this
up automatically):

    python -m cosmos_curate_tpu.engine.remote_agent --driver HOST:PORT

The agent spawns the SAME worker processes the driver uses locally
(engine/worker.py ``worker_main`` — spawn, never fork; CPU-pinned JAX) and
relays their control/result queues over the authenticated socket. The
control link carries REFS only: input segments stream in from their owner
(the driver's store or a peer agent) over the object channel
(engine/object_channel.py), segments this node already owns are consumed
in place, and outputs stay here until the driver releases them — the
driver's NIC is not on the data path. Reference match: the per-node Ray
worker processes xenna schedules onto, with refs moving centrally and data
peer-to-peer (ARCHITECTURE.md:70-81).
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import queue
import socket
import threading
import time

from cosmos_curate_tpu.engine import object_channel, object_store
from cosmos_curate_tpu.engine.remote_plane import (
    AgentReady,
    AgentResult,
    Bye,
    Hello,
    ReleaseObjects,
    StartWorker,
    StopWorker,
    SubmitBatch,
    WorkerDied,
    _token,
    connect_channel,
)
from cosmos_curate_tpu.engine.worker import (
    ProcessMsg,
    ReadyMsg,
    ResultMsg,
    SetupMsg,
    ShutdownMsg,
    worker_main,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MP = mp.get_context("spawn")


def _delete_segments_with_prefix(prefix: str) -> int:
    n = 0
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return 0
    for name in names:
        if name.startswith(prefix) and object_store.valid_segment_name(name):
            try:
                os.unlink(os.path.join("/dev/shm", name))
                n += 1
            except OSError:
                pass
    return n


class NodeAgent:
    def __init__(self, driver: str, *, node_id: str | None = None, num_cpus: float | None = None) -> None:
        host, _, port = driver.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.node_id = node_id or f"{socket.gethostname()}-{os.getpid()}"
        self.num_cpus = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
        self.token = _token()
        # guards `workers` and `inflight`: both are mutated from the recv
        # loop, the result-relay thread and the watchdog thread
        self._lock = threading.Lock()
        self.workers: dict[str, tuple[object, object]] = {}  # key -> (in_q, proc)
        # (worker_key, batch_id) -> input refs this agent FETCHED (local
        # copies of remote segments), deleted once the result is relayed (or
        # the worker dies) so /dev/shm never accumulates. Locally-owned
        # input refs (this node's earlier outputs) are NOT tracked here —
        # the driver releases those via ReleaseObjects.
        self.inflight: dict[tuple[str, int], list] = {}
        # (worker_key, batch_id) -> monotonic deadline (SubmitBatch.timeout_s
        # > 0): the watchdog kills workers whose batch outlives it — hang
        # detection for the driver's batch_timeout_s on REMOTE workers.
        # Guarded by self._lock like inflight.
        self.deadlines: dict[tuple[str, int], float] = {}
        self.results_q: mp.Queue = _MP.Queue()
        self._stop = threading.Event()
        # serves THIS node's segments to the driver and peer agents
        self.object_server = object_channel.ObjectServer(self.token)
        self.driver_object_addr: tuple[str, int] = ("", 0)
        self._last_run_id: bytes | None = None

    def run(self, *, connect_timeout_s: float = 60.0, reconnect: bool = True) -> int:
        """Serve the driver until it says Bye.

        A lost link (driver restart, transient network) does NOT end the
        agent: workers are torn down and the agent dials again — a
        rejoining agent is just a fresh Hello to the driver, which re-places
        workers on it at the next autoscale tick. Exit paths: an explicit
        Bye, or the driver staying unreachable past the reconnect window
        (a Bye lost to a RST must not pin the slurm allocation forever) —
        both exit 0."""
        reconnect_s = float(os.environ.get("CURATE_AGENT_RECONNECT_S", "300"))
        while True:
            try:
                said_bye = self._serve_once(connect_timeout_s=connect_timeout_s)
            except OSError as e:
                logger.info("driver unreachable (%s); agent exiting", e)
                return 0
            if said_bye or not reconnect:
                return 0
            logger.info("driver link lost; reconnecting")
            connect_timeout_s = reconnect_s

    def _serve_once(self, *, connect_timeout_s: float) -> bool:
        """One connect→serve cycle; True when the driver sent Bye."""
        object_store.cleanup_stale_segments()
        # the previous cycle's in-flight inputs are dead weight now (their
        # workers were terminated): unlink the shm segments — this agent
        # process stays alive, so the stale-segment janitor never would
        for key, batch_id in list(self.inflight):
            self._release_inflight(key, batch_id)
        # stale worker results must not leak into the NEW session (the
        # driver would see results for workers it never started)
        try:
            while True:
                self.results_q.get_nowait()
        except queue.Empty:
            pass
        # each cycle gets its OWN stop event: a relay thread stuck in a
        # stalled send can never be revived by a later cycle's clear()
        self._stop = threading.Event()
        with self._lock:
            self.workers.clear()
            self.inflight.clear()
            self.deadlines.clear()
        deadline = time.monotonic() + connect_timeout_s
        while True:  # the driver may come up after the agents (srun races)
            try:
                sock = socket.create_connection(self.addr, timeout=10)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self.sock = sock
        # mutual-nonce handshake: both sides contribute fresh randomness
        # to the session id, so no recorded session replays (either
        # direction) into this one (see SecureChannel/connect_channel)
        self.chan, ack = connect_channel(
            sock, self.token,
            Hello(self.node_id, self.num_cpus, object_port=self.object_server.port),
        )
        self.driver_object_addr = (self.addr[0], ack.driver_object_port)
        # output segments from a PREVIOUS run are unreferenced dead weight;
        # a transient link blip within the SAME run must keep them — the
        # driver still references them as downstream inputs (run_id tells
        # the two apart)
        if self._last_run_id is not None and ack.run_id != self._last_run_id:
            n = _delete_segments_with_prefix(f"cur{os.getpid()}-")
            if n:
                logger.info("dropped %d output segments from the previous run", n)
        self._last_run_id = ack.run_id
        logger.info(
            "agent %s joined driver %s:%d (%.0f cpus)",
            self.node_id, self.addr[0], self.addr[1], self.num_cpus,
        )
        stop = self._stop
        relay = threading.Thread(target=self._relay_results, args=(stop,), daemon=True)
        relay.start()
        watchdog = threading.Thread(target=self._watchdog, args=(stop,), daemon=True)
        watchdog.start()
        said_bye = False
        try:
            while True:
                msg = self.chan.recv()
                if isinstance(msg, Bye):
                    said_bye = True
                    break
                try:
                    self._handle(msg)
                except Exception:
                    # one poisoned batch/worker must not sever the link
                    logger.exception("agent failed handling %s", type(msg).__name__)
                    if isinstance(msg, SubmitBatch):
                        import traceback

                        self._send(
                            AgentResult(
                                msg.worker_key, msg.batch_id, error=traceback.format_exc()
                            )
                        )
        except (ConnectionError, OSError) as e:
            logger.warning("driver link lost: %s", e)
        finally:
            self._stop.set()
            for key, (in_q, _proc) in list(self.workers.items()):
                try:
                    in_q.put(ShutdownMsg())
                except (OSError, ValueError):  # queue already closed/broken
                    logger.debug("shutdown enqueue failed for %s", key, exc_info=True)
            time.sleep(0.2)
            for key, (_in_q, proc) in list(self.workers.items()):
                if proc.is_alive():
                    proc.terminate()
            try:
                sock.close()
            except OSError:
                pass
        return said_bye

    def _send(self, msg) -> None:
        # SecureChannel serializes sends internally (per-frame sequence)
        self.chan.send(msg)

    def _handle(self, msg) -> None:
        if isinstance(msg, StartWorker):
            with self._lock:
                stale = self.workers.pop(msg.worker_key, None)
            if stale is not None:
                # a driver retry re-sent StartWorker while the first process
                # was still setting up: terminate it, or its results would
                # keep relaying under the same key (and the process leak)
                logger.warning(
                    "duplicate StartWorker for %s; terminating the old process",
                    msg.worker_key,
                )
                try:
                    stale[1].terminate()
                except Exception:
                    pass
                # the watchdog only scans self.workers, so the popped
                # process's in-flight input segments must be freed here
                for wkey, batch_id in list(self.inflight):
                    if wkey == msg.worker_key:
                        self._release_inflight(wkey, batch_id)
            in_q = _MP.Queue()
            env = dict(msg.env)
            env["CURATE_WORKER_ID"] = msg.worker_key
            env["CURATE_STORE_OWNER"] = str(os.getpid())  # agent owns segments
            proc = _MP.Process(
                target=worker_main,
                args=(in_q, self.results_q, env),
                daemon=True,
                name=msg.worker_key,
            )
            proc.start()
            in_q.put(SetupMsg(msg.stage_pickle, msg.meta_pickle))
            with self._lock:
                self.workers[msg.worker_key] = (in_q, proc)
        elif isinstance(msg, SubmitBatch):
            with self._lock:
                entry = self.workers.get(msg.worker_key)
            if entry is None:
                self._send(
                    AgentResult(
                        msg.worker_key, msg.batch_id, error="unknown worker on agent"
                    )
                )
                return
            # the agent's own hop in the trace: input resolution (peer/
            # driver fetches over the object channel) parents onto the
            # driver's stage span via the frame's traceparent. No-op
            # unless the agent runs with CURATE_TRACING=1.
            from cosmos_curate_tpu.observability.tracing import traced_span

            with traced_span(
                "agent.resolve_inputs",
                traceparent=getattr(msg, "traceparent", "") or None,
                worker=msg.worker_key,
                batch_id=msg.batch_id,
                node=self.node_id,
            ):
                refs, fetched = self._resolve_specs(msg.refs)
            # the fetch above can take seconds: the worker may have died and
            # been reaped by the watchdog meanwhile. Re-check under the same
            # lock hold as the inflight insert — inserting for a reaped key
            # would leak the fetched segments forever (the watchdog already
            # scanned inflight and will never revisit this key).
            with self._lock:
                alive = msg.worker_key in self.workers
                if alive:
                    self.inflight[(msg.worker_key, msg.batch_id)] = fetched
                    if getattr(msg, "timeout_s", 0.0) > 0:
                        # the deadline starts AFTER the input fetch (which
                        # can take seconds and is not the worker's fault)
                        self.deadlines[(msg.worker_key, msg.batch_id)] = (
                            time.monotonic() + msg.timeout_s
                        )
            if not alive:
                # WorkerDied was already reported; the driver requeues the
                # batch — just free this attempt's local copies
                for r in fetched:
                    try:
                        object_store.delete(r)
                    except OSError:
                        pass
                return
            entry[0].put(
                ProcessMsg(
                    batch_id=msg.batch_id,
                    refs=refs,
                    traceparent=getattr(msg, "traceparent", ""),
                )
            )
        elif isinstance(msg, ReleaseObjects):
            for name in msg.names:
                object_store.delete(object_store.ObjectRef(name, 0, 0))
        elif isinstance(msg, StopWorker):
            with self._lock:
                entry = self.workers.pop(msg.worker_key, None)
            if entry is not None:
                try:
                    entry[0].put(ShutdownMsg())
                except Exception:
                    entry[1].terminate()

    def _resolve_specs(self, specs) -> tuple[list, list]:
        """RefSpecs -> local ObjectRefs. Segments this node already owns
        are used in place (node affinity: zero bytes moved); everything
        else streams from its owner — the driver's store or a PEER agent —
        over the object channel, never through the driver's control socket.
        Returns (refs_for_worker, fetched_local_copies)."""
        refs: list = []
        fetched: list = []
        try:
            for s in specs:
                local = object_store.ObjectRef(s.shm_name, s.total_size, s.num_buffers)
                if s.owner_node == self.node_id and os.path.exists(
                    object_store.segment_path(s.shm_name)
                ):
                    refs.append(local)  # ours already; driver releases it later
                    continue
                if s.owner_node == "":  # driver-owned: dial the control host
                    addr = self.driver_object_addr
                else:
                    addr = (s.owner_host, s.owner_port)
                copy = object_channel.fetch_object(addr, self.token, local)
                refs.append(copy)
                fetched.append(copy)
        except BaseException:
            # partial failure must not orphan the copies already written
            # (retries would leak a fresh set each attempt)
            for r in fetched:
                try:
                    object_store.delete(r)
                except OSError:
                    logger.debug("cleanup delete failed for %s", r.shm_name, exc_info=True)
            raise
        return refs, fetched

    def _release_inflight(self, worker_key: str, batch_id: int) -> None:
        with self._lock:
            refs = self.inflight.pop((worker_key, batch_id), [])
            self.deadlines.pop((worker_key, batch_id), None)
        for r in refs:
            try:
                object_store.delete(r)
            except OSError:  # segment already unlinked: nothing to release
                pass

    def _relay_results(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                msg = self.results_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if isinstance(msg, ReadyMsg):
                    self._send(AgentReady(msg.worker_id, error=msg.error))
                elif isinstance(msg, ResultMsg):
                    self._release_inflight(msg.worker_id, msg.batch_id)
                    if msg.error is not None:
                        self._send(
                            AgentResult(
                                msg.worker_id,
                                msg.batch_id,
                                error=msg.error,
                                process_time_s=msg.process_time_s,
                            )
                        )
                        continue
                    # outputs STAY in this node's store; only descriptors
                    # ride the control link. Consumers pull the bytes from
                    # our ObjectServer; the driver sends ReleaseObjects when
                    # the last consumer is done.
                    self._send(
                        AgentResult(
                            msg.worker_id,
                            msg.batch_id,
                            out_refs=[
                                (r.shm_name, r.total_size, r.num_buffers)
                                for r in msg.out_refs
                            ],
                            process_time_s=msg.process_time_s,
                            deserialize_time_s=msg.deserialize_time_s,
                        )
                    )
            except OSError:
                return

    def _watchdog(self, stop: threading.Event) -> None:
        """Detect remote worker PROCESS deaths (the driver can only see the
        link): report WorkerDied so the driver's reap requeues the batch,
        and free the dead worker's in-flight input segments. Also enforces
        per-batch deadlines (SubmitBatch.timeout_s): a worker whose batch
        outlives its deadline is presumed hung, killed, and reported
        through the same WorkerDied path as a real death."""
        while not stop.is_set():
            time.sleep(1.0)
            now = time.monotonic()
            with self._lock:
                expired = [k for k, d in self.deadlines.items() if now >= d]
            for key, batch_id in expired:
                with self._lock:
                    entry = self.workers.pop(key, None)
                    self.deadlines.pop((key, batch_id), None)
                if entry is None:
                    continue  # already reaped as a death
                logger.warning(
                    "worker %s batch %d exceeded its deadline on agent; "
                    "killing hung worker", key, batch_id,
                )
                try:
                    entry[1].kill()  # SIGKILL: hung code may ignore SIGTERM
                    entry[1].join(timeout=2.0)
                except (OSError, AttributeError):
                    logger.debug("kill failed for %s", key, exc_info=True)
                for wkey, b_id in list(self.inflight):
                    if wkey == key:
                        self._release_inflight(wkey, b_id)
                try:
                    self._send(WorkerDied(key))
                except OSError:
                    return
            for key, (_in_q, proc) in list(self.workers.items()):
                if proc.is_alive():
                    continue
                with self._lock:
                    self.workers.pop(key, None)
                logger.warning("worker %s died on agent (exit %s)", key, proc.exitcode)
                for wkey, batch_id in list(self.inflight):
                    if wkey == key:
                        self._release_inflight(wkey, batch_id)
                try:
                    self._send(WorkerDied(key))
                except OSError:
                    return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="cosmos-curate-tpu engine node agent")
    ap.add_argument("--driver", required=True, help="driver HOST:PORT")
    ap.add_argument("--node-id", default=None)
    ap.add_argument("--num-cpus", type=float, default=None)
    args = ap.parse_args(argv)
    from cosmos_curate_tpu import chaos
    from cosmos_curate_tpu.observability.tracing import setup_tracing_from_env

    chaos.install_from_env()  # soak rigs arm agent-side faults via env
    setup_tracing_from_env()  # CURATE_TRACING=1 joins the agent to the trace
    return NodeAgent(args.driver, node_id=args.node_id, num_cpus=args.num_cpus).run()


if __name__ == "__main__":
    raise SystemExit(main())
