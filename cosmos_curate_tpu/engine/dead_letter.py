"""Durable dead-letter queue for batches the engine gives up on.

The reference engine's last resort for a poison batch is a log line — at
petabyte scale that is silent data loss (engine/runner.py's ``drop
LOUDLY`` path). The DLQ turns every permanent drop into a durable,
inspectable, re-runnable artifact: before the batch's refs are released,
its task payloads are materialized and persisted together with failure
metadata.

Layout (one directory per run, one per dead batch)::

    <root>/<run_id>/
        batch-<id>-<stage>/
            meta.json     # stage, attempts, worker_deaths, reason, error tail
            tasks.pkl     # cloudpickle list[PipelineTask]

``root`` resolves from ``CURATE_DLQ_DIR`` (default
``~/.cache/cosmos-curate-tpu/dlq``); set it to "" to disable persistence
entirely. Directories are created lazily — a clean run writes nothing.

Inspect and re-run with ``cosmos-curate-tpu dlq list|show|requeue``
(cli/dlq_cli.py) or programmatically via :func:`list_entries` /
:meth:`DlqEntry.load_tasks`.
"""

from __future__ import annotations

import json
import os
import re
import time
import uuid
from dataclasses import dataclass
from pathlib import Path

from cosmos_curate_tpu.utils import schema_stamp
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DLQ_DIR_ENV = "CURATE_DLQ_DIR"
_ERROR_TAIL = 4000  # chars of the failure traceback kept in meta.json


def default_root() -> str:
    """'' disables the DLQ (explicit empty env var)."""
    if DLQ_DIR_ENV in os.environ:
        return os.environ[DLQ_DIR_ENV]
    return os.path.join(os.path.expanduser("~"), ".cache", "cosmos-curate-tpu", "dlq")


@dataclass(frozen=True)
class DlqEntry:
    """One dead batch on disk."""

    path: Path  # .../<run_id>/batch-<id>-<stage>
    meta: dict

    @property
    def entry_id(self) -> str:
        return f"{self.path.parent.name}/{self.path.name}"

    def load_tasks(self) -> list:
        import cloudpickle

        with open(self.path / "tasks.pkl", "rb") as f:
            return cloudpickle.loads(f.read())

    def mark_requeued(self) -> None:
        meta = dict(self.meta)
        meta["requeued_at"] = time.time()
        (self.path / "meta.json").write_text(json.dumps(meta, indent=2))


class DeadLetterQueue:
    """Run-scoped writer. Lazy: the run directory appears on first record.

    Persistence must never turn a dropped batch into a crashed pipeline —
    every failure in here degrades to the old log-only behavior.
    """

    def __init__(self, root: str | None = None, *, run_id: str | None = None) -> None:
        self.root = default_root() if root is None else root
        # the random suffix keeps two runs started in the same second (same
        # service process) from sharing a dir and overwriting each other
        self.run_id = run_id or (
            f"run-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        )
        self.recorded = 0

    @property
    def enabled(self) -> bool:
        return bool(self.root)

    @property
    def run_dir(self) -> Path:
        return Path(self.root) / self.run_id

    def record(
        self,
        *,
        stage_name: str,
        batch_id: int,
        tasks: list,
        attempts: int,
        worker_deaths: int,
        reason: str,
        error: str = "",
        payload_errors: list[str] | None = None,
        trace_id: str = "",
        lost_node: str = "",
        node_deaths: int = 0,
        lineage: list | None = None,
    ) -> Path | None:
        """Persist one dead batch; returns its directory (None = disabled
        or failed — the caller's drop proceeds regardless).

        ``trace_id`` links the entry back to its distributed trace; when
        omitted, the recorder captures the current context's trace id (the
        runners record drops from inside their run span), so `dlq show`
        can answer "which trace dropped this batch".

        ``lost_node`` + ``lineage`` stamp owner-loss drops: the node whose
        death orphaned the batch's inputs and the producer chain lineage
        reconstruction walked before giving up — so operators can tell
        "node died past budget" (requeue and move on) from "batch is
        poison" (investigate the payload)."""
        if not self.enabled:
            return None
        import cloudpickle

        if not trace_id:
            from cosmos_curate_tpu.observability.tracing import current_trace_id

            trace_id = current_trace_id() or ""

        # stage names are arbitrary user strings; path separators (or any
        # exotic char) must not nest/escape the entry dir and break the CLI
        safe_stage = re.sub(r"[^A-Za-z0-9._-]", "_", stage_name) or "stage"
        entry = self.run_dir / f"batch-{batch_id}-{safe_stage}"
        try:
            entry.mkdir(parents=True, exist_ok=True)
            with open(entry / "tasks.pkl", "wb") as f:
                f.write(cloudpickle.dumps(tasks))
            meta = {
                "stage": stage_name,
                "batch_id": batch_id,
                "num_tasks": len(tasks),
                "attempts": attempts,
                "worker_deaths": worker_deaths,
                "reason": reason,
                "error_tail": error[-_ERROR_TAIL:] if error else "",
                "dropped_at": time.time(),
                "run_id": self.run_id,
                "trace_id": trace_id,
            }
            if payload_errors:
                # some payloads could not be materialized (e.g. their owner
                # node died): the entry is partial, and says so
                meta["payload_errors"] = payload_errors
            if lost_node:
                meta["lost_node"] = lost_node
            if node_deaths:
                meta["node_deaths"] = node_deaths
            if lineage:
                meta["lineage"] = lineage
            schema_stamp.stamp(meta, "dlq-meta")
            (entry / "meta.json").write_text(json.dumps(meta, indent=2))
        except Exception:
            logger.exception(
                "DLQ write failed for stage %s batch %d (dropping without record)",
                stage_name, batch_id,
            )
            return None
        self.recorded += 1
        logger.error(
            "stage %s batch %d dead-lettered to %s (%d tasks)",
            stage_name, batch_id, entry, len(tasks),
        )
        return entry


def record_exhausted_batch(
    dlq: "DeadLetterQueue | None",
    *,
    stage_name: str,
    batch_id: int,
    tasks: list,
    attempts: int,
    error: str = "",
) -> bool:
    """Shared drop path for the in-process runners (SequentialRunner,
    PipelinedRunner): persist a batch whose ``num_run_attempts`` budget is
    exhausted. Keeps both runners' DLQ records in lockstep with each other
    (reason string, worker_deaths=0) so the ``dlq`` CLI treats them
    identically. Returns True when an entry was written; never raises —
    the caller's drop proceeds regardless."""
    if dlq is None or not dlq.enabled:
        return False
    try:
        return (
            dlq.record(
                stage_name=stage_name,
                batch_id=batch_id,
                tasks=tasks,
                attempts=attempts,
                worker_deaths=0,
                reason=f"num_run_attempts ({attempts}) exhausted",
                error=error,
            )
            is not None
        )
    except Exception:
        logger.exception("DLQ record failed; batch dropped without record")
        return False


def list_entries(root: str | None = None, *, run_id: str | None = None) -> list[DlqEntry]:
    """All entries under ``root`` (newest run first), or one run's."""
    base = Path(default_root() if root is None else root)
    if not base.is_dir():
        return []
    runs = (
        [base / run_id]
        if run_id
        else sorted((p for p in base.iterdir() if p.is_dir()), reverse=True)
    )
    out: list[DlqEntry] = []
    for run in runs:
        if not run.is_dir():
            continue
        for entry in sorted(p for p in run.iterdir() if p.is_dir()):
            meta_path = entry / "meta.json"
            try:
                # pre-stamp (v1) entries migrate through the shim chain;
                # entries written by a NEWER build read as-is (strict=False)
                # — listing is display-only, unknown fields are harmless
                meta = schema_stamp.upgrade(
                    json.loads(meta_path.read_text()), "dlq-meta", strict=False
                )
            except (OSError, ValueError):
                meta = {"stage": "?", "batch_id": -1, "error_tail": "unreadable meta.json"}
            out.append(DlqEntry(path=entry, meta=meta))
    return out


def find_entry(entry_id: str, root: str | None = None) -> DlqEntry:
    """Resolve ``<run_id>/<batch-dir>`` (or a unique suffix of it)."""
    entries = list_entries(root)
    exact = [e for e in entries if e.entry_id == entry_id]
    if not exact:
        exact = [e for e in entries if e.entry_id.endswith(entry_id)]
    if not exact:
        raise FileNotFoundError(f"no DLQ entry matching {entry_id!r}")
    if len(exact) > 1:
        raise ValueError(
            f"{entry_id!r} is ambiguous: "
            + ", ".join(e.entry_id for e in exact[:5])
        )
    return exact[0]
