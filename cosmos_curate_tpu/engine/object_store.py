"""Shared-memory object store (plasma-lite).

Equivalent capability of the Ray object store the reference rides
(ARCHITECTURE.md:29-32 in /root/reference/docs — refs move centrally, data
stays put): objects are pickled with protocol 5, large buffers (numpy
arrays, bytes) land in one POSIX shared-memory segment per object, and only
a small ``ObjectRef`` travels through queues. A consumer process maps the
segment and reconstructs the object with zero-copy views for numpy arrays.

Ownership: the creating side holds the segment; the engine coordinator
tracks refcounts and unlinks when every consumer is done. Capacity is
budgeted; ``put`` blocks (backpressure) when the store is full.
"""

from __future__ import annotations

import os
import pickle
import re
import threading
import uuid
from dataclasses import dataclass
from multiprocessing import shared_memory

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_HEADER = 8  # u64 pickle-bytes length prefix


@dataclass(frozen=True)
class ObjectRef:
    """48-byte-ish handle that travels through control queues."""

    shm_name: str
    total_size: int
    num_buffers: int

    def __repr__(self) -> str:
        return f"ObjectRef({self.shm_name}, {self.total_size}B)"


def _native_put(name: str, payload: bytes, views: list, sizes: list[int], total: int) -> bool:
    """Single-pass native framing (cosmos_curate_tpu/native); False = fall
    back to the Python path. numpy wraps each buffer to get a stable
    pointer without copying (works for read-only buffers too)."""
    from cosmos_curate_tpu.native import load_native

    lib = load_native()
    if lib is None:
        return False
    import ctypes

    import numpy as _np

    n = len(views)
    ptrs = (ctypes.c_void_p * max(1, n))()
    szs = (ctypes.c_uint64 * max(1, n))()
    arrs = []  # keep alive until the call returns
    for i, v in enumerate(views):
        a = _np.frombuffer(v.cast("B"), _np.uint8)
        arrs.append(a)
        ptrs[i] = a.ctypes.data
        szs[i] = a.nbytes
    rc = lib.cn_put(
        f"/{name}".encode(), payload, len(payload), ptrs, szs, n, total
    )
    return rc == 0


def put(obj, *, prefix: str | None = None) -> ObjectRef:
    """Serialize ``obj`` into a fresh shm segment; returns its ref.

    Segment names embed the *coordinator's* pid (``cur<pid>-<hex>``) so the
    janitor can reclaim segments after a whole pipeline dies. Workers inherit
    the coordinator pid via ``CURATE_STORE_OWNER`` — segments must NOT carry
    the worker's own pid, because recycled/crashed workers leave live data
    behind that downstream stages still consume."""
    if prefix is None:
        prefix = f"cur{os.environ.get('CURATE_STORE_OWNER', os.getpid())}"
    import cloudpickle

    buffers: list[pickle.PickleBuffer] = []
    # cloudpickle (same protocol-5 out-of-band buffer path as pickle, and
    # its output is a standard pickle stream for get()): tasks whose classes
    # live in __main__ — a user's driver script — serialize by value, which
    # the cross-node plane needs on agents that never import that script
    payload = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    sizes = [len(v) for v in views]
    # layout: [u64 len(payload)][payload][u64 nbuf][u64 size]*nbuf [buffers...]
    meta = len(sizes).to_bytes(8, "little") + b"".join(s.to_bytes(8, "little") for s in sizes)
    total = _HEADER + len(payload) + len(meta) + sum(sizes)
    name = f"{prefix}-{uuid.uuid4().hex[:16]}"
    if _native_put(name, payload, views, sizes, max(total, 16)):
        for b in buffers:
            b.release()
        return ObjectRef(shm_name=name, total_size=total, num_buffers=len(sizes))
    seg = shared_memory.SharedMemory(name=name, create=True, size=max(total, 16))
    # CPython's resource tracker registers every segment and unlinks the
    # "leaks" when *this* process exits — but ownership here is the
    # coordinator's (a recycled worker must not destroy segments downstream
    # stages still consume). Deletion is handled by StoreBudget.release and
    # the stale-segment janitor instead.
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass
    try:
        mv = seg.buf
        try:
            mv[:_HEADER] = len(payload).to_bytes(8, "little")
            off = _HEADER
            mv[off : off + len(payload)] = payload
            off += len(payload)
            mv[off : off + len(meta)] = meta
            off += len(meta)
            for v in views:
                n = v.nbytes
                mv[off : off + n] = v.cast("B") if v.ndim != 1 or v.format != "B" else v
                off += n
        finally:
            del mv  # release exported pointer before close
    finally:
        for b in buffers:
            b.release()
        seg.close()
    return ObjectRef(shm_name=name, total_size=total, num_buffers=len(sizes))


_SHM_DIR = "/dev/shm"
_COPY_THRESHOLD = 1 << 20  # buffers below 1 MiB are copied out of the view


def get(ref: ObjectRef):
    """Reconstruct the object: ONE read of the whole segment, then zero-copy
    memoryview slices feed pickle's out-of-band buffers (numpy arrays view
    the read buffer directly).

    Reads the segment file directly — attaching via
    ``multiprocessing.shared_memory`` would register it with this process's
    resource tracker, which unlinks registered segments at process exit and
    would destroy data other processes still need (worker recycling).
    """
    path = os.path.join(_SHM_DIR, ref.shm_name)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError as e:
        raise FileNotFoundError(f"object store segment {ref.shm_name} missing") from e
    return loads_segment(data)


def loads_segment(data: bytes):
    """Reconstruct an object from raw segment bytes (the store's on-disk
    format) — used both by local get() and by the cross-node object channel
    when the consumer wants the value without creating a local segment."""
    mv = memoryview(data)
    plen = int.from_bytes(mv[:_HEADER], "little")
    off = _HEADER
    payload = mv[off : off + plen]
    off += plen
    nbuf = int.from_bytes(mv[off : off + 8], "little")
    off += 8
    sizes = [
        int.from_bytes(mv[off + 8 * i : off + 8 * (i + 1)], "little") for i in range(nbuf)
    ]
    off += 8 * nbuf
    # Small buffers are copied out: a kept small array must not pin the
    # whole segment bytes via its memoryview. Large buffers stay views —
    # they dominate the segment anyway, so pinning costs ~nothing.
    bufs = []
    for s in sizes:
        chunk = mv[off : off + s]
        bufs.append(bytes(chunk) if s < _COPY_THRESHOLD else chunk)
        off += s
    return pickle.loads(payload, buffers=bufs)


def segment_path(name: str) -> str:
    return os.path.join(_SHM_DIR, name)


def valid_segment_name(name: str) -> bool:
    """Only store-shaped names may cross the object channel (a hostile GET
    must not read arbitrary /dev/shm files, nor contain path separators)."""
    return re.fullmatch(r"cur\d+-[0-9a-f]+", name) is not None


def put_raw_chunks(chunks, total_size: int, num_buffers: int, *, prefix: str | None = None) -> ObjectRef:
    """Write raw segment bytes (the on-disk format, e.g. streamed from
    another node's store) into a LOCAL segment under the local owner's
    name — the source node's name must not be reused, because the stale
    -segment janitor reclaims segments whose embedded pid is dead on THIS
    host. Constant-memory: ``chunks`` is an iterable of byte chunks."""
    if prefix is None:
        prefix = f"cur{os.environ.get('CURATE_STORE_OWNER', os.getpid())}"
    name = f"{prefix}-{uuid.uuid4().hex[:16]}"
    tmp = segment_path(name) + ".tmp"
    written = 0
    try:
        with open(tmp, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
                written += len(chunk)
        if written != total_size:
            raise ConnectionError(
                f"object transfer truncated: got {written} of {total_size} bytes"
            )
    except BaseException:
        # any failure (source raised mid-stream, MAC mismatch, short write)
        # must not leave a .tmp pinning /dev/shm RAM — the janitor's name
        # pattern never matches it
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, segment_path(name))
    return ObjectRef(shm_name=name, total_size=total_size, num_buffers=num_buffers)


def delete(ref: ObjectRef) -> None:
    try:
        os.unlink(os.path.join(_SHM_DIR, ref.shm_name))
    except FileNotFoundError:
        pass


def cleanup_stale_segments(shm_dir: str = "/dev/shm") -> int:
    """Unlink ``cur<pid>-*`` segments whose creating process is gone —
    crashed or killed runs must not leak shared memory forever. Returns the
    number reclaimed. Safe against concurrent live pipelines: only segments
    of dead pids are touched."""
    n = 0
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0
    for name in names:
        m = re.fullmatch(r"cur(\d+)-[0-9a-f]+", name)
        if not m:
            continue
        pid = int(m.group(1))
        try:
            os.kill(pid, 0)
            continue  # owner alive
        except ProcessLookupError:
            pass
        except PermissionError:
            continue  # someone else's pid namespace; leave it
        try:
            os.unlink(os.path.join(shm_dir, name))
            n += 1
        except OSError:
            pass
    if n:
        logger.info("reclaimed %d stale object-store segments", n)
    return n


class StoreBudget:
    """Coordinator-side capacity accounting with blocking backpressure.

    ``deleter`` frees a released ref's storage; the default unlinks the
    local segment, and the cross-node runner passes a location-aware
    deleter that forwards agent-owned segments to their owner."""

    def __init__(self, capacity_bytes: int, *, deleter=None) -> None:
        self.capacity = capacity_bytes
        self._used = 0
        self._live: dict[str, int] = {}
        self._cv = threading.Condition()
        self._deleter = deleter or delete

    @property
    def used(self) -> int:
        return self._used

    def account(self, ref: ObjectRef) -> None:
        """Unconditionally account an object that already exists (stage
        outputs): accounting must never lose track of live segments, so
        this can push ``used`` above capacity — ``has_headroom`` then gates
        new admissions (input seeding) until consumers release."""
        with self._cv:
            self._live[ref.shm_name] = ref.total_size
            self._used += ref.total_size

    def has_headroom(self) -> bool:
        with self._cv:
            return self._used < self.capacity or not self._live

    def tracks(self, ref: ObjectRef) -> bool:
        """Whether ``ref`` is currently in the ledger (accounted, not yet
        released) — reconstruction uses this to release exactly the refs
        it accounted at adoption and no others."""
        with self._cv:
            return ref.shm_name in self._live

    def release(self, ref: ObjectRef) -> None:
        with self._cv:
            size = self._live.pop(ref.shm_name, 0)
            self._used -= size
            self._cv.notify_all()
        self._deleter(ref)
