"""Peer-to-peer object transfer for the cross-node engine plane.

Equivalent capability of the Ray object plane the reference rides
(ARCHITECTURE.md:70-81 — the central loop moves ~48-byte refs; DATA moves
directly between the nodes that produce and consume it): every engine
process (driver and each node agent) runs an ``ObjectServer`` over its
local shared-memory store, and consumers pull segments straight from the
owner. The driver's control socket carries only ref descriptors; segment
RELEASE also rides the control link (remote_plane.ReleaseObjects), so this
channel is read-only.

Wire protocol (per connection, authenticated with the cluster token):
- request: one MAC'd control frame (remote_plane.send_msg) —
  ``("get", shm_name, nonce16)``, optionally extended with the caller's
  W3C traceparent (``("get", shm_name, nonce16, traceparent)``) so the
  OWNER's serve span joins the fetcher's trace instead of fragmenting.
- response: ``status u8 | total u64 | data stream | hmac-sha256`` where
  the MAC covers ``shm_name || nonce || data`` — binding the stream to
  THIS request, so a recorded stream of a different segment (or an old
  stream of the same name) cannot be replayed as the answer. The MAC is
  computed incrementally on both sides: transfers are constant-memory
  with no frame-size cap, so large batches stream instead of hitting a
  control-frame cliff.
"""

from __future__ import annotations

import contextlib
import hashlib
import hmac
import os
import socket
import struct
import threading
from typing import Iterator

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.engine import object_store
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CHUNK = 1 << 20
_OK = b"\x01"
_MISSING = b"\x02"
_DENIED = b"\x03"


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("object channel peer closed")
        buf += chunk
    return bytes(buf)


def _stream_mac(token: bytes, name: str, nonce: bytes) -> "hmac.HMAC":
    mac = hmac.new(token, digestmod=hashlib.sha256)
    mac.update(name.encode())
    mac.update(nonce)
    return mac


class ObjectServer:
    """Serves GETs for the local object store. One thread per request —
    transfers are IO-bound and overlap; the store is just files in
    /dev/shm, so there is no shared mutable state to lock."""

    def __init__(self, token: bytes, *, host: str = "0.0.0.0") -> None:
        self._token = token
        self._server = socket.create_server((host, 0))
        self.port = self._server.getsockname()[1]
        self._closed = False
        # one request thread per GET: the counters are mutated concurrently
        self._stats_lock = threading.Lock()
        self.gets_served = 0  # observability + tests
        self.bytes_served = 0
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(sock,), daemon=True).start()

    def _serve_one(self, sock: socket.socket) -> None:
        from cosmos_curate_tpu.engine.remote_plane import recv_msg

        try:
            # a wedged/half-open peer must not pin this thread forever
            sock.settimeout(30)
            req = recv_msg(sock, self._token, max_bytes=1 << 20)
            if (
                isinstance(req, tuple)
                and len(req) in (3, 4)
                and req[0] == "get"
                and isinstance(req[2], bytes)
            ):
                tp = req[3] if len(req) == 4 and isinstance(req[3], str) else ""
                self._serve_get(sock, req[1], req[2], tp)
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("object server request failed")
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _serve_get(
        self, sock: socket.socket, name, nonce: bytes, traceparent: str = ""
    ) -> None:
        from cosmos_curate_tpu.observability.tracing import traced_span

        # kind=error: the connection resets before any bytes are served —
        # consumers see a dropped transfer, exactly like a mid-GET peer death
        chaos.fire(chaos.SITE_OBJECT_CHANNEL_SERVE)
        if not isinstance(name, str) or not object_store.valid_segment_name(name):
            sock.sendall(_DENIED + struct.pack(">Q", 0))
            return
        # serve threads have no ambient context, so an un-traced peer's pull
        # records nothing (a span without the incoming traceparent could
        # only start a one-span fragment). The span opens BEFORE the
        # segment lookup: a missing segment (release race, premature
        # eviction) is exactly the serve outcome worth tracing
        with contextlib.ExitStack() as stack:
            if traceparent:
                span = stack.enter_context(
                    traced_span(
                        "object_channel.serve", traceparent=traceparent, segment=name
                    )
                )
            else:
                span = None
            try:
                f = open(object_store.segment_path(name), "rb")
            except FileNotFoundError:
                if span is not None:
                    span.set_attribute("result", "missing")
                sock.sendall(_MISSING + struct.pack(">Q", 0))
                return
            stack.enter_context(f)
            f.seek(0, 2)
            total = f.tell()
            f.seek(0)
            if span is not None:
                span.set_attribute("bytes", total)
            sock.sendall(_OK + struct.pack(">Q", total))
            mac = _stream_mac(self._token, name, nonce)
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                mac.update(chunk)
                sock.sendall(chunk)
            sock.sendall(mac.digest())
        with self._stats_lock:
            self.gets_served += 1
            self.bytes_served += total

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass


def _open_get(
    addr: tuple[str, int], token: bytes, name: str
) -> tuple[socket.socket, int, "Iterator[bytes]"]:
    from cosmos_curate_tpu.engine.remote_plane import send_msg
    from cosmos_curate_tpu.observability.tracing import format_traceparent

    # kind=error: the dial/transfer fails as a ConnectionError, flowing
    # through the same localize/fetch retry paths a real drop would
    chaos.fire(chaos.SITE_OBJECT_CHANNEL_FETCH)
    nonce = os.urandom(16)
    sock = socket.create_connection(addr, timeout=30)
    try:
        # the traceparent rides the request so the OWNER's serve span joins
        # this fetch's trace (the caller's fetch span is ambient here).
        # Untraced requests keep the legacy 3-tuple. Version skew is no
        # longer this tuple's problem: every peer on the plane passed the
        # PROTOCOL_VERSION handshake (remote_plane.Hello/HelloAck), so a
        # mixed-version fleet is rejected at connect rather than reaching
        # this request — the old "tracing requires same-version peers"
        # caveat is now enforced, not documented. The tuple's shape is a
        # registered contract surface (`lint --schema`): changing its arity
        # or element types requires a PROTOCOL_VERSION bump.
        tp = format_traceparent()
        req = ("get", name, nonce, tp) if tp else ("get", name, nonce)
        send_msg(sock, req, token)
        head = _recv_exact(sock, 1 + 8)
        status = head[:1]
        (total,) = struct.unpack(">Q", head[1:])
        if status == _MISSING:
            raise FileNotFoundError(f"object {name} not on owner")
        if status != _OK:
            raise ConnectionError(f"object fetch for {name} denied")
    except BaseException:
        sock.close()
        raise

    def chunks() -> "Iterator[bytes]":
        mac = _stream_mac(token, name, nonce)
        left = total
        while left:
            chunk = sock.recv(min(_CHUNK, left))
            if not chunk:
                raise ConnectionError("object stream truncated")
            mac.update(chunk)
            left -= len(chunk)
            yield chunk
        trailer = _recv_exact(sock, 32)
        if not hmac.compare_digest(trailer, mac.digest()):
            raise ConnectionError(f"object {name} failed stream authentication")

    return sock, total, chunks()


def fetch_object(
    addr: tuple[str, int], token: bytes, ref: object_store.ObjectRef
) -> object_store.ObjectRef:
    """Pull a segment from its owner into the LOCAL store; returns the
    local ref. Constant-memory streaming; the request-bound trailing MAC
    authenticates the whole stream. The .tmp-then-rename in put_raw_chunks
    means a truncated/forged transfer never becomes a visible segment."""
    from cosmos_curate_tpu.observability.tracing import traced_span

    with traced_span(
        "object_channel.fetch",
        segment=ref.shm_name,
        owner=f"{addr[0]}:{addr[1]}",
    ) as span:
        sock, total, chunks = _open_get(addr, token, ref.shm_name)
        span.set_attribute("bytes", total)
        try:
            return object_store.put_raw_chunks(chunks, total, ref.num_buffers)
        finally:
            try:
                sock.close()
            except OSError:
                pass


def fetch_value(addr: tuple[str, int], token: bytes, ref: object_store.ObjectRef):
    """Pull a segment and reconstruct the object WITHOUT creating a local
    segment (final-sink materialization)."""
    from cosmos_curate_tpu.observability.tracing import traced_span

    with traced_span(
        "object_channel.fetch_value",
        segment=ref.shm_name,
        owner=f"{addr[0]}:{addr[1]}",
    ) as span:
        sock, total, chunks = _open_get(addr, token, ref.shm_name)
        span.set_attribute("bytes", total)
        try:
            # chunks() delivers exactly `total` bytes or raises (truncation
            # and MAC failures surface from the generator)
            return object_store.loads_segment(b"".join(chunks))
        finally:
            try:
                sock.close()
            except OSError:
                pass
