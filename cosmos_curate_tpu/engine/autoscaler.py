"""Throughput-based autoscaler.

Equivalent capability of xenna's autoscaler (reference
docs/curator/reference/ARCHITECTURE.md:83-93): measure per-worker throughput
per stage, then solve for the worker allocation that maximizes *balanced*
pipeline throughput under the CPU/TPU budget.

Solver: water-filling. The pipeline rate is min over stages of
(workers_i x rate_i); repeatedly grant a worker to the stage with the lowest
projected stage rate until the budget is exhausted. Stages without
throughput samples yet get their minimum and first claim on resources.

Backpressure signals: the observed input-queue depth *biases* the fill —
between stages with similar projected rates, the one with the deeper backlog
wins — and a drained stage (empty queue, known rate) stops receiving extra
workers beyond its minimum, so budget flows to starved stages after a
throughput shift (reference ARCHITECTURE.md:83-93 solves the same balanced-
throughput-under-backpressure problem).
"""

from __future__ import annotations

from dataclasses import dataclass

from cosmos_curate_tpu.core.stage import StageSpec


@dataclass
class StageScaleState:
    spec: StageSpec
    current_workers: int
    throughput_per_worker: float | None  # batches/s; None = unknown yet
    queued: int


@dataclass(frozen=True)
class Budget:
    cpus: float
    tpus: float


def discover_tpu_chips(cfg, stage_specs: list[StageSpec]) -> int:
    """Local TPU chip count for the budget, shared by the streaming and
    pipelined runners. Only probes devices when some stage actually
    requests TPU resources — a jax import can hang on a dead TPU tunnel,
    so pure-CPU pipelines never pay it. An explicit
    ``PipelineConfig.num_tpu_chips`` wins outright."""
    if cfg.num_tpu_chips is not None:
        return cfg.num_tpu_chips
    if not any(s.stage.resources.uses_tpu for s in stage_specs):
        return 0
    try:
        import jax

        return max(1, len([d for d in jax.devices() if d.platform == "tpu"]))
    except Exception:
        return 1


def plan_allocation(stages: list[StageScaleState], budget: Budget) -> list[int]:
    """Target worker counts per stage (same order as input)."""
    n = len(stages)
    alloc = [0] * n
    cpu_left = budget.cpus
    tpu_left = budget.tpus

    def cost(i: int) -> tuple[float, float]:
        r = stages[i].spec.stage.resources
        tpus = r.tpus if not r.entire_tpu_host else budget.tpus
        cpus = r.cpus
        if cpus <= 0 and tpus <= 0:
            # A declared zero-cost stage (pure-IO) must still consume budget,
            # or the water-fill below never terminates (fits() forever true).
            cpus = 0.25
        return (cpus, tpus)

    def fits(i: int) -> bool:
        c, t = cost(i)
        return c <= cpu_left + 1e-9 and t <= tpu_left + 1e-9

    def grant(i: int) -> None:
        nonlocal cpu_left, tpu_left
        c, t = cost(i)
        alloc[i] += 1
        cpu_left -= c
        tpu_left -= t

    # 1. minimum viable allocation: every stage gets >= min_workers (>=1)
    #    even if that oversubscribes the host — a pipeline where some stage
    #    has zero workers can never finish. Only *additional* workers
    #    respect the budget.
    for i, st in enumerate(stages):
        want = max(1, st.spec.min_workers)
        if st.spec.num_workers is not None:
            want = st.spec.num_workers
        if st.spec.stage.resources.uses_tpu:
            want = 1  # one in-process worker per TPU stage (see engine/pool.py)
        grant(i)  # unconditional first worker
        for _ in range(want - 1):
            if fits(i):
                grant(i)

    # 2. water-fill the bottleneck with the remaining budget
    while True:
        best = None
        best_score = None
        for i, st in enumerate(stages):
            if st.spec.num_workers is not None:  # fixed-size pool
                continue
            cap = st.spec.max_workers
            if cap is not None and alloc[i] >= cap:
                continue
            if not fits(i):
                continue
            # TPU in-process pools don't scale by worker count
            if st.spec.stage.resources.uses_tpu and alloc[i] >= 1:
                continue
            rate = st.throughput_per_worker
            if rate is not None and st.queued == 0 and alloc[i] >= max(1, st.spec.min_workers):
                # Drained and measured: no backlog to spend extra workers
                # on; leave the budget for starved stages (scale-down
                # pressure — the runner stops the now-surplus idle workers).
                continue
            projected = (rate if rate is not None else 1.0) * alloc[i]
            # Queue bias: between similar projected rates, the deeper
            # backlog wins. Dimensionless damping keeps rate primary.
            score = projected / (1.0 + float(st.queued))
            if best_score is None or score < best_score:
                best_score = score
                best = i
        if best is None:
            break
        grant(best)
    return alloc
