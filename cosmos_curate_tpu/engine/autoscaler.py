"""Throughput-based autoscaler.

Equivalent capability of xenna's autoscaler (reference
docs/curator/reference/ARCHITECTURE.md:83-93): measure per-worker throughput
per stage, then solve for the worker allocation that maximizes *balanced*
pipeline throughput under the CPU/TPU budget.

Solver: water-filling. The pipeline rate is min over stages of
(workers_i x rate_i); repeatedly grant a worker to the stage with the lowest
projected stage rate until the budget is exhausted. Stages without
throughput samples yet get their minimum and first claim on resources.

Backpressure signals: the observed input-queue depth *biases* the fill —
between stages with similar projected rates, the one with the deeper backlog
wins — and a drained stage (empty queue, known rate) stops receiving extra
workers beyond its minimum, so budget flows to starved stages after a
throughput shift (reference ARCHITECTURE.md:83-93 solves the same balanced-
throughput-under-backpressure problem).

Cross-host: ``plan_node_allocation`` lifts the same water-fill to **per-node
budgets** (one ``NodeBudget`` per connected agent plus the driver). The
per-stage totals come from the flat solver over the aggregate budget — so a
single-node plan is bit-identical to ``plan_allocation`` — and a placement
pass then pins device stages to TPU-bearing nodes, honors explicit
``Stage.node_affinity`` hints, and fans CPU workers across nodes weighted by
each node's measured per-worker throughput for that stage, with a
co-location bias toward the previous stage's node so inter-stage bytes stay
on-node (the T5X data/model-axis split: data-parallel CPU pools scale out
across hosts, the model mesh stays whole on its host).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cosmos_curate_tpu.core.stage import StageSpec


@dataclass
class StageScaleState:
    spec: StageSpec
    current_workers: int
    throughput_per_worker: float | None  # batches/s; None = unknown yet
    queued: int
    # node_id -> measured per-worker batches/s ON that node. Empty when the
    # run is single-node or no per-node samples landed yet; the per-node
    # placement pass biases CPU fan-out toward faster nodes with it.
    node_rates: dict[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class Budget:
    cpus: float
    tpus: float


@dataclass(frozen=True)
class NodeBudget:
    """One schedulable host: the driver (``node_id=""``, matching the
    runner's worker-node convention) or a connected agent from
    ``engine/remote_agent.py``."""

    node_id: str
    cpus: float
    tpu_chips: int = 0
    memory_gb: float = 0.0


@dataclass
class NodeAllocation:
    """``plan_node_allocation`` output.

    ``targets[i]`` is stage i's total worker count (identical to
    ``plan_allocation`` over the aggregate budget); ``per_node[i]`` splits
    it across nodes; ``preferred_node[i]`` is the node holding the
    plurality of stage i's workers — the router's affinity key (stage k's
    outputs should land where stage k+1's workers live)."""

    targets: list[int]
    per_node: list[dict[str, int]]
    preferred_node: list[str]


def discover_tpu_chips(cfg, stage_specs: list[StageSpec]) -> int:
    """Local TPU chip count for the budget, shared by the streaming and
    pipelined runners. Only probes devices when some stage actually
    requests TPU resources — a jax import can hang on a dead TPU tunnel,
    so pure-CPU pipelines never pay it. An explicit
    ``PipelineConfig.num_tpu_chips`` wins outright."""
    if cfg.num_tpu_chips is not None:
        return cfg.num_tpu_chips
    if not any(s.stage.resources.uses_tpu for s in stage_specs):
        return 0
    try:
        import jax

        return max(1, len([d for d in jax.devices() if d.platform == "tpu"]))
    except Exception:
        return 1


def plan_allocation(stages: list[StageScaleState], budget: Budget) -> list[int]:
    """Target worker counts per stage (same order as input)."""
    n = len(stages)
    alloc = [0] * n
    cpu_left = budget.cpus
    tpu_left = budget.tpus

    def cost(i: int) -> tuple[float, float]:
        r = stages[i].spec.stage.resources
        tpus = r.tpus if not r.entire_tpu_host else budget.tpus
        cpus = r.cpus
        if cpus <= 0 and tpus <= 0:
            # A declared zero-cost stage (pure-IO) must still consume budget,
            # or the water-fill below never terminates (fits() forever true).
            cpus = 0.25
        return (cpus, tpus)

    def fits(i: int) -> bool:
        c, t = cost(i)
        return c <= cpu_left + 1e-9 and t <= tpu_left + 1e-9

    def grant(i: int) -> None:
        nonlocal cpu_left, tpu_left
        c, t = cost(i)
        alloc[i] += 1
        cpu_left -= c
        tpu_left -= t

    # 1. minimum viable allocation: every stage gets >= min_workers (>=1)
    #    even if that oversubscribes the host — a pipeline where some stage
    #    has zero workers can never finish. Only *additional* workers
    #    respect the budget.
    for i, st in enumerate(stages):
        want = max(1, st.spec.min_workers)
        if st.spec.num_workers is not None:
            want = st.spec.num_workers
        if st.spec.stage.resources.uses_tpu:
            want = 1  # one in-process worker per TPU stage (see engine/pool.py)
        grant(i)  # unconditional first worker
        for _ in range(want - 1):
            if fits(i):
                grant(i)

    # 2. water-fill the bottleneck with the remaining budget
    while True:
        best = None
        best_score = None
        for i, st in enumerate(stages):
            if st.spec.num_workers is not None:  # fixed-size pool
                continue
            cap = st.spec.max_workers
            if cap is not None and alloc[i] >= cap:
                continue
            if not fits(i):
                continue
            # TPU in-process pools don't scale by worker count
            if st.spec.stage.resources.uses_tpu and alloc[i] >= 1:
                continue
            rate = st.throughput_per_worker
            if rate is not None and st.queued == 0 and alloc[i] >= max(1, st.spec.min_workers):
                # Drained and measured: no backlog to spend extra workers
                # on; leave the budget for starved stages (scale-down
                # pressure — the runner stops the now-surplus idle workers).
                continue
            projected = (rate if rate is not None else 1.0) * alloc[i]
            # Queue bias: between similar projected rates, the deeper
            # backlog wins. Dimensionless damping keeps rate primary.
            score = projected / (1.0 + float(st.queued))
            if best_score is None or score < best_score:
                best_score = score
                best = i
        if best is None:
            break
        grant(best)
    return alloc


def plan_node_allocation(
    stages: list[StageScaleState], nodes: list[NodeBudget]
) -> NodeAllocation:
    """Per-node × per-stage worker allocation.

    Totals come from ``plan_allocation`` over the aggregate budget (so one
    node reproduces today's plan exactly); placement then assigns each
    worker to a node:

    - TPU stages go to TPU-bearing nodes only (in this engine that is the
      driver — chips belong to the engine process, pool.py invariant).
    - ``Stage.node_affinity`` pins a stage outright (``"driver"`` → the
      driver node).
    - CPU stages water-fill across nodes: each grant goes to the fitting
      node with the best (measured stage rate, co-location with the
      previous stage's preferred node, free CPUs) score — so a
      decode-heavy CPU node systematically feeds a TPU embed node instead
      of competing with it for driver cores.
    """
    if not nodes:
        nodes = [NodeBudget("", cpus=1.0)]
    budget = Budget(
        cpus=sum(n.cpus for n in nodes),
        tpus=float(sum(n.tpu_chips for n in nodes)),
    )
    targets = plan_allocation(stages, budget)
    cpu_left = {n.node_id: n.cpus for n in nodes}
    chips_left = {n.node_id: float(n.tpu_chips) for n in nodes}
    # memory budget participates in the CPU fit check only where BOTH the
    # node declares capacity and the stage declares demand (0 = unknown,
    # fit on CPUs alone — the pre-memory behavior)
    mem_left = {n.node_id: n.memory_gb for n in nodes}
    driver_id = nodes[0].node_id  # runner convention: nodes[0] is the driver
    per_node: list[dict[str, int]] = []
    preferred: list[str] = []
    prev_pref = driver_id
    for i, (st, want) in enumerate(zip(stages, targets)):
        res = st.spec.stage.resources
        affinity = getattr(st.spec.stage, "node_affinity", None)
        counts: dict[str, int] = {}
        for _ in range(want):
            if affinity == "driver":
                chosen = driver_id
            elif res.uses_tpu:
                # device stages pin to TPU-bearing nodes; with none visible
                # (CPU-fallback dev boxes) the driver hosts the in-process
                # worker exactly as the flat path does
                cands = [n.node_id for n in nodes if n.tpu_chips > 0] or [driver_id]
                chosen = max(cands, key=lambda nid: chips_left[nid])
                chips_left[chosen] -= (
                    res.tpus if not res.entire_tpu_host else chips_left[chosen]
                )
            else:
                ccost = res.cpus if res.cpus > 0 else 0.25
                chosen = _best_cpu_node(
                    st, nodes, cpu_left, ccost, prev_pref,
                    mem_left=mem_left, mem_cost=res.memory_gb,
                )
            counts[chosen] = counts.get(chosen, 0) + 1
            cpu_left[chosen] -= res.cpus if res.cpus > 0 else 0.25
            mem_left[chosen] -= res.memory_gb
        per_node.append(counts)
        # plurality node; deterministic tie-break by node order, so the
        # router's affinity key is stable across replans with equal splits
        order = {n.node_id: j for j, n in enumerate(nodes)}
        pref = (
            max(counts, key=lambda nid: (counts[nid], -order.get(nid, 0)))
            if counts
            else prev_pref
        )
        preferred.append(pref)
        prev_pref = pref
    return NodeAllocation(targets=targets, per_node=per_node, preferred_node=preferred)


def _best_cpu_node(
    st: StageScaleState,
    nodes: list[NodeBudget],
    cpu_left: dict[str, float],
    ccost: float,
    prev_pref: str,
    *,
    mem_left: dict[str, float] | None = None,
    mem_cost: float = 0.0,
) -> str:
    """One CPU-worker grant: fitting nodes first, then measured per-worker
    rate on that node (a node that decodes 2× faster per worker earns the
    worker), then co-location with the upstream stage's node (inter-stage
    bytes stay local), then free CPUs (balance). A node with no samples
    yet ranks at the MEAN measured rate — neutral exploration — so an
    unmeasured late joiner neither outranks every measured-but-slow node
    nor starves, and the co-location bias stays decisive between
    rate-equivalent nodes. Nothing fits → least oversubscribed node,
    mirroring the flat planner's unconditional min-viable grant."""
    measured = [r for r in st.node_rates.values() if r > 0]
    neutral = sum(measured) / len(measured) if measured else 1.0

    def key(n: NodeBudget):
        fits = cpu_left[n.node_id] + 1e-9 >= ccost
        if fits and mem_cost > 0 and n.memory_gb > 0 and mem_left is not None:
            fits = mem_left[n.node_id] + 1e-9 >= mem_cost
        rate = st.node_rates.get(n.node_id)
        return (
            fits,
            rate if rate is not None else neutral,
            1 if n.node_id == prev_pref else 0,
            cpu_left[n.node_id],
        )

    return max(nodes, key=key).node_id
