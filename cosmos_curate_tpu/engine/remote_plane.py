"""Cross-node inter-stage CONTROL plane for the streaming engine.

Equivalent capability of xenna's cross-node execution (reference
ARCHITECTURE.md:25-27,70-81 — the central loop moves ~48-byte refs between
nodes' per-stage pools; DATA moves producer→consumer): worker processes on
REMOTE hosts join a CPU stage's pool; SubmitBatch frames carry location
-aware ref descriptors and results return as descriptors too — the actual
bytes ride the peer-to-peer object channel (engine/object_channel.py)
between whichever nodes produce and consume them, so the driver's NIC
never gates data volume and large batches stream with no frame-size cap.
The orchestration loop, retries, autoscaler and object store are
unchanged; dispatch prefers the worker whose node already owns a batch's
input bytes.

Topology: the driver (node rank 0) listens on ``CURATE_ENGINE_DRIVER_PORT``;
every other node runs ``python -m cosmos_curate_tpu.engine.remote_agent
--driver host:port``, which spawns the SAME spawned-process workers
(engine/worker.py) the driver uses locally and relays their queues over the
socket. TPU stages never place remotely — each host's chips belong to that
host's engine process (the package invariant); host-level TPU scale stays
with the partition/work-stealing modes.

Wire format: length-prefixed frames authenticated with
HMAC-SHA256(``CURATE_ENGINE_TOKEN``) — a frame that fails the MAC is
dropped before any unpickling, so the plane refuses to run without a
shared token. This replaces the reference's Ray object-plane trust model
with an explicit cluster secret.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import cloudpickle

from cosmos_curate_tpu import chaos
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

TOKEN_ENV = "CURATE_ENGINE_TOKEN"
DRIVER_PORT_ENV = "CURATE_ENGINE_DRIVER_PORT"
WAIT_NODES_ENV = "CURATE_ENGINE_WAIT_NODES"
WAIT_S_ENV = "CURATE_ENGINE_WAIT_S"
# driver-side failure detector: an agent that misses HEARTBEAT_MISSES
# consecutive heartbeat windows (the agent's watchdog ships AgentStats —
# possibly empty — every HEARTBEAT_S) is declared dead deterministically,
# instead of whenever a TCP send happens to fail. 0 disables the deadline
# (link errors still mark agents dead, as before).
HEARTBEAT_S_ENV = "CURATE_AGENT_HEARTBEAT_S"
HEARTBEAT_MISSES_ENV = "CURATE_AGENT_HEARTBEAT_MISSES"
DEFAULT_HEARTBEAT_S = 3.0
DEFAULT_HEARTBEAT_MISSES = 5

_MAGIC = b"CRPL"

# Control-plane protocol version. Frames are unversioned cloudpickle'd
# dataclasses, so two builds whose frame schemas drifted would not fail
# cleanly — they would MISDECODE each other mid-run (missing attributes
# surfacing as AttributeErrors deep in the orchestration loop, or worse,
# defaults silently papering over renamed fields). Instead the version
# rides the Hello/HelloAck handshake and mismatched peers are rejected at
# CONNECT time with an error that names both versions. Bump this whenever
# any frame in this module (or the object-channel request tuple) changes
# shape — `lint --schema` diffs the frame schemas against
# analysis/schemas/remote-plane.json and fails the gate when the shape
# drifted without a bump here.
#
# v1: the unversioned plane (pre-handshake-check builds send no version
#     and read as v0/v1 — rejected).
# v2: Hello/HelloAck carry protocol_version; object-channel GET requests
#     may carry a traceparent 4th element (peers are handshake-matched, so
#     the old "tracing requires same-version peers" caveat is enforced
#     rather than documented).
PROTOCOL_VERSION = 2


def skew_error(peer_version: int, *, peer: str) -> str:
    """The one message both rejection paths log/raise: names both versions
    and the fix, because 'connection closed' during a rolling upgrade is a
    debugging session while this string is a shrug-and-upgrade."""
    return (
        f"protocol version skew: {peer} speaks v{peer_version}, this process "
        f"speaks v{PROTOCOL_VERSION}; refusing at handshake (mixed-version "
        "engine planes misdecode frames mid-run — upgrade the older side)"
    )


class ProtocolSkewError(ConnectionError):
    """Handshake rejected for a VERSION mismatch. Distinct from transient
    ConnectionErrors so the agent's reconnect loop fails fast (redialing a
    skewed driver every 0.5 s until the window expires helps nobody) while
    still flowing through every existing ConnectionError handler."""


def frame_version(frame: object) -> int:
    """The protocol version a handshake frame ACTUALLY carries. Must read
    the instance dict, never getattr: unpickling restores only the
    sender's fields, and on a missing attribute getattr falls back to the
    receiver's CLASS default — which is the receiver's own version, so a
    pre-versioning peer would masquerade as current. ``vars()`` makes the
    missing field read as 0 (pre-versioning) as intended."""
    return int(vars(frame).get("protocol_version", 0))


# -- messages ---------------------------------------------------------------


@dataclass
class Hello:
    node_id: str
    num_cpus: float
    # the agent's ObjectServer port (engine/object_channel.py): peers pull
    # this node's segments directly from here
    object_port: int = 0
    # host RAM in GiB for the per-node planner's memory fit check
    # (0 = unknown: the planner then fits on CPUs alone)
    memory_gb: float = 0.0
    # agent process pid (0 = unknown/old agent): on a reconnect the driver
    # uses it to tell a same-process link blip (segments survived — re-point
    # their locations) from a BOUNCED agent process (its stale-segment
    # janitor reclaimed the old pid's segments — leave locations on the
    # dead link so consumers reconstruct instead of fetching ghosts)
    pid: int = 0
    # handshake version gate: a peer built before versioning restores with
    # the attribute missing entirely (pickle state dicts carry only the
    # sender's fields), so the driver reads it as 0 and rejects cleanly
    protocol_version: int = PROTOCOL_VERSION


@dataclass
class StartWorker:
    worker_key: str
    stage_pickle: bytes
    meta_pickle: bytes
    env: dict[str, str]


@dataclass(frozen=True)
class RefSpec:
    """Location-aware object descriptor — what SubmitBatch carries instead
    of task payloads (reference ARCHITECTURE.md:70-81: the central loop
    moves refs; data moves producer→consumer). ``owner_node`` is '' when
    the driver's store owns the segment (the agent then dials the driver's
    control host at ``owner_port``)."""

    shm_name: str
    total_size: int
    num_buffers: int
    owner_node: str = ""
    owner_host: str = ""
    owner_port: int = 0


@dataclass
class SubmitBatch:
    worker_key: str
    batch_id: int
    refs: list  # list[RefSpec]
    # StageSpec.batch_timeout_s; 0 = no deadline. The AGENT's watchdog
    # enforces it (the driver cannot signal a process on another host):
    # an expired worker is killed and reported as WorkerDied, and the
    # driver's normal reap requeues the batch.
    timeout_s: float = 0.0
    # W3C trace context of the driver-side stage span: crosses the control
    # socket so the remote worker's spans parent onto the driver's trace
    # instead of starting a per-host fragment. '' = tracing off.
    traceparent: str = ""


@dataclass
class StopWorker:
    worker_key: str


@dataclass
class ReleaseObjects:
    """Driver → agent: these agent-owned segments have no remaining
    consumers — free them (the driver's StoreBudget.release for local
    segments, forwarded to the owner)."""

    names: list  # list[str]


@dataclass
class PrefetchObjects:
    """Driver → agent push-ahead: the router has decided the NEXT stage's
    batches will run on this node, so start pulling these segments from
    their owners NOW — into the agent's bounded prefetch cache — instead
    of waiting for the demand pull inside SubmitBatch input resolution.
    The transfer overlaps the node's current compute; a later SubmitBatch
    naming these segments resolves them as cache hits with ~zero wait.
    Purely advisory: a dropped or evicted prefetch degrades to the demand
    fetch, never to an error."""

    refs: list  # list[RefSpec]


@dataclass
class AgentStats:
    """Agent → driver (periodic, from the watchdog thread): object-plane
    transfer DELTAS since the last frame (stage_timer.OBJECT_PLANE_KEYS
    schema). Deltas, not totals, so the driver's per-node fold stays
    correct across link blips and reconnects."""

    object_plane: dict


@dataclass
class AgentReady:
    worker_key: str
    error: str | None = None


@dataclass
class AgentResult:
    worker_key: str
    batch_id: int
    # (shm_name, total_size, num_buffers) per output — the segments STAY in
    # the agent's store; consumers pull them over the object channel
    out_refs: list | None = None
    error: str | None = None
    process_time_s: float = 0.0
    deserialize_time_s: float = 0.0
    # the error is an INPUT LOSS (object-channel fetch failed: owner dead
    # or segment gone), not a user-code exception — the driver routes it to
    # lineage reconstruction / the node-death budget instead of burning the
    # batch's num_run_attempts
    input_loss: bool = False


@dataclass
class WorkerDied:
    """Agent → driver: a remote worker PROCESS died (the link is fine).

    The driver marks the worker dead so the orchestration loop's normal
    dead-worker reap requeues its in-flight batch — remote crashes recover
    through the same path as local ones."""

    worker_key: str


@dataclass
class Bye:
    pass


@dataclass
class HelloAck:
    """Driver's handshake reply: echoes the agent's session nonce (inside
    the MAC'd frame, so the binding cannot be forged) and contributes the
    driver's own nonce. The channel session id is the concatenation — BOTH
    sides contribute fresh randomness, so neither direction of a recorded
    session can be replayed into a later one. Also advertises the driver's
    ObjectServer port so agents can pull driver-owned segments."""

    agent_sid: bytes
    driver_object_port: int = 0
    # stable for one RemoteWorkerManager lifetime: agents use it to tell a
    # transient link blip (same run — keep output segments, the driver still
    # references them) from a driver restart (new run — the old outputs are
    # unreferenced dead weight)
    run_id: bytes = b""
    # the driver's protocol version: the agent verifies it in
    # connect_channel and refuses a skewed driver with a clear error
    protocol_version: int = PROTOCOL_VERSION


# Every dataclass that rides the control socket. This tuple IS the wire
# contract surface `lint --schema` snapshots (analysis/schema_check.py):
# add a frame here when you add one, and bump PROTOCOL_VERSION whenever
# any listed frame changes shape. Driver-local bookkeeping dataclasses
# (AgentLink) are deliberately absent — they never cross a process.
WIRE_FRAMES: tuple[type, ...] = (
    Hello, HelloAck, StartWorker, StopWorker, RefSpec, SubmitBatch,
    ReleaseObjects, PrefetchObjects, AgentStats, AgentReady, AgentResult,
    WorkerDied, Bye,
)


# -- framing ----------------------------------------------------------------


def _token() -> bytes:
    tok = os.environ.get(TOKEN_ENV, "")
    if not tok:
        raise RuntimeError(
            f"the cross-node engine plane requires {TOKEN_ENV} (shared "
            "cluster secret; frames are HMAC-authenticated before unpickling)"
        )
    return tok.encode()


MAX_FRAME_BYTES = 1 << 31


def _pack_meta(sid: bytes, direction: bytes, seq: int) -> bytes:
    return struct.pack(">H", len(sid)) + sid + direction + struct.pack(">Q", seq)


def _unpack_meta(meta: bytes) -> tuple[bytes, bytes, int]:
    if len(meta) < 2:
        raise ConnectionError("malformed frame meta")
    (n,) = struct.unpack(">H", meta[:2])
    if len(meta) != 2 + n + 3 + 8:
        raise ConnectionError("malformed frame meta")
    sid = meta[2 : 2 + n]
    direction = meta[2 + n : 5 + n]
    (seq,) = struct.unpack(">Q", meta[5 + n :])
    return sid, direction, seq


def send_msg(sock: socket.socket, msg: Any, token: bytes, *, meta: bytes = b"") -> int:
    """One MAC'd frame: [meta_len u16][meta][cloudpickle payload]. ``meta``
    carries freshness fields (session id, direction, sequence) OUTSIDE the
    pickle so the receiver verifies them before deserializing anything."""
    payload = cloudpickle.dumps(msg)
    body = struct.pack(">H", len(meta)) + meta + payload
    if len(body) > MAX_FRAME_BYTES:
        # enforce the receiver's cap at the SENDER: an oversized frame must
        # fail as one batch error, not sever the link when the peer rejects
        raise ValueError(
            f"frame of {len(body)} bytes exceeds the plane's "
            f"{MAX_FRAME_BYTES}-byte cap; shrink the stage batch size"
        )
    mac = hmac.new(token, body, hashlib.sha256).digest()
    header = _MAGIC + struct.pack(">Q", len(body)) + mac
    sock.sendall(header + body)
    return len(header) + len(body)


def send_frame(
    sock: socket.socket, token: bytes, sid: bytes, direction: bytes, seq: int, msg: Any
) -> int:
    return send_msg(sock, msg, token, meta=_pack_meta(sid, direction, seq))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def recv_msg_raw(
    sock: socket.socket, token: bytes, *, max_bytes: int = MAX_FRAME_BYTES
) -> tuple[bytes, bytes]:
    """MAC-verified (meta, pickled_payload) WITHOUT deserializing the
    payload — freshness checks must gate cloudpickle.loads, not follow it."""
    header = _recv_exact(sock, 4 + 8 + 32)
    if header[:4] != _MAGIC:
        raise ConnectionError("bad frame magic")
    (length,) = struct.unpack(">Q", header[4:12])
    if length > max_bytes:
        raise ConnectionError(f"frame too large: {length}")
    mac = header[12:44]
    body = _recv_exact(sock, length)
    want = hmac.new(token, body, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        raise ConnectionError("frame failed authentication")
    if len(body) < 2:
        raise ConnectionError("malformed frame body")
    (n,) = struct.unpack(">H", body[:2])
    if len(body) < 2 + n:
        raise ConnectionError("malformed frame meta length")
    return body[2 : 2 + n], body[2 + n :]


def recv_msg(sock: socket.socket, token: bytes, *, max_bytes: int = MAX_FRAME_BYTES) -> Any:
    _, payload = recv_msg_raw(sock, token, max_bytes=max_bytes)
    return cloudpickle.loads(payload)


def recv_frame(
    sock: socket.socket, token: bytes, *, max_bytes: int = MAX_FRAME_BYTES
) -> tuple[bytes, bytes, int, Any]:
    meta, payload = recv_msg_raw(sock, token, max_bytes=max_bytes)
    sid, direction, seq = _unpack_meta(meta)
    return sid, direction, seq, cloudpickle.loads(payload)


class SecureChannel:
    """Replay-bound framing over one connection.

    The HMAC alone authenticates bytes but not freshness or direction: an
    on-path recorder could replay a StartWorker/SubmitBatch frame verbatim
    and re-execute its cloudpickle payload. Every frame therefore carries
    ``(session_id, direction, sequence)`` INSIDE the MAC'd payload: the
    session id is random per agent connection (a replayed frame from an
    old session cannot match a new session's id), the per-direction
    sequence must advance exactly by one (an in-session replay or
    reordering drops the link), and the direction tag stops reflecting a
    peer's own frames back at it. The freshness fields ride a fixed
    header INSIDE the MAC'd frame but OUTSIDE the pickled payload, so a
    stale or reflected frame is rejected BEFORE any object is
    deserialized (ADVICE r4)."""

    A2D = b"a2d"  # agent -> driver
    D2A = b"d2a"  # driver -> agent

    def __init__(
        self,
        sock: socket.socket,
        token: bytes,
        sid: bytes,
        send_dir: bytes,
        recv_dir: bytes,
        *,
        send_seq_start: int = 0,
        recv_seq_start: int = 0,
    ) -> None:
        self.sock = sock
        self._token = token
        self.sid = sid
        self._send_dir = send_dir
        self._recv_dir = recv_dir
        self._send_seq = send_seq_start
        self._recv_seq = recv_seq_start
        self._lock = threading.Lock()
        # control-plane byte accounting: with the P2P object channel these
        # must stay O(refs) regardless of data volume (tests assert it)
        self.bytes_sent = 0
        self.bytes_received = 0

    def send(self, msg: Any) -> None:
        # kind=error: the control link drops mid-send (InjectedFault is a
        # ConnectionError, so the agent/driver reconnect paths engage)
        chaos.fire(chaos.SITE_REMOTE_PLANE_SEND)
        with self._lock:
            self.bytes_sent += send_frame(
                self.sock, self._token, self.sid, self._send_dir, self._send_seq, msg
            )
            self._send_seq += 1

    def recv(self, *, max_bytes: int = MAX_FRAME_BYTES) -> Any:
        chaos.fire(chaos.SITE_REMOTE_PLANE_RECV)  # kind=error: link reset
        meta, payload = recv_msg_raw(self.sock, self._token, max_bytes=max_bytes)
        self.bytes_received += len(meta) + len(payload) + 44
        sid, direction, seq = _unpack_meta(meta)
        # freshness gates deserialization: a replayed/cross-session frame is
        # rejected before its payload objects are ever reconstructed
        if sid != self.sid:
            raise ConnectionError("frame from a different session (replay?)")
        if direction != self._recv_dir:
            raise ConnectionError("frame direction mismatch (reflection?)")
        if seq != self._recv_seq:
            raise ConnectionError(
                f"frame out of order: got seq {seq}, expected {self._recv_seq} (replay?)"
            )
        self._recv_seq += 1
        return cloudpickle.loads(payload)


def accept_channel(
    sock: socket.socket, token: bytes, *, object_port: int = 0, run_id: bytes = b""
) -> tuple["SecureChannel", Any]:
    """Driver side of the handshake: read the agent's bootstrap frame,
    reply with the driver's own nonce (HelloAck, binding the agent's), and
    return (channel over the COMBINED session id, hello_msg). A recorded
    agent session replayed wholesale dies here: the driver's fresh nonce
    changes the combined id, so every post-handshake replayed frame is
    rejected."""
    meta, payload = recv_msg_raw(sock, token)
    agent_sid, direction, seq = _unpack_meta(meta)
    if direction != SecureChannel.A2D or seq != 0:
        raise ConnectionError("bad channel bootstrap frame")
    msg = cloudpickle.loads(payload)
    driver_sid = os.urandom(16)
    send_frame(
        sock, token, driver_sid, SecureChannel.D2A, 0,
        HelloAck(agent_sid, driver_object_port=object_port, run_id=run_id),
    )
    chan = SecureChannel(
        sock,
        token,
        agent_sid + driver_sid,
        SecureChannel.D2A,
        SecureChannel.A2D,
        send_seq_start=1,
        recv_seq_start=1,
    )
    return chan, msg


def connect_channel(
    sock: socket.socket, token: bytes, hello: Any
) -> tuple["SecureChannel", "HelloAck"]:
    """Agent side of the handshake: send the bootstrap Hello under a fresh
    nonce, verify the driver's ack binds it, and return (channel over the
    combined session id, the driver's ack)."""
    agent_sid = os.urandom(16)
    send_frame(sock, token, agent_sid, SecureChannel.A2D, 0, hello)
    meta, payload = recv_msg_raw(sock, token)
    driver_sid, direction, seq = _unpack_meta(meta)
    if direction != SecureChannel.D2A or seq != 0:
        raise ConnectionError("bad handshake ack from driver")
    ack = cloudpickle.loads(payload)
    if not isinstance(ack, HelloAck) or ack.agent_sid != agent_sid:
        raise ConnectionError("bad handshake ack from driver")
    # version gate BEFORE any post-handshake frame: a skewed driver must
    # fail here, at connect, with a message naming both versions — never
    # as a misdecoded StartWorker three frames later
    ack_version = frame_version(ack)
    if ack_version != PROTOCOL_VERSION:
        raise ProtocolSkewError(skew_error(ack_version, peer="driver"))
    chan = SecureChannel(
        sock,
        token,
        agent_sid + driver_sid,
        SecureChannel.A2D,
        SecureChannel.D2A,
        send_seq_start=1,
        recv_seq_start=1,
    )
    return chan, ack


# -- driver side ------------------------------------------------------------


class _RemoteProc:
    """Stands in for mp.Process in WorkerHandle: liveness = agent link AND
    the worker process on the agent (WorkerDied marks the latter)."""

    exitcode = "remote"  # runner logs this; remote exit codes stay remote

    def __init__(self, agent: "AgentLink", worker_key: str) -> None:
        self._agent = agent
        self._key = worker_key

    def is_alive(self) -> bool:
        return self._agent.alive and self._key not in self._agent.dead_workers

    def join(self, timeout: float | None = None) -> None:  # noqa: ARG002
        return

    def terminate(self) -> None:
        return


class _RemoteInQ:
    """Stands in for the worker's mp in-queue.

    ``put`` only ENQUEUES — materialization, pickling and the socket send
    happen on the manager's sender thread, so a large batch or a slow agent
    link never stalls the orchestration loop (the local path's mp.Queue has
    the same non-blocking property via its feeder thread)."""

    def __init__(self, mgr: "RemoteWorkerManager", agent: "AgentLink", worker_key: str) -> None:
        self._mgr = mgr
        self._agent = agent
        self._key = worker_key

    def put(self, msg: Any) -> None:
        from cosmos_curate_tpu.engine.worker import ProcessMsg, ShutdownMsg

        if isinstance(msg, (ShutdownMsg, ProcessMsg)):
            self._mgr.enqueue_send(self._agent, self._key, msg)
            return
        raise TypeError(f"unexpected message for remote worker: {type(msg)}")


@dataclass
class AgentLink:
    node_id: str
    num_cpus: float
    sock: socket.socket
    token: bytes
    memory_gb: float = 0.0
    chan: "SecureChannel | None" = None
    alive: bool = True
    # the agent's ObjectServer endpoint (peer IP from the control socket +
    # the Hello's object_port): where this node's segments are pulled from
    object_addr: tuple = ("", 0)
    # worker_key -> cpu cost; accounting is in CPU units, matching the
    # autoscaler's per-worker resources.cpus
    worker_costs: dict = field(default_factory=dict)
    dead_workers: set = field(default_factory=set)
    # failure-detector state: agent process pid (Hello), when the last
    # frame arrived (any frame counts — results ARE liveness), and whether
    # this link's death was already surfaced as a node event (one event
    # per link, however many paths notice the death)
    pid: int = 0
    last_seen: float = field(default_factory=time.monotonic)
    death_recorded: bool = False

    @property
    def cpus_used(self) -> float:
        return sum(self.worker_costs.values())

    def send(self, msg: Any) -> None:
        if self.chan is None:
            return
        try:
            self.chan.send(msg)
        except OSError:
            self.alive = False


class RemoteWorkerManager:
    """Driver-side registry of connected node agents.

    ``results_q`` receives ReadyMsg/ResultMsg exactly as local pools emit
    them; remote outputs are put() into the driver's object store first, so
    downstream stages cannot tell where a batch ran."""

    def __init__(self, port: int, results_q, *, local_cpu_budget: float) -> None:
        from cosmos_curate_tpu.engine.object_channel import ObjectServer

        self.token = _token()
        self.results_q = results_q
        self.local_cpu_budget = local_cpu_budget
        self.local_cpus_used = 0.0  # all pools' locally placed workers (cpu units)
        self.agents: list[AgentLink] = []
        self._lock = threading.Lock()
        # P2P object plane: the driver serves ITS segments from here, and
        # tracks which agent owns every remote segment (shm_name -> link)
        self.object_server = ObjectServer(self.token)
        self._locations: dict[str, AgentLink] = {}
        # shm_name -> EVERY node a push-ahead copy was sent to (a replan
        # can redirect a stage mid-run, pushing the same segment to a
        # second target): release must purge every target's prefetch
        # cache, or never-adopted copies sit in /dev/shm until cap
        # eviction (bounded; cleared wholesale past the cap)
        self._pushed_to: dict[str, list[AgentLink]] = {}
        # releases addressed to a currently-dead link wait here (node_id ->
        # segment names) and flush when that node rejoins — a transient blip
        # must not leak the agent's segments for the rest of the run
        self._pending_releases: dict[str, list] = {}
        # failure detector: per-agent heartbeat deadline (the agent's
        # watchdog ships an AgentStats frame — empty deltas included —
        # every heartbeat_s; see remote_agent._watchdog). Newly-declared
        # deaths queue here for the runner's live-replan poll.
        self.heartbeat_s = float(os.environ.get(HEARTBEAT_S_ENV, str(DEFAULT_HEARTBEAT_S)))
        self.heartbeat_misses = max(
            1, int(os.environ.get(HEARTBEAT_MISSES_ENV, str(DEFAULT_HEARTBEAT_MISSES)))
        )
        self._node_deaths: list[dict] = []
        self.run_id = os.urandom(16)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # a restarted driver must rebind the well-known port: SO_REUSEADDR
        # covers TIME_WAIT, and a short retry covers the window where a
        # predecessor's accepted connections are still tearing down
        # (agents keep dialing, so seconds of delay cost nothing)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        import errno

        deadline = time.monotonic() + 20.0
        while True:
            try:
                self._server.bind(("0.0.0.0", port))
                break
            except OSError as e:
                # only the predecessor-teardown race is transient; EACCES
                # etc. are deterministic and must surface immediately
                if e.errno != errno.EADDRINUSE or time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self._server.listen(8)
        self._closed = False
        # async sender: materialize+pickle+send off the orchestration loop
        import queue as _queue

        self._send_q: "_queue.Queue" = _queue.Queue()
        threading.Thread(target=self._sender_loop, daemon=True).start()
        threading.Thread(target=self._accept_loop, daemon=True).start()
        logger.info("engine driver listening for node agents on :%d", port)

    def enqueue_send(self, agent: AgentLink, worker_key: str, msg) -> None:
        self._send_q.put((agent, worker_key, msg))

    def _sender_loop(self) -> None:
        import queue as _queue

        from cosmos_curate_tpu.engine.worker import ProcessMsg, ShutdownMsg

        while not self._closed:
            try:
                agent, key, msg = self._send_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            if isinstance(msg, ShutdownMsg):
                agent.send(StopWorker(key))
                with self._lock:
                    agent.worker_costs.pop(key, None)
                continue
            if isinstance(msg, (ReleaseObjects, PrefetchObjects)):
                agent.send(msg)
                continue
            if not isinstance(msg, ProcessMsg):
                continue
            # refs only — no payloads on the driver socket. The consumer
            # agent pulls each segment straight from its owner (this node's
            # ObjectServer, or a peer agent's) over the object channel.
            agent.send(
                SubmitBatch(
                    key, msg.batch_id, [self._spec_for(r) for r in msg.refs],
                    timeout_s=msg.timeout_s,
                    traceparent=getattr(msg, "traceparent", ""),
                )
            )

    def _spec_for(self, ref) -> RefSpec:
        with self._lock:
            link = self._locations.get(ref.shm_name)
        if link is None:  # driver-owned: agents dial the control host
            return RefSpec(
                ref.shm_name, ref.total_size, ref.num_buffers,
                owner_node="", owner_host="", owner_port=self.object_server.port,
            )
        return RefSpec(
            ref.shm_name, ref.total_size, ref.num_buffers,
            owner_node=link.node_id,
            owner_host=link.object_addr[0],
            owner_port=link.object_addr[1],
        )

    # -- connection handling -------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, addr = self._server.accept()
            except OSError:
                return
            if self._closed:
                # close() does not wake a thread already blocked in accept()
                # — the kernel listener stays alive until the NEXT dial, and
                # that dial is returned here after shutdown began. Serving
                # it would park the agent on a dead driver's socket (it
                # blocks in recv instead of redialing the successor), so
                # drop it: the agent's connect loop retries and reaches the
                # live driver.
                try:
                    sock.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._serve_agent, args=(sock, addr), daemon=True
            ).start()

    def _serve_agent(self, sock: socket.socket, addr) -> None:
        try:
            chan, hello = accept_channel(
                sock, self.token,
                object_port=self.object_server.port, run_id=self.run_id,
            )
        except (ConnectionError, OSError) as e:
            logger.warning("rejected agent connection from %s: %s", addr, e)
            sock.close()
            return
        if not isinstance(hello, Hello):
            sock.close()
            return
        hello_version = frame_version(hello)
        if hello_version != PROTOCOL_VERSION:
            # reject at connect: the HelloAck already carried the driver's
            # version (sent in accept_channel), so the agent's own gate in
            # connect_channel raises the same clear error on its side
            logger.warning(
                "rejected agent %s from %s: %s",
                hello.node_id, addr, skew_error(hello_version, peer="agent"),
            )
            sock.close()
            return
        link = AgentLink(
            hello.node_id, hello.num_cpus, sock, self.token, chan=chan,
            memory_gb=getattr(hello, "memory_gb", 0.0),
            object_addr=(addr[0], hello.object_port),
            pid=getattr(hello, "pid", 0),
        )
        # Hello dedup: links are keyed by node_id — an agent that bounced
        # (or healed from a partition) before the driver noticed must not
        # appear as TWO NodeBudgets or leave the stale link reachable from
        # the sender loop. The stale link is declared dead (quarantined,
        # one node event) and dropped from the registry; the reconnecting
        # agent joins as a FRESH node.
        with self._lock:
            stale_links = [a for a in self.agents if a.node_id == hello.node_id]
        for old in stale_links:
            self.note_agent_dead(old, reason="superseded by a reconnecting agent")
        with self._lock:
            self.agents = [a for a in self.agents if a.node_id != hello.node_id]
            self.agents.append(link)
            # REJOIN of the SAME agent process (link blip): the node kept
            # its segments (same run_id, same pid), so re-point their
            # location entries at the live link. A BOUNCED process (new
            # pid) reclaimed the old pid's segments at startup — its
            # entries stay on the dead link, so consumers see a dead owner
            # and reconstruct instead of fetching ghosts.
            for name, old in list(self._locations.items()):
                if (
                    old.node_id == hello.node_id
                    and old is not link
                    and link.pid
                    and old.pid == link.pid
                ):
                    self._locations[name] = link
            stale = self._pending_releases.pop(hello.node_id, [])
        if stale:
            with self._lock:
                for name in stale:
                    self._locations.pop(name, None)
            self._send_q.put((link, "", ReleaseObjects(stale)))
        logger.info(
            "node agent joined: %s (%.0f cpus) from %s", hello.node_id, hello.num_cpus, addr
        )
        try:
            while True:
                msg = chan.recv()
                link.last_seen = time.monotonic()  # any frame is a heartbeat
                self._on_agent_msg(link, msg)
        except (ConnectionError, OSError):
            link.alive = False
            logger.warning("node agent %s disconnected", link.node_id)

    def _on_agent_msg(self, link: AgentLink, msg: Any) -> None:
        from cosmos_curate_tpu.engine import object_store
        from cosmos_curate_tpu.engine.worker import ReadyMsg, ResultMsg

        if isinstance(msg, AgentStats):
            # fold the agent's object-plane deltas under its node id — the
            # driver is the only process with a metrics exporter, so the
            # pipeline_object_plane_* series covers every node's traffic
            from cosmos_curate_tpu.observability.stage_timer import (
                record_node_object_plane,
            )

            if msg.object_plane:
                record_node_object_plane(link.node_id, msg.object_plane)
        elif isinstance(msg, WorkerDied):
            with self._lock:
                link.dead_workers.add(msg.worker_key)
                link.worker_costs.pop(msg.worker_key, None)
        elif isinstance(msg, AgentReady):
            self.results_q.put(ReadyMsg(worker_id=msg.worker_key, error=msg.error))
        elif isinstance(msg, AgentResult):
            if msg.error is not None:
                self.results_q.put(
                    ResultMsg(
                        msg.batch_id,
                        error=msg.error,
                        process_time_s=msg.process_time_s,
                        worker_id=msg.worker_key,
                        input_loss=getattr(msg, "input_loss", False),
                    )
                )
                return
            # outputs stay in the AGENT's store: register their location and
            # hand the orchestration loop ordinary refs — data only moves
            # when (and to where) a consumer needs it
            refs = []
            with self._lock:
                for name, size, nbuf in msg.out_refs or []:
                    refs.append(object_store.ObjectRef(name, size, nbuf))
                    self._locations[name] = link
            self.results_q.put(
                ResultMsg(
                    msg.batch_id,
                    out_refs=refs,
                    process_time_s=msg.process_time_s,
                    deserialize_time_s=msg.deserialize_time_s,
                    worker_id=msg.worker_key,
                )
            )

    def push_ahead(self, refs: list, node_id: str) -> int:
        """Ask ``node_id``'s agent to prefetch these segments from their
        owners (router push-ahead: the consumer node starts pulling while
        the producer's compute continues). Segments the target already
        owns are skipped. Returns how many were requested; 0 when the
        target is unknown/dead (the demand pull still works)."""
        with self._lock:
            link = next(
                (a for a in self.agents if a.alive and a.node_id == node_id), None
            )
        if link is None:
            return 0
        specs = [
            self._spec_for(r) for r in refs if self.owner_node(r) != node_id
        ]
        if specs:
            with self._lock:
                if len(self._pushed_to) > 65536:
                    self._pushed_to.clear()  # worst case: one missed purge
                for s in specs:
                    targets = self._pushed_to.setdefault(s.shm_name, [])
                    if link not in targets:
                        targets.append(link)
            self._send_q.put((link, "", PrefetchObjects(specs)))
        return len(specs)

    def node_budgets(self) -> list:
        """Live agents as ``(node_id, num_cpus, memory_gb)`` for the
        per-node planner (the driver's own NodeBudget is the runner's to
        build)."""
        with self._lock:
            return [
                (a.node_id, a.num_cpus, a.memory_gb)
                for a in self.agents
                if a.alive
            ]

    def heartbeat_ages(self, now: float | None = None) -> dict:
        """node_id -> heartbeat freshness for every registered link — the
        live-status snapshot's node-health section (the anomaly detector
        flags ``heartbeat_degraded`` from these ages BEFORE the failure
        detector's declare-dead deadline fires)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {
                a.node_id: {
                    "heartbeat_age_s": round(max(0.0, now - a.last_seen), 3),
                    "alive": bool(a.alive),
                    "workers": len(a.worker_costs),
                }
                for a in self.agents
            }

    # -- failure detector ----------------------------------------------
    def note_agent_dead(self, link: AgentLink, *, reason: str = "declared dead") -> bool:
        """Declare one agent dead: quarantine the link (socket closed, so a
        hung/partitioned recv thread unblocks and a late frame from the old
        session can never resurrect stale state), record ONE node event for
        the runner's live-replan poll, and let the normal dead-worker reap
        fail its in-flight SubmitBatches as worker deaths (``_RemoteProc.
        is_alive`` keys on ``link.alive``). Idempotent per link; returns
        True the first time."""
        with self._lock:
            if link.death_recorded:
                link.alive = False
                return False
            link.death_recorded = True
            link.alive = False
            self._node_deaths.append(
                {
                    "node": link.node_id,
                    "reason": reason,
                    "at": time.time(),
                    "workers_lost": len(link.worker_costs),
                }
            )
        logger.warning("node %s declared dead: %s", link.node_id, reason)
        try:
            if link.sock is not None:
                link.sock.close()
        except OSError:
            pass
        return True

    def check_heartbeats(self, now: float | None = None) -> None:
        """Sweep the registry: links past their heartbeat deadline are
        declared dead; links some send/recv path already marked ``alive =
        False`` get their (single) node event recorded here. Cheap — a
        float compare per agent — so the runner calls it every loop tick."""
        now = time.monotonic() if now is None else now
        deadline = self.heartbeat_s * self.heartbeat_misses
        with self._lock:
            links = list(self.agents)
        for link in links:
            if not link.alive:
                if not link.death_recorded:
                    self.note_agent_dead(link, reason="link lost")
                continue
            if self.heartbeat_s > 0 and now - link.last_seen > deadline:
                self.note_agent_dead(
                    link,
                    reason=(
                        f"missed {self.heartbeat_misses} heartbeats "
                        f"(silent {now - link.last_seen:.1f}s > {deadline:.1f}s)"
                    ),
                )

    def poll_node_deaths(self) -> list[dict]:
        """Sweep heartbeats, then drain newly-recorded node-death events
        (the runner replans immediately on a non-empty result)."""
        self.check_heartbeats()
        with self._lock:
            out, self._node_deaths = self._node_deaths, []
        return out

    def owner_dead(self, ref) -> bool:
        """True when the segment's owning agent is registered but dead —
        the signal that a failed fetch is a NODE loss (reconstruct via
        lineage) rather than a transient error (retry)."""
        with self._lock:
            link = self._locations.get(ref.shm_name)
        return link is not None and not link.alive

    def node_of(self, name: str) -> str:
        """The node id registered as owning segment ``name`` (dead links
        included — DLQ entries stamp the LOST node); '' when unknown or
        driver-owned."""
        with self._lock:
            link = self._locations.get(name)
        return link.node_id if link is not None else ""

    def place_for(self, node_id: str, cpu_cost: float) -> "AgentLink | None":
        """Planner-directed placement: ``node_id == ''`` places locally;
        otherwise the named agent, falling back to the legacy least-loaded
        ``place`` when that agent is gone (an allocation plan must not
        wedge worker startup on a node that just died)."""
        if node_id == "":
            return None  # local; start_worker books note_local_start
        with self._lock:
            link = next(
                (a for a in self.agents if a.alive and a.node_id == node_id), None
            )
        if link is not None:
            return link
        return self.place(cpu_cost)

    # -- P2P data plane -------------------------------------------------
    def owner_node(self, ref) -> str:
        """'' when the driver's store owns the segment, else the agent's
        node id (dispatch affinity keys on this)."""
        with self._lock:
            link = self._locations.get(ref.shm_name)
        return link.node_id if link is not None else ""

    def localize(self, ref):
        """Pull an agent-owned segment into the DRIVER's store (a local
        consumer needs the bytes); returns the local ref. Driver-owned refs
        return unchanged."""
        from cosmos_curate_tpu.engine import object_channel

        with self._lock:
            link = self._locations.get(ref.shm_name)
        if link is None:
            return ref
        return object_channel.fetch_object(link.object_addr, self.token, ref)

    def fetch_value_if_remote(self, ref):
        """Materialize a ref wherever it lives (final-sink path): remote
        refs stream from their owner without creating a local segment."""
        from cosmos_curate_tpu.engine import object_channel, object_store

        with self._lock:
            link = self._locations.get(ref.shm_name)
        if link is None:
            return object_store.get(ref)
        return object_channel.fetch_value(link.object_addr, self.token, ref)

    def release_data(self, ref) -> None:
        """Location-aware delete: local segments unlink here; agent-owned
        segments release at their owner (via the control link's sender
        thread — never the orchestration loop). A dead link's releases are
        parked and flushed when that node rejoins."""
        from cosmos_curate_tpu.engine import object_store

        with self._lock:
            link = self._locations.get(ref.shm_name)
            if link is not None and not link.alive:
                self._pending_releases.setdefault(link.node_id, []).append(
                    ref.shm_name
                )
                return
            self._locations.pop(ref.shm_name, None)
            pushed = self._pushed_to.pop(ref.shm_name, None) or []
        for target in pushed:
            if target is not link and target.alive:
                # a push-ahead target that never consumed its copy (the
                # batch was routed elsewhere, or a replan superseded the
                # target): purge its prefetch cache too — the name can
                # never be demanded again
                self._send_q.put((target, "", ReleaseObjects([ref.shm_name])))
        if link is None:
            object_store.delete(ref)
        else:
            self._send_q.put((link, "", ReleaseObjects([ref.shm_name])))

    # -- placement (all accounting in CPU units: a worker costs its
    # stage's resources.cpus, matching the autoscaler's budget math) ----
    def remote_cpus(self) -> float:
        with self._lock:
            return sum(a.num_cpus for a in self.agents if a.alive)

    def place(self, cpu_cost: float) -> AgentLink | None:
        """None = place locally. Local CPUs fill first (no network hop),
        then the least-loaded live agent with room for this worker."""
        cost = max(0.25, cpu_cost)  # zero-cost stages still occupy budget
        with self._lock:
            if self.local_cpus_used + cost <= self.local_cpu_budget + 1e-9:
                return None
            candidates = [
                a
                for a in self.agents
                if a.alive and a.cpus_used + cost <= a.num_cpus + 1e-9
            ]
            if not candidates:
                return None
            return min(candidates, key=lambda a: a.cpus_used)

    def note_local_start(self, cpu_cost: float) -> None:
        with self._lock:
            self.local_cpus_used += max(0.25, cpu_cost)

    def note_local_stop(self, cpu_cost: float) -> None:
        with self._lock:
            self.local_cpus_used = max(0.0, self.local_cpus_used - max(0.25, cpu_cost))

    def note_remote_gone(self, proc: _RemoteProc) -> None:
        with self._lock:
            proc._agent.worker_costs.pop(proc._key, None)
            proc._agent.dead_workers.discard(proc._key)

    def start_remote_worker(
        self,
        agent: AgentLink,
        worker_key: str,
        stage_pickle: bytes,
        meta_pickle: bytes,
        env: dict,
        *,
        cpu_cost: float = 1.0,
    ):
        with self._lock:
            agent.worker_costs[worker_key] = max(0.25, cpu_cost)
        agent.send(StartWorker(worker_key, stage_pickle, meta_pickle, env))
        return _RemoteInQ(self, agent, worker_key), _RemoteProc(agent, worker_key)

    def wait_for_agents(self, n: int, timeout_s: float) -> int:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                live = sum(1 for a in self.agents if a.alive)
            if live >= n:
                return live
            time.sleep(0.1)
        with self._lock:
            return sum(1 for a in self.agents if a.alive)

    def stats(self) -> dict:
        with self._lock:
            return {
                a.node_id: {
                    "cpus": a.num_cpus,
                    "workers": len(a.worker_costs),
                    "cpus_used": a.cpus_used,
                    # control-link bytes: O(refs), never O(data)
                    "ctrl_bytes_sent": a.chan.bytes_sent if a.chan else 0,
                    "ctrl_bytes_received": a.chan.bytes_received if a.chan else 0,
                }
                for a in self.agents
            }

    def shutdown(self, *, drain_s: float = 0.5) -> None:
        self._closed = True
        with self._lock:
            agents = list(self.agents)
        for a in agents:
            a.send(Bye())
        if agents and drain_s > 0:
            # agents answer Bye with a forced final AgentStats flush; keep
            # their sockets open long enough for the per-agent recv threads
            # to fold those last object-plane deltas — closing immediately
            # would systematically drop every run's tail-window transfers
            time.sleep(drain_s)
        for a in agents:
            if a.sock is not None:
                try:
                    a.sock.close()
                except OSError:
                    pass
        try:
            self._server.close()
        except OSError:
            pass
        self.object_server.close()


def maybe_create_manager(results_q, *, local_cpu_budget: float) -> RemoteWorkerManager | None:
    """Driver-side entry: active only when the env contract is present."""
    port = os.environ.get(DRIVER_PORT_ENV)
    if not port:
        return None
    mgr = RemoteWorkerManager(int(port), results_q, local_cpu_budget=local_cpu_budget)
    want = int(os.environ.get(WAIT_NODES_ENV, "0"))
    if want:
        got = mgr.wait_for_agents(want, float(os.environ.get(WAIT_S_ENV, "30")))
        logger.info("engine plane: %d/%d node agents connected", got, want)
    return mgr
