"""`cosmos-curate-tpu local …` — run pipelines on this host.

Equivalent of the reference's local CLI + pipeline entry
(cosmos_curate/client/local_cli/, pipelines/video/run_pipeline.py:51-101),
with the same dual invocation: flags or a YAML/JSON config file.
"""

from __future__ import annotations

import argparse
import json
import sys

# mirrors models/vlm/model.py VLM_FLAVORS (pinned by
# tests/models/test_vlm_engine.py::test_cli_choices_match_flavors)
CAPTION_MODEL_CHOICES = (
    "base",
    "qwen25vl-7b",
    "qwen2vl-2b",
    "qwen3moe-a3b-lm",
    "qwen3vl-moe-a3b",
    "qwen3moe-tiny-test",
    "qwen-chat-tiny-test",
    "tiny-test",
)


def register(sub: argparse._SubParsersAction) -> None:
    local = sub.add_parser("local", help="run pipelines on this host")
    lsub = local.add_subparsers(dest="subcommand", metavar="pipeline")

    hello = lsub.add_parser("hello", help="hello-world example pipeline")
    hello.set_defaults(func=_cmd_hello)

    split = lsub.add_parser("split", help="split-annotate videos into curated clips")
    split.add_argument("--input-path", required=False, default="", help="videos dir or config file")
    split.add_argument("--output-path", default="")
    split.add_argument("--config", default="", help="YAML/JSON config (alternative to flags)")
    split.add_argument("--limit", type=int, default=0)
    split.add_argument("--splitting-algorithm", choices=["fixed-stride", "transnetv2"], default="fixed-stride")
    split.add_argument("--fixed-stride-len-s", type=float, default=10.0)
    split.add_argument("--min-clip-len-s", type=float, default=2.0)
    split.add_argument("--multicam", action="store_true", help="input is <session>/<camera>.mp4 dirs")
    split.add_argument("--primary-camera", default="", help="primary camera filename stem")
    split.add_argument("--motion-filter", choices=["disable", "score-only", "enable"], default="disable")
    split.add_argument(
        "--motion-backend",
        choices=["auto", "mv", "frame-diff"],
        default="auto",
        help="motion estimator: codec motion vectors, frame differences, "
        "or auto (MVs with frame-diff fallback)",
    )
    split.add_argument("--aesthetic-threshold", type=float, default=None)
    split.add_argument(
        "--embedding-model",
        choices=["", "clip", "video", "video-512", "video-256", "iv2", "iv2-tiny-test"],
        default="",
    )
    split.add_argument(
        "--corpus-index",
        action="store_true",
        help="append clip embeddings to the persistent corpus index "
        "in-pipeline (consolidated at end of run)",
    )
    split.add_argument(
        "--index-path", default="", help="corpus index root (default <output>/index)"
    )
    split.add_argument(
        "--incremental-dedup",
        choices=["disable", "score-only", "enable"],
        default="disable",
        help="query the corpus index as clips flow; enable drops duplicates "
        "before captioning/writing",
    )
    split.add_argument("--dedup-eps", type=float, default=0.07)
    split.add_argument(
        "--dedup-nprobe", type=int, default=0,
        help="clusters probed per incremental-dedup query (0 = index default)",
    )
    split.add_argument("--captioning", action="store_true")
    # static list (kept in sync with VLM_FLAVORS by a test): importing the
    # model module here would pull jax into --help, which can hang when the
    # TPU relay is wedged
    split.add_argument(
        "--caption-model",
        default="base",
        choices=CAPTION_MODEL_CHOICES,
        help="VLM flavor for every caption-family stage",
    )
    split.add_argument("--enhance-captions", action="store_true")
    split.add_argument("--t5-embeddings", action="store_true")
    split.add_argument("--previews", action="store_true")
    split.add_argument("--tracking", action="store_true")
    split.add_argument("--tracking-annotated", action="store_true")
    split.add_argument("--per-event-captions", action="store_true")
    split.add_argument("--sr", action="store_true", help="super-resolve clips after transcode")
    split.add_argument("--sr-variant", choices=["diffusion", "srnet"], default="diffusion")
    split.add_argument("--sr-window-frames", type=int, default=128)
    split.add_argument("--sr-overlap-frames", type=int, default=64)
    split.add_argument("--sr-sp-size", type=int, default=1, help="sequence-parallel mesh size for SR")
    split.add_argument("--text-filter", choices=["disable", "score-only", "enable"], default="disable")
    split.add_argument("--semantic-filter", choices=["disable", "score-only", "enable"], default="disable")
    split.add_argument("--clip-chunk-size", type=int, default=64)
    split.add_argument("--sequential", action="store_true", help="run in-process (no engine)")
    split.add_argument(
        "--runner",
        choices=["auto", "sequential", "pipelined", "streaming", "map"],
        default="auto",
        help="execution backend: stage-overlapped thread pools (pipelined; "
        "the single-host default), streaming engine, in-process "
        "sequential, or barrier map over a process pool",
    )
    split.add_argument("--profile-cpu", action="store_true")
    split.add_argument("--profile-memory", action="store_true")
    split.add_argument("--tracing", action="store_true")
    split.add_argument("--stage-save-rate", type=float, default=0.0)
    split.set_defaults(func=_cmd_split)

    av = lsub.add_parser("av", help="multi-camera AV pipelines")
    av.add_argument(
        "subcommand2",
        choices=["ingest", "split", "caption", "trajectory", "annotate", "package", "shard"],
        metavar="step",
    )
    av.add_argument(
        "--caption-variants",
        default="av",
        help="comma-separated prompt variants; first is the primary caption",
    )
    av.add_argument("--input-path", required=True)
    av.add_argument("--output-path", required=True)
    av.add_argument("--db-path", default="")
    av.add_argument("--clip-len-s", type=float, default=10.0)
    av.add_argument("--min-clip-len-s", type=float, default=None)
    av.add_argument("--limit", type=int, default=0)
    av.add_argument("--sequential", action="store_true")
    av.set_defaults(func=_cmd_av)

    image = lsub.add_parser("image-annotate", help="curate still images")
    image.add_argument("--input-path", required=True)
    image.add_argument("--output-path", required=True)
    image.add_argument("--limit", type=int, default=0)
    image.add_argument("--aesthetic-threshold", type=float, default=None)
    image.add_argument("--captioning", action="store_true")
    image.add_argument(
        "--semantic-filter", choices=["disable", "score-only", "enable"], default="disable"
    )
    image.add_argument("--semantic-filter-prompt", default=None)
    image.add_argument(
        "--classifier-labels", default="", help="comma-separated label set; empty = off"
    )
    image.add_argument(
        "--api-caption-url", default="", help="OpenAI-compatible endpoint for captioning"
    )
    image.add_argument("--api-caption-model", default="default")
    image.add_argument(
        "--api-caption-key",
        default="",
        help="bearer token for the caption endpoint (or set CURATE_API_KEY)",
    )
    image.add_argument("--sequential", action="store_true")
    image.set_defaults(func=_cmd_image)

    dedup = lsub.add_parser("dedup", help="semantic dedup over clip embeddings")
    dedup.add_argument("--input-path", required=True, help="split output root")
    dedup.add_argument("--output-path", default="")
    dedup.add_argument("--embedding-model", default="")
    dedup.add_argument("--eps", type=float, default=0.07)
    dedup.add_argument("--n-clusters", type=int, default=0)
    dedup.add_argument(
        "--no-index",
        action="store_true",
        help="force full re-clustering even when a corpus index exists",
    )
    dedup.add_argument(
        "--index-path", default="", help="corpus index root (default <input>/index)"
    )
    dedup.add_argument("--nprobe", type=int, default=0, help="0 = index default")
    dedup.set_defaults(func=_cmd_dedup)

    shard = lsub.add_parser("shard", help="pack curated clips into webdataset tars")
    shard.add_argument("--input-path", required=True, help="split output root")
    shard.add_argument("--output-path", required=True)
    shard.add_argument("--dedup-csv", default="")
    shard.add_argument("--max-samples-per-shard", type=int, default=512)
    shard.set_defaults(func=_cmd_shard)

    merge = lsub.add_parser(
        "merge-summaries",
        help="combine per-node summary-node*.json into one summary-merged.json",
    )
    merge.add_argument("--output-path", required=True, help="pipeline output root")
    merge.set_defaults(func=_cmd_merge_summaries)

    local.set_defaults(func=lambda args: (local.print_help(), 2)[1])


def _cmd_merge_summaries(args: argparse.Namespace) -> int:
    import json

    from cosmos_curate_tpu.utils.summary import merge_node_summaries

    merged = merge_node_summaries(args.output_path)
    if merged is None:
        print(f"no summaries found under {args.output_path}")
        return 1
    # this runs once per multi-node run, after all nodes finished — also the
    # right moment for artifact delivery's driver phase (manifest merge,
    # chunk verify/reassembly)
    from cosmos_curate_tpu.observability.artifacts import finalize_delivery

    report = finalize_delivery(args.output_path)
    if report.files or report.errors:
        print(
            f"artifacts: {report.files} files from nodes {report.nodes}"
            + (f"; ERRORS: {report.errors}" if report.errors else "")
        )
    # multi-node flight recorder: every node's spans are collected now, so
    # the merged run report (one trace across hosts) is built here.
    # require_spans: an untraced run must not gain an empty report; the
    # guard matches run_split's — a recorder failure never fails the merge.
    try:
        from cosmos_curate_tpu.observability.flight_recorder import (
            load_node_stats,
            load_report,
            report_path,
            write_run_report,
        )

        # runner-sourced sections (dead-letter counts, stage times,
        # dispatch/flow aggregates) live in the ORIGINAL drivers' memory,
        # not this process: source them from the per-node sidecars every
        # multi-node run_split finalize writes, falling back to a
        # previously-written report (single-node re-merge) — never
        # overwrite them with empties
        prior = load_node_stats(args.output_path) or load_report(
            report_path(args.output_path)
        )
        run_report = write_run_report(args.output_path, prior=prior, require_spans=True)
        if run_report["span_count"]:
            print(
                f"run report: {run_report['span_count']} spans, "
                f"{len(run_report['trace_ids'])} trace(s) -> "
                f"{run_report['report_path']}"
            )
    except Exception as e:  # noqa: BLE001 - report is best-effort here
        print(f"flight recorder failed (merge unaffected): {e}", file=sys.stderr)
    print(json.dumps(merged, indent=2))
    return 0


def _cmd_hello(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.pipelines.examples.hello_world import run_hello_world

    for task in run_hello_world():
        print(f"{task.text!r} score={task.score:.4f} device={task.device}")
    return 0


def _cmd_av(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.pipelines.av import pipeline as av

    variants = [v.strip() for v in args.caption_variants.split(",") if v.strip()]
    pargs = av.AVPipelineArgs(
        input_path=args.input_path,
        output_path=args.output_path,
        db_path=args.db_path,
        clip_len_s=args.clip_len_s,
        min_clip_len_s=args.min_clip_len_s,
        caption_prompt_variant=variants[0] if variants else "av",
        extra_caption_variants=tuple(variants[1:]),
        limit=args.limit,
    )
    step = args.subcommand2
    if step == "ingest":
        summary = av.run_av_ingest(pargs)
    elif step == "split":
        summary = av.run_av_split(
            pargs, runner=SequentialRunner() if args.sequential else None
        )
    elif step == "caption":
        summary = av.run_av_caption(pargs)
    elif step == "trajectory":
        from cosmos_curate_tpu.pipelines.av.trajectory import run_av_trajectory

        summary = run_av_trajectory(pargs)
    elif step == "annotate":
        summary = av.run_av_annotate(pargs)
    elif step == "package":
        summary = av.run_av_package(pargs)
    else:
        summary = av.run_av_shard(pargs)
    print(json.dumps(summary, indent=2))
    return 0


def _cmd_image(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.pipelines.image.annotate import ImagePipelineArgs, run_image_annotate

    summary = run_image_annotate(
        ImagePipelineArgs(
            input_path=args.input_path,
            output_path=args.output_path,
            limit=args.limit,
            aesthetic_threshold=args.aesthetic_threshold,
            captioning=args.captioning,
            semantic_filter=args.semantic_filter,
            semantic_filter_prompt=args.semantic_filter_prompt,
            classifier_labels=tuple(
                s.strip() for s in args.classifier_labels.split(",") if s.strip()
            ),
            api_caption_url=args.api_caption_url,
            api_caption_model=args.api_caption_model,
            api_caption_key=args.api_caption_key,
        ),
        runner=SequentialRunner() if args.sequential else None,
    )
    print(json.dumps(summary, indent=2))
    return 0


def _cmd_dedup(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.pipelines.video.dedup import DedupPipelineArgs, run_dedup

    summary = run_dedup(
        DedupPipelineArgs(
            input_path=args.input_path,
            output_path=args.output_path,
            embedding_model=args.embedding_model,
            eps=args.eps,
            n_clusters=args.n_clusters,
            use_index=not args.no_index,
            index_path=args.index_path,
            nprobe=args.nprobe,
        )
    )
    print(json.dumps(summary, indent=2))
    return 0


def _cmd_shard(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.pipelines.video.shard import ShardPipelineArgs, run_shard

    summary = run_shard(
        ShardPipelineArgs(
            input_path=args.input_path,
            output_path=args.output_path,
            dedup_csv=args.dedup_csv,
            max_samples_per_shard=args.max_samples_per_shard,
        )
    )
    print(json.dumps(summary, indent=2))
    return 0


def _cmd_split(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.core.runner import SequentialRunner
    from cosmos_curate_tpu.pipelines.video.split import SplitPipelineArgs, run_split

    if args.config:
        from cosmos_curate_tpu.utils.config import load_pipeline_config

        pargs = load_pipeline_config(args.config, SplitPipelineArgs)
    else:
        if not args.input_path or not args.output_path:
            print("error: --input-path and --output-path (or --config) are required")
            return 2
        pargs = SplitPipelineArgs(
            input_path=args.input_path,
            output_path=args.output_path,
            limit=args.limit,
            splitting_algorithm=args.splitting_algorithm,
            fixed_stride_len_s=args.fixed_stride_len_s,
            min_clip_len_s=args.min_clip_len_s,
            multicam=args.multicam,
            primary_camera=args.primary_camera,
            motion_filter=args.motion_filter,
            motion_backend=args.motion_backend,
            aesthetic_threshold=args.aesthetic_threshold,
            embedding_model=args.embedding_model,
            corpus_index=args.corpus_index,
            index_path=args.index_path,
            incremental_dedup=args.incremental_dedup,
            dedup_eps=args.dedup_eps,
            dedup_nprobe=args.dedup_nprobe,
            captioning=args.captioning,
            caption_model=args.caption_model,
            enhance_captions=args.enhance_captions,
            t5_embeddings=args.t5_embeddings,
            previews=args.previews,
            tracking=args.tracking or args.tracking_annotated,  # annotated implies tracking
            tracking_annotated=args.tracking_annotated,
            per_event_captions=args.per_event_captions,
            text_filter=args.text_filter,
            semantic_filter=args.semantic_filter,
            sr=args.sr,
            sr_variant=args.sr_variant,
            sr_window_frames=args.sr_window_frames,
            sr_overlap_frames=args.sr_overlap_frames,
            sr_sp_size=args.sr_sp_size,
            clip_chunk_size=args.clip_chunk_size,
            profile_cpu=args.profile_cpu,
            profile_memory=args.profile_memory,
            tracing=args.tracing,
            stage_save_rate=args.stage_save_rate,
        )
    choice = getattr(args, "runner", "auto")
    if args.sequential:
        if choice not in ("auto", "sequential"):
            print(
                f"error: --sequential conflicts with --runner {choice}", file=sys.stderr
            )
            return 2
        choice = "sequential"
    if choice == "sequential":
        runner = SequentialRunner()
    elif choice == "pipelined":
        from cosmos_curate_tpu.core.pipelined_runner import PipelinedRunner

        # same poison-batch semantics as `auto` (default_runner) and the
        # streaming engine: exhausted batches dead-letter, the run continues
        runner = PipelinedRunner(raise_on_error=False)
    elif choice == "map":
        from cosmos_curate_tpu.core.map_runner import MapRunner

        runner = MapRunner()
    elif choice == "streaming":
        from cosmos_curate_tpu.engine.runner import StreamingRunner

        runner = StreamingRunner()
    else:
        runner = None  # run_split picks the default
    summary = run_split(pargs, runner=runner)
    print(json.dumps(summary, indent=2))
    return 0
