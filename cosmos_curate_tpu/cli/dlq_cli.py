"""`cosmos-curate-tpu dlq …` — inspect and re-run dead-lettered batches.

The streaming engine persists permanently-dropped batches (retry budget or
worker-death budget exhausted) to the dead-letter queue
(engine/dead_letter.py). This sub-app makes that lost work visible and
recoverable:

- ``dlq list``              — every entry, newest run first
- ``dlq show ENTRY``        — one entry's metadata + task summaries
- ``dlq requeue ENTRY``     — write the entry's tasks to a cloudpickle file
  (``--out``) for re-injection into a pipeline run, and stamp the entry as
  requeued. Library callers use ``DlqEntry.load_tasks()`` directly.

``ENTRY`` is ``<run_id>/<batch-dir>`` as printed by ``list`` (any unique
suffix works).
"""

from __future__ import annotations

import argparse
import json
import sys


def register(sub: argparse._SubParsersAction) -> None:
    dlq = sub.add_parser("dlq", help="inspect/re-run dead-lettered batches")
    dsub = dlq.add_subparsers(dest="subcommand", metavar="action")

    ls = dsub.add_parser("list", help="list dead-lettered batches")
    ls.add_argument("--dlq-dir", default=None, help="DLQ root (default: CURATE_DLQ_DIR)")
    ls.add_argument("--run-id", default=None, help="restrict to one run")
    ls.add_argument("--json", action="store_true", dest="as_json")
    ls.set_defaults(func=_cmd_list)

    show = dsub.add_parser("show", help="show one entry's metadata and tasks")
    show.add_argument("entry", help="<run_id>/<batch-dir> (unique suffix ok)")
    show.add_argument("--dlq-dir", default=None)
    show.set_defaults(func=_cmd_show)

    rq = dsub.add_parser(
        "requeue", help="export an entry's tasks for re-running and mark it requeued"
    )
    rq.add_argument("entry", help="<run_id>/<batch-dir> (unique suffix ok)")
    rq.add_argument("--dlq-dir", default=None)
    rq.add_argument(
        "--out",
        default="",
        help="write tasks to this cloudpickle file (default: <entry>/requeued-tasks.pkl)",
    )
    rq.set_defaults(func=_cmd_requeue)

    dlq.set_defaults(func=lambda args: (dlq.print_help(), 2)[1])


def _cmd_list(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.engine.dead_letter import list_entries

    entries = list_entries(args.dlq_dir, run_id=args.run_id)
    if args.as_json:
        print(json.dumps([{"entry": e.entry_id, **e.meta} for e in entries], indent=2))
        return 0
    if not entries:
        print("dead-letter queue is empty")
        return 0
    for e in entries:
        m = e.meta
        requeued = " [requeued]" if m.get("requeued_at") else ""
        trace = f" trace={m['trace_id']}" if m.get("trace_id") else ""
        # owner-loss drops name the dead node: "node died past budget"
        # reads differently from "batch is poison"
        lost = f" lost_node={m['lost_node']}" if m.get("lost_node") else ""
        print(
            f"{e.entry_id}: stage={m.get('stage')} tasks={m.get('num_tasks')} "
            f"attempts={m.get('attempts')} worker_deaths={m.get('worker_deaths')} "
            f"reason={m.get('reason', '')!r}{lost}{trace}{requeued}"
        )
    return 0


def _cmd_show(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.engine.dead_letter import find_entry

    try:
        entry = find_entry(args.entry, args.dlq_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(entry.meta, indent=2))
    if entry.meta.get("lineage"):
        # the producer chain reconstruction walked before giving up
        print("lineage chain (reconstruction gave up here):")
        for hop in entry.meta["lineage"]:
            print(
                f"  {hop.get('ref')} <- {hop.get('produced_by_stage')} "
                f"(inputs: {', '.join(hop.get('inputs', [])) or '-'})"
            )
    try:
        tasks = entry.load_tasks()
    except Exception as e:  # payloads can outlive their class definitions
        print(f"tasks.pkl unreadable: {e}", file=sys.stderr)
        return 1
    for i, t in enumerate(tasks):
        print(f"[{i}] {type(t).__name__}: {_clip(repr(t))}")
    return 0


def _cmd_requeue(args: argparse.Namespace) -> int:
    import cloudpickle

    from cosmos_curate_tpu.engine.dead_letter import find_entry

    try:
        entry = find_entry(args.entry, args.dlq_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        tasks = entry.load_tasks()
    except Exception as e:
        print(f"error: tasks.pkl unreadable: {e}", file=sys.stderr)
        return 1
    out = args.out or str(entry.path / "requeued-tasks.pkl")
    with open(out, "wb") as f:
        f.write(cloudpickle.dumps(tasks))
    entry.mark_requeued()
    print(f"{len(tasks)} task(s) from {entry.entry_id} -> {out}")
    return 0


def _clip(s: str, n: int = 200) -> str:
    return s if len(s) <= n else s[: n - 1] + "…"
