"""`cosmos-curate-tpu` CLI root.

Equivalent of the reference's typer app (cosmos_curate/client/cli.py:25-39)
built on argparse (typer is not in this image). Sub-apps register themselves
here as they are built: local (run pipelines), view (clip viewer), slurm,
serve (job service).
"""

from __future__ import annotations

import argparse
import sys

from cosmos_curate_tpu import __version__


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cosmos-curate-tpu",
        description="TPU-native video curation pipelines",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command", metavar="command")

    info = sub.add_parser("info", help="show environment and device info")
    info.set_defaults(func=_cmd_info)

    lance = sub.add_parser(
        "export-lance",
        help="convert a run's embeddings parquet output to lance datasets "
        "(requires `pip install pylance` in the target environment)",
    )
    lance.add_argument("--src", required=True, help="embeddings/ dir (or one model subdir)")
    lance.add_argument("--dest", required=True, help="output root for <model>.lance datasets")
    lance.add_argument("--mode", default="create", choices=["create", "overwrite", "append"])
    lance.set_defaults(func=_cmd_export_lance)

    # Lazy registration of heavier sub-apps to keep `--help` fast.
    try:
        from cosmos_curate_tpu.cli import local_cli

        local_cli.register(sub)
    except ImportError:
        pass
    try:
        from cosmos_curate_tpu.cli import serve_cli

        serve_cli.register(sub)
    except ImportError:
        pass
    try:
        from cosmos_curate_tpu.cli import view_cli

        view_cli.register(sub)
    except ImportError:
        pass
    try:
        from cosmos_curate_tpu.cli import slurm_cli

        slurm_cli.register(sub)
    except ImportError:
        pass
    try:
        from cosmos_curate_tpu.cli import models_cli

        models_cli.register(sub)
    except ImportError:
        pass
    # no optional deps — an ImportError here would be a real defect, so no guard
    from cosmos_curate_tpu.cli import lint_cli

    lint_cli.register(sub)
    from cosmos_curate_tpu.cli import postgres_cli

    postgres_cli.register(sub)
    from cosmos_curate_tpu.cli import image_cli

    image_cli.register(sub)
    from cosmos_curate_tpu.cli import dlq_cli

    dlq_cli.register(sub)
    from cosmos_curate_tpu.cli import report_cli

    report_cli.register(sub)
    from cosmos_curate_tpu.cli import top_cli

    top_cli.register(sub)
    from cosmos_curate_tpu.cli import index_cli

    index_cli.register(sub)

    agent = sub.add_parser(
        "agent",
        help="join a driver's cross-node engine plane as a worker node",
    )
    agent.add_argument("--driver", required=True, help="driver HOST:PORT")
    agent.add_argument("--node-id", default=None)
    agent.add_argument("--num-cpus", type=float, default=None)
    agent.set_defaults(func=_cmd_agent)
    return parser


def _cmd_agent(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.engine.remote_agent import NodeAgent

    return NodeAgent(
        args.driver, node_id=args.node_id, num_cpus=args.num_cpus
    ).run()


def _cmd_info(args: argparse.Namespace) -> int:
    import platform

    print(f"cosmos-curate-tpu {__version__}")
    print(f"python {platform.python_version()} on {platform.system().lower()}")
    try:
        import jax

        devs = jax.devices()
        print(f"jax {jax.__version__}: {len(devs)} device(s), platform={devs[0].platform}")
    except Exception as e:  # device discovery can fail off-TPU; still report
        print(f"jax unavailable: {e}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "command", None):
        parser.print_help()
        return 2
    try:
        return int(args.func(args) or 0)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130


def _cmd_export_lance(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.storage.lance_export import export_parquet_to_lance

    try:
        written = export_parquet_to_lance(args.src, args.dest, mode=args.mode)
    except (RuntimeError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for uri, rows in written.items():
        print(f"{uri}: {rows} rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
