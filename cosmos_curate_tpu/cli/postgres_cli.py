"""`cosmos-curate-tpu postgres` — AV state-database admin commands.

Equivalent capability of the reference's Postgres manager CLI
(cosmos_curate/core/managers/postgres_cli.py:204-490: show_tables,
show_table_schemas, update_schemas, show_foreign_keys,
delete_foreign_keys_by_reference), built over the SDK-free wire client
(utils/pg_client.py) instead of sqlalchemy — and equally usable against the
sqlite twin, so the same commands administer a laptop run and a fleet DB.

``update-schemas`` diffs the live database against the AV state schema
declared in pipelines/av/state_db.py and applies additive changes only
(CREATE TABLE for missing tables, ALTER TABLE ADD COLUMN for missing
columns); extra tables/columns are reported, never dropped — matching the
reference's guarded schema migration.
"""

from __future__ import annotations

import argparse
import re
from dataclasses import dataclass

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass(frozen=True)
class ColumnInfo:
    name: str
    data_type: str
    nullable: bool


@dataclass(frozen=True)
class ForeignKeyInfo:
    table: str
    column: str
    ref_table: str
    ref_column: str


# -- target schema ---------------------------------------------------------


def parse_schema_ddl(ddl: str) -> dict[str, list[ColumnInfo]]:
    """Extract table -> columns from the state_db CREATE TABLE DDL (the
    schema source of truth; simple comma-split is sufficient for it)."""
    tables: dict[str, list[ColumnInfo]] = {}
    for m in re.finditer(
        r"CREATE TABLE IF NOT EXISTS (\w+)\s*\((.*?)\);", ddl, re.S | re.I
    ):
        name, body = m.group(1), m.group(2)
        cols: list[ColumnInfo] = []
        depth = 0
        piece = ""
        pieces: list[str] = []
        for ch in body:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                pieces.append(piece)
                piece = ""
            else:
                piece += ch
        if piece.strip():
            pieces.append(piece)
        for p in pieces:
            p = " ".join(p.split())
            if not p or re.match(r"(PRIMARY KEY|FOREIGN KEY|UNIQUE|CHECK)\b", p, re.I):
                continue
            parts = p.split()
            # type may be multi-word (DOUBLE PRECISION): take words until a
            # constraint keyword
            stop = {"NOT", "NULL", "PRIMARY", "DEFAULT", "UNIQUE", "REFERENCES", "CHECK"}
            type_words = []
            for w in parts[1:]:
                if w.upper() in stop:
                    break
                type_words.append(w.upper())
            cols.append(
                ColumnInfo(
                    name=parts[0],
                    data_type=" ".join(type_words) or "TEXT",
                    nullable="NOT NULL" not in p.upper(),
                )
            )
        tables[name] = cols
    return tables


def target_schema(dialect: str) -> dict[str, list[ColumnInfo]]:
    from cosmos_curate_tpu.pipelines.av import state_db

    ddl = state_db._PG_SCHEMA if dialect == "postgres" else state_db._SCHEMA
    return parse_schema_ddl(ddl)


# -- inspectors ------------------------------------------------------------


def quote_ident(name: str) -> str:
    """Quote an SQL identifier (table names come from DB metadata, which a
    hostile or merely mixed-case schema can use to break — or inject into —
    the admin session's queries)."""
    return '"' + name.replace('"', '""') + '"'


class SqliteInspector:
    dialect = "sqlite"

    def __init__(self, path: str) -> None:
        import sqlite3

        self._db = sqlite3.connect(path)

    def tables(self) -> list[str]:
        rows = self._db.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name NOT LIKE 'sqlite_%' ORDER BY name"
        ).fetchall()
        return [r[0] for r in rows]

    def row_count(self, table: str) -> int:
        return self._db.execute(f"SELECT COUNT(*) FROM {quote_ident(table)}").fetchone()[0]

    def columns(self, table: str) -> list[ColumnInfo]:
        rows = self._db.execute(f"PRAGMA table_info({quote_ident(table)})").fetchall()
        return [ColumnInfo(r[1], (r[2] or "TEXT").upper(), not r[3]) for r in rows]

    def foreign_keys(self) -> list[ForeignKeyInfo]:
        out = []
        for t in self.tables():
            for r in self._db.execute(f"PRAGMA foreign_key_list({quote_ident(t)})").fetchall():
                out.append(ForeignKeyInfo(t, r[3], r[2], r[4] or ""))
        return out

    def execute(self, sql: str) -> None:
        with self._db:
            self._db.execute(sql)

    def close(self) -> None:
        self._db.close()


class PostgresInspector:
    dialect = "postgres"

    def __init__(self, dsn: str) -> None:
        from cosmos_curate_tpu.utils.pg_client import PgConnection, parse_dsn

        self._conn = PgConnection(**parse_dsn(dsn))

    def tables(self) -> list[str]:
        res = self._conn.execute(
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_schema = 'public' ORDER BY table_name"
        )
        return [r[0] for r in res.rows]

    _SCHEMA_FILTER = "AND table_schema = 'public' "

    def row_count(self, table: str) -> int:
        res = self._conn.execute(f"SELECT COUNT(*) FROM {quote_ident(table)}")
        return int(res.rows[0][0])

    def columns(self, table: str) -> list[ColumnInfo]:
        from cosmos_curate_tpu.utils.pg_client import quote_literal

        res = self._conn.execute(
            "SELECT column_name, data_type, is_nullable "
            "FROM information_schema.columns "
            f"WHERE table_name = {quote_literal(table)} "
            f"{self._SCHEMA_FILTER}ORDER BY ordinal_position"
        )
        return [
            ColumnInfo(r[0], (r[1] or "text").upper(), r[2] in ("YES", "1"))
            for r in res.rows
        ]

    def foreign_keys(self) -> list[ForeignKeyInfo]:
        res = self._conn.execute(
            "SELECT tc.table_name, kcu.column_name, ccu.table_name, ccu.column_name "
            "FROM information_schema.table_constraints tc "
            "JOIN information_schema.key_column_usage kcu "
            "ON tc.constraint_name = kcu.constraint_name "
            "JOIN information_schema.constraint_column_usage ccu "
            "ON tc.constraint_name = ccu.constraint_name "
            "WHERE tc.constraint_type = 'FOREIGN KEY' "
            "AND tc.table_schema = 'public'"
        )
        return [ForeignKeyInfo(*r) for r in res.rows]

    def execute(self, sql: str) -> None:
        self._conn.execute(sql)

    def close(self) -> None:
        self._conn.close()


def open_inspector(db: str):
    if db.startswith(("postgres://", "postgresql://")):
        return PostgresInspector(db)
    return SqliteInspector(db)


# -- schema diff -----------------------------------------------------------


@dataclass
class SchemaChanges:
    missing_tables: list[str]
    missing_columns: list[tuple[str, ColumnInfo]]
    extra_tables: list[str]
    extra_columns: list[tuple[str, str]]

    @property
    def empty(self) -> bool:
        return not (self.missing_tables or self.missing_columns)


def diff_schema(insp, target: dict[str, list[ColumnInfo]]) -> SchemaChanges:
    live = {t: {c.name for c in insp.columns(t)} for t in insp.tables()}
    changes = SchemaChanges([], [], [], [])
    for table, cols in target.items():
        if table not in live:
            changes.missing_tables.append(table)
            continue
        for col in cols:
            if col.name not in live[table]:
                changes.missing_columns.append((table, col))
        for name in sorted(live[table] - {c.name for c in cols}):
            changes.extra_columns.append((table, name))
    for table in sorted(set(live) - set(target)):
        changes.extra_tables.append(table)
    return changes


def apply_changes(insp, changes: SchemaChanges, *, dry_run: bool) -> list[str]:
    """Additive DDL only. Returns the statements (executed unless dry_run)."""
    from cosmos_curate_tpu.pipelines.av import state_db

    ddl = state_db._PG_SCHEMA if insp.dialect == "postgres" else state_db._SCHEMA
    stmts: list[str] = []
    for table in changes.missing_tables:
        m = re.search(
            rf"(CREATE TABLE IF NOT EXISTS {table}\s*\(.*?\);)", ddl, re.S | re.I
        )
        if m:
            stmts.append(m.group(1))
    for table, col in changes.missing_columns:
        # backfill default must match the column type; for types we can't
        # guess a safe default for, add the column nullable and warn — an
        # additive migration must not abort half-applied on bad DDL
        head = col.data_type.split()[0]
        if col.nullable:
            null = ""
        elif head in ("INTEGER", "BIGINT", "SMALLINT", "REAL", "DOUBLE", "NUMERIC", "FLOAT"):
            null = " NOT NULL DEFAULT 0"
        elif head in ("TEXT", "VARCHAR", "CHARACTER", "CHAR"):
            null = " NOT NULL DEFAULT ''"
        elif head in ("BOOLEAN", "BOOL"):
            null = " NOT NULL DEFAULT FALSE"
        else:
            logger.warning(
                "no safe backfill default for %s.%s (%s); adding as nullable",
                table, col.name, col.data_type,
            )
            null = ""
        stmts.append(f"ALTER TABLE {table} ADD COLUMN {col.name} {col.data_type}{null}")
    for sql in stmts:
        if dry_run:
            logger.info("[dry-run] %s", " ".join(sql.split()))
        else:
            logger.info("applying: %s", " ".join(sql.split()))
            insp.execute(sql)
    return stmts


# -- commands --------------------------------------------------------------


def _cmd_show_tables(args) -> int:
    insp = open_inspector(args.db)
    try:
        for t in insp.tables():
            print(f"{t}\t{insp.row_count(t)}")
    finally:
        insp.close()
    return 0


def _cmd_show_schemas(args) -> int:
    insp = open_inspector(args.db)
    try:
        for t in insp.tables():
            print(t)
            for c in insp.columns(t):
                null = "NULL" if c.nullable else "NOT NULL"
                print(f"  {c.name}\t{c.data_type}\t{null}")
    finally:
        insp.close()
    return 0


def _cmd_update_schemas(args) -> int:
    insp = open_inspector(args.db)
    try:
        changes = diff_schema(insp, target_schema(insp.dialect))
        if changes.empty:
            print("schema up to date")
        stmts = apply_changes(insp, changes, dry_run=args.dry_run)
        for s in stmts:
            print(("would apply: " if args.dry_run else "applied: ") + " ".join(s.split()))
        for table in changes.extra_tables:
            print(f"extra table (kept): {table}")
        for table, col in changes.extra_columns:
            print(f"extra column (kept): {table}.{col}")
    finally:
        insp.close()
    return 0


def _cmd_show_foreign_keys(args) -> int:
    insp = open_inspector(args.db)
    try:
        for fk in insp.foreign_keys():
            print(f"{fk.table}.{fk.column} -> {fk.ref_table}.{fk.ref_column}")
    finally:
        insp.close()
    return 0


def register(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("postgres", help="AV state database admin")
    psub = p.add_subparsers(dest="pg_command", metavar="subcommand", required=True)

    for name, func, helptext in [
        ("show-tables", _cmd_show_tables, "list tables with row counts"),
        ("show-schemas", _cmd_show_schemas, "show per-table column schemas"),
        ("show-foreign-keys", _cmd_show_foreign_keys, "list foreign-key relationships"),
    ]:
        sp = psub.add_parser(name, help=helptext)
        sp.add_argument("--db", required=True, help="postgres:// DSN or sqlite path")
        sp.set_defaults(func=func)

    up = psub.add_parser(
        "update-schemas", help="diff live schema vs the AV state schema; apply additive DDL"
    )
    up.add_argument("--db", required=True, help="postgres:// DSN or sqlite path")
    up.add_argument("--dry-run", action="store_true", help="print DDL without applying")
    up.set_defaults(func=_cmd_update_schemas)
