"""`cosmos-curate-tpu index …` — manage the persistent corpus embedding index.

The operational surface over dedup/corpus_index.py:

    index build  --input-path <split-root>   train centroids + shard a run's
                                             embeddings (also folds any
                                             pending in-pipeline fragments)
    index add    --input-path <split-root>   route another run into an
                                             existing index (no re-cluster)
    index query  --input-path <split-root>   dedup-query a run against the
                                             index; optional CSV in the
                                             dedup_summary format `local
                                             shard --dedup-csv` consumes
    index stats  --index-path <root>         meta + shard/pending counts
    index consolidate --index-path <root>    fold pending fragments into the
                                             index (the multi-node path:
                                             after `merge-summaries`, no
                                             full `index build` needed)
    index compact --index-path <root>        one compaction pass: fold
                                             pending, rebalance skew,
                                             refresh centroids, publish a
                                             new manifest generation
    index serve  --index-path <root>         standalone HTTP search server
                                             (POST /v1/search; the job
                                             service mounts the same route
                                             via `serve --index-path`)

``--index-path`` defaults to ``<input>/index`` — the same root
``local split --corpus-index`` writes in-pipeline fragments to.
"""

from __future__ import annotations

import argparse
import json
import sys


def register(sub: argparse._SubParsersAction) -> None:
    index = sub.add_parser(
        "index", help="persistent corpus embedding index (IVF dedup queries)"
    )
    isub = index.add_subparsers(dest="subcommand", metavar="action")

    def _common(p: argparse.ArgumentParser, needs_input: bool = True) -> None:
        if needs_input:
            p.add_argument(
                "--input-path", required=True,
                help="split output root (with embeddings/<model>/)",
            )
        p.add_argument(
            "--index-path", default="",
            help="index root (default: <input>/index)",
        )
        p.add_argument("--embedding-model", default="", help='"" = first found')
        p.add_argument("--no-mesh", action="store_true")

    build = isub.add_parser(
        "build", help="train centroids and shard a run's embeddings"
    )
    _common(build)
    build.add_argument("--k", type=int, default=0, help="clusters (0 = sqrt(N))")
    build.add_argument("--iters", type=int, default=20)
    build.set_defaults(func=_cmd_build)

    add = isub.add_parser("add", help="route a run's embeddings into an existing index")
    _common(add)
    add.set_defaults(func=_cmd_add)

    query = isub.add_parser(
        "query", help="dedup-query a run's embeddings against the index"
    )
    _common(query)
    query.add_argument("--eps", type=float, default=0.07)
    query.add_argument("--nprobe", type=int, default=0, help="0 = index default")
    query.add_argument("--top-k", type=int, default=8)
    query.add_argument(
        "--output-csv", default="",
        help="write a dedup_summary CSV (consumable by `local shard --dedup-csv`)",
    )
    query.set_defaults(func=_cmd_query)

    stats = isub.add_parser("stats", help="index metadata + shard/pending counts")
    stats.add_argument("--index-path", required=True)
    stats.set_defaults(func=_cmd_stats)

    consolidate = isub.add_parser(
        "consolidate",
        help="fold pending fragments into the index (multi-node helper: "
        "run after merging split outputs — trains centroids only if the "
        "index does not exist yet)",
    )
    consolidate.add_argument("--index-path", required=True)
    consolidate.add_argument("--k", type=int, default=0, help="clusters (0 = sqrt(N))")
    consolidate.add_argument("--iters", type=int, default=20)
    consolidate.add_argument("--no-mesh", action="store_true")
    consolidate.set_defaults(func=_cmd_consolidate)

    compact = isub.add_parser(
        "compact",
        help="one compaction pass: fold pending, rebalance skewed clusters, "
        "refresh centroids, publish a new manifest generation",
    )
    compact.add_argument("--index-path", required=True)
    compact.add_argument("--rebalance-factor", type=float, default=4.0,
                         help="split clusters larger than this × mean rows")
    compact.add_argument("--no-rebalance", action="store_true")
    compact.add_argument("--no-fold-pending", action="store_true")
    compact.add_argument("--no-refresh-centroids", action="store_true")
    compact.add_argument("--force", action="store_true",
                         help="publish a generation even when nothing changed")
    compact.add_argument(
        "--gc", action="store_true",
        help="delete fragments superseded generations reference (safe only "
        "with no live index-server readers; a running server GCs on its own "
        "as old generations drain)",
    )
    compact.add_argument("--no-mesh", action="store_true")
    compact.set_defaults(func=_cmd_compact)

    srv = isub.add_parser(
        "serve",
        help="standalone HTTP search server over the index (POST /v1/search)",
    )
    srv.add_argument("--index-path", required=True)
    srv.add_argument("--host", default="0.0.0.0")
    srv.add_argument("--port", type=int, default=8081)
    srv.add_argument("--text-model", default="clip-text-b-tpu",
                     help="CLIP text tower for text-to-clip queries")
    srv.add_argument("--cache-mb", type=int, default=0,
                     help="warm shard cache budget in MB (0 = env/default)")
    srv.add_argument("--no-warmup", action="store_true")
    srv.add_argument("--max-inflight", type=int, default=8)
    srv.add_argument("--max-waiting", type=int, default=32)
    srv.add_argument("--compact-interval-s", type=float, default=0.0,
                     help="background compaction cadence (0 disables)")
    srv.add_argument("--metrics-port", type=int, default=None)
    srv.set_defaults(func=_cmd_serve)

    index.set_defaults(func=lambda args: (index.print_help(), 2)[1])


def _index_root(args: argparse.Namespace) -> str:
    return (
        args.index_path or f"{args.input_path.rstrip('/')}/index"
    ).rstrip("/")


def _mesh(args: argparse.Namespace):
    if getattr(args, "no_mesh", False):
        return None
    try:
        from cosmos_curate_tpu.parallel.mesh import best_effort_mesh

        return best_effort_mesh()
    except Exception as e:
        print(f"no mesh available ({e}); single device", file=sys.stderr)
        return None


def _cmd_build(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex
    from cosmos_curate_tpu.dedup.index_store import IndexStore
    from cosmos_curate_tpu.pipelines.video.dedup import load_embeddings

    root = _index_root(args)
    mesh = _mesh(args)
    ids, vecs, model = load_embeddings(args.input_path, args.embedding_model)
    index = CorpusIndex.build(
        root, ids, vecs, model=model,
        k=args.k or None, iters=args.iters, mesh=mesh, metrics_name="index_cli",
    )
    # Pending in-pipeline fragments at this root (a --corpus-index run that
    # skipped driver consolidation, e.g. multi-node) hold the SAME rows the
    # writer also wrote to embeddings parquet — the build above already
    # ingested them, so consolidating the fragments too would double every
    # clip. Clear them instead.
    cleared = IndexStore(root).clear_pending()
    print(json.dumps({**index.stats(), "pending_cleared": cleared}, indent=2))
    return 0


def _cmd_add(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex
    from cosmos_curate_tpu.pipelines.video.dedup import load_embeddings

    root = _index_root(args)
    index = CorpusIndex.open(root, mesh=_mesh(args), metrics_name="index_cli")
    ids, vecs, model = load_embeddings(args.input_path, args.embedding_model)
    if index.meta.get("model") and model != index.meta["model"]:
        print(
            f"error: run embeddings are from {model!r} but the index holds "
            f"{index.meta['model']!r} — one embedding space per index",
            file=sys.stderr,
        )
        return 2
    added = index.add(ids, vecs)
    print(json.dumps({**index.stats(), "added": added}, indent=2))
    return 0


def _cmd_query(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex, incremental_dedup
    from cosmos_curate_tpu.pipelines.video.dedup import load_embeddings

    root = _index_root(args)
    index = CorpusIndex.open(root, mesh=_mesh(args), metrics_name="index_cli")
    ids, vecs, model = load_embeddings(args.input_path, args.embedding_model)
    result = incremental_dedup(
        index, ids, vecs,
        eps=args.eps, nprobe=args.nprobe or None, top_k=args.top_k,
    )
    if args.output_csv:
        from cosmos_curate_tpu.storage.writers import write_csv

        rows = [
            {
                "clip_uuid": cid,
                "action": "removed",
                "duplicate_of": result["duplicate_of"].get(cid, ""),
            }
            for cid in result["removed"]
        ] + [
            {"clip_uuid": cid, "action": "kept", "duplicate_of": ""}
            for cid in result["kept"]
        ]
        write_csv(args.output_csv, rows, ["clip_uuid", "action", "duplicate_of"])
    print(
        json.dumps(
            {
                "index_path": root,
                "embedding_model": model,
                "eps": args.eps,
                "num_queries": len(ids),
                "num_kept": len(result["kept"]),
                "num_removed": len(result["removed"]),
                "duplicate_of": result["duplicate_of"],
                "output_csv": args.output_csv,
            },
            indent=2,
        )
    )
    return 0


def _cmd_consolidate(args: argparse.Namespace) -> int:
    """The multi-node "index remainders" path: merged split outputs carry
    every node's pending fragments under one index root (chunk-scoped tags
    never collide); this folds them against the existing centroids — or
    trains centroids from them when the index is brand new — without the
    full `index build` re-read of the run's embeddings parquet."""
    from cosmos_curate_tpu.dedup.corpus_index import consolidate_index

    result = consolidate_index(
        args.index_path.rstrip("/"),
        k=args.k or None, iters=args.iters, mesh=_mesh(args),
        metrics_name="index_cli",
    )
    print(json.dumps({"index_path": args.index_path.rstrip("/"), **result}, indent=2))
    return 0


def _cmd_compact(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.dedup.compaction import compact_index

    report = compact_index(
        args.index_path.rstrip("/"),
        mesh=_mesh(args),
        fold_pending=not args.no_fold_pending,
        rebalance=not args.no_rebalance,
        rebalance_factor=args.rebalance_factor,
        refresh_centroids=not args.no_refresh_centroids,
        force=args.force,
        gc=args.gc,
        metrics_name="index_cli",
    )
    print(json.dumps(report, indent=2))
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.service.search import SearchConfig, serve_index

    if args.metrics_port is not None:
        from cosmos_curate_tpu.engine.metrics import get_metrics

        get_metrics(args.metrics_port)
    serve_index(
        host=args.host,
        port=args.port,
        cfg=SearchConfig(
            index_path=args.index_path.rstrip("/"),
            max_inflight=args.max_inflight,
            max_waiting=args.max_waiting,
            text_model=args.text_model,
            cache_bytes=(args.cache_mb << 20) or None,
            warmup=not args.no_warmup,
            compact_interval_s=args.compact_interval_s,
        ),
    )
    return 0


def _cmd_stats(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex
    from cosmos_curate_tpu.dedup.index_store import IndexStore

    root = args.index_path.rstrip("/")
    store = IndexStore(root)
    if not store.exists():
        pending = len(store.list_pending())
        print(
            json.dumps(
                {
                    "index_path": root,
                    "exists": False,
                    "pending_fragments": pending,
                    "hint": "run `index build` (or a --corpus-index split) first",
                },
                indent=2,
            )
        )
        return 0 if pending else 2
    print(json.dumps(CorpusIndex.open(root).stats(), indent=2))
    return 0
