"""`cosmos-curate-tpu report …` — render a run's flight-recorder report.

The flight recorder (observability/flight_recorder.py) writes
``<output>/report/run_report.json`` at run finalize for traced runs. This
sub-app renders it: trace connectivity (ONE trace id = cross-process
propagation held), the span-tree critical path, and the per-stage /
device-dispatch / flow time breakdowns.

``RUN`` is the pipeline output root (or a direct path to a
``run_report.json``). ``--rebuild`` regenerates the report from the run's
collected trace artifacts — useful after copying a run directory around or
when the run predates the recorder.
"""

from __future__ import annotations

import argparse
import json
import sys


def register(sub: argparse._SubParsersAction) -> None:
    rep = sub.add_parser(
        "report",
        help="render a run's flight-recorder report (critical path, "
        "per-stage time, trace connectivity)",
    )
    rep.add_argument("run", help="pipeline output root (or a run_report.json path)")
    rep.add_argument("--json", action="store_true", dest="as_json", help="raw JSON")
    rep.add_argument(
        "--rebuild",
        action="store_true",
        help="regenerate the report from the run's trace artifacts first",
    )
    rep.set_defaults(func=_cmd_report)


def _cmd_report(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.observability.flight_recorder import (
        REPORT_REL,
        load_report,
        render_report,
        report_path,
        write_run_report,
    )

    run = args.run
    suffix = f"/{REPORT_REL}"
    if run.endswith(".json"):
        path = run
        # the run root is only derivable when the json sits at its
        # canonical in-run location; a bare copied file has no root
        root = run[: -len(suffix)] if run.endswith(suffix) and len(run) > len(suffix) else None
    else:
        path = report_path(run)
        root = run
    existing: dict | None = None
    try:
        existing = load_report(path, strict=True)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        if not args.rebuild:
            return 2
    if args.rebuild or existing is None:
        if root is None:
            print(
                f"error: cannot rebuild from {run!r} — pass the run's "
                "output root instead of a detached json file",
                file=sys.stderr,
            )
            return 2
        if not args.rebuild:
            print(
                f"no report at {path}; rebuilding from trace artifacts",
                file=sys.stderr,
            )
        # `prior` carries over the sections only the original driver could
        # source (dispatch/flow aggregates, runner stage times) — a rebuild
        # refreshes the span analysis without degrading the artifact
        report = write_run_report(root, prior=existing, require_spans=True)
        if not report["span_count"]:
            print(
                f"error: no trace spans under {root}/profile — was the run "
                "traced (--tracing)?",
                file=sys.stderr,
            )
            return 2
    else:
        report = existing
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(render_report(report))
    return 0
