"""`cosmos-curate-tpu report …` — render a run's flight-recorder report.

The flight recorder (observability/flight_recorder.py) writes
``<output>/report/run_report.json`` at run finalize for traced runs. This
sub-app renders it: trace connectivity (ONE trace id = cross-process
propagation held), the span-tree critical path, and the per-stage /
device-dispatch / flow time breakdowns.

``RUN`` is the pipeline output root (or a direct path to a
``run_report.json``). ``--rebuild`` regenerates the report from the run's
collected trace artifacts — useful after copying a run directory around or
when the run predates the recorder.

A LIVE run has no ``run_report.json`` yet — instead of failing, the CLI
falls back to the run's live ops snapshot
(``<out>/report/live/status.json``) under a clear ``RUN IN PROGRESS``
banner; ``--follow`` keeps refreshing that view and renders the final
flight-recorder report the moment finalize writes it.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def register(sub: argparse._SubParsersAction) -> None:
    rep = sub.add_parser(
        "report",
        help="render a run's flight-recorder report (critical path, "
        "per-stage time, trace connectivity); live runs render their "
        "in-flight snapshot instead",
    )
    rep.add_argument("run", help="pipeline output root (or a run_report.json path)")
    rep.add_argument("--json", action="store_true", dest="as_json", help="raw JSON")
    rep.add_argument(
        "--rebuild",
        action="store_true",
        help="regenerate the report from the run's trace artifacts first",
    )
    rep.add_argument(
        "--follow",
        action="store_true",
        help="refresh the live view until the final report lands, then "
        "render it",
    )
    rep.add_argument(
        "--interval", type=float, default=2.0, help="--follow refresh seconds"
    )
    rep.set_defaults(func=_cmd_report)


def _render_live(root: str, as_json: bool) -> bool:
    """Render the live snapshot under a RUN IN PROGRESS banner; False when
    there is no snapshot to show."""
    from cosmos_curate_tpu.observability.live_status import read_status, render_status

    snap = read_status(root)
    if snap is None:
        return False
    if as_json:
        print(json.dumps(snap))
        return True
    state = str(snap.get("state", "running")).upper()
    banner = "RUN IN PROGRESS" if state == "RUNNING" else f"RUN {state}"
    print("=" * 22, banner, "=" * 22)
    print("(no run_report.json yet — rendering the live ops snapshot)")
    print(render_status(snap))
    return True


def _cmd_report(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.observability.flight_recorder import (
        REPORT_REL,
        load_report,
        render_report,
        report_path,
        write_run_report,
    )

    run = args.run
    suffix = f"/{REPORT_REL}"
    if run.endswith(".json"):
        path = run
        # the run root is only derivable when the json sits at its
        # canonical in-run location; a bare copied file has no root
        root = run[: -len(suffix)] if run.endswith(suffix) and len(run) > len(suffix) else None
    else:
        path = report_path(run)
        root = run
    if args.follow and root is not None:
        # live loop: render the in-flight snapshot until finalize writes
        # the real report (then fall through and render that) — or until
        # the snapshot goes terminal on an UNTRACED run, which never
        # writes run_report.json (the final live frame is the exit)
        from cosmos_curate_tpu.observability.live_status import read_status

        while load_report(path) is None:
            if not args.as_json:
                sys.stdout.write("\x1b[2J\x1b[H")
            snap = read_status(root)
            if not _render_live(root, args.as_json):
                print(f"waiting for a live snapshot under {root} ...")
            sys.stdout.flush()
            if snap is not None and snap.get("state") != "running":
                # run over. Traced runs write the report a few seconds
                # AFTER the terminal snapshot (artifact collection runs in
                # between) — grace-poll before concluding this run is
                # untraced and the live frame is final.
                deadline = time.monotonic() + max(2.0, 3 * args.interval)
                while load_report(path) is None:
                    if time.monotonic() >= deadline:
                        return 0  # untraced: no report is ever coming
                    time.sleep(0.2)
                break  # report landed: fall through and render it
            time.sleep(max(0.2, args.interval))
    existing: dict | None = None
    try:
        existing = load_report(path, strict=True)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        if not args.rebuild:
            return 2
    if existing is None and not args.rebuild and root is not None:
        # a LIVE run has no report yet: show the in-flight view with a
        # clear banner instead of failing on the missing artifact.
        # Finished runs fall through to the rebuild-from-traces path — a
        # terminal snapshot is strictly poorer than a rebuilt report.
        from cosmos_curate_tpu.observability.live_status import read_status

        snap = read_status(root)
        if snap is not None and snap.get("state") == "running":
            _render_live(root, args.as_json)
            return 0
    if args.rebuild or existing is None:
        if root is None:
            print(
                f"error: cannot rebuild from {run!r} — pass the run's "
                "output root instead of a detached json file",
                file=sys.stderr,
            )
            return 2
        if not args.rebuild:
            print(
                f"no report at {path}; rebuilding from trace artifacts",
                file=sys.stderr,
            )
        # `prior` carries over the sections only the original driver could
        # source (dispatch/flow aggregates, runner stage times) — a rebuild
        # refreshes the span analysis without degrading the artifact
        report = write_run_report(root, prior=existing, require_spans=True)
        if not report["span_count"]:
            print(
                f"error: no trace spans under {root}/profile — was the run "
                "traced (--tracing)?",
                file=sys.stderr,
            )
            return 2
    else:
        report = existing
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(render_report(report))
    return 0
