"""`cosmos-curate-tpu lint`: run the static-analysis rule set.

Usage:

    cosmos-curate-tpu lint                       # lint cosmos_curate_tpu/
    cosmos-curate-tpu lint path/a.py dir/        # specific targets
    cosmos-curate-tpu lint --rules min-python    # subset of rules
    cosmos-curate-tpu lint --list-rules

Exit status: 0 clean, 1 findings, 2 usage error. Findings print as
``file:line rule-id message``; see docs/STATIC_ANALYSIS.md for the rule
catalogue, the ``[tool.curate-lint]`` config section and suppression
comments.
"""

from __future__ import annotations

import argparse
import sys


def register(sub: "argparse._SubParsersAction") -> None:
    lint = sub.add_parser(
        "lint",
        help="static analysis: engine lock discipline, interpreter-floor "
        "APIs, jit transfer smells, silent exception swallows",
    )
    lint.add_argument(
        "paths",
        nargs="*",
        default=["cosmos_curate_tpu"],
        help="files or directories to lint (default: cosmos_curate_tpu/)",
    )
    lint.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule ids to run (default: all enabled in "
        "[tool.curate-lint])",
    )
    lint.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue and exit"
    )
    lint.set_defaults(func=_cmd_lint)


def _cmd_lint(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.analysis.ast_lint import run_lint
    from cosmos_curate_tpu.analysis.rules import all_rules

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.rule_id:16s} {rule.description}")
        return 0
    rule_ids = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        findings = run_lint(args.paths, rule_ids=rule_ids)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.render())
    n_files = len(args.paths)
    if findings:
        print(
            f"curate-lint: {len(findings)} finding(s) "
            f"(suppress with '# curate-lint: disable=<rule>')",
            file=sys.stderr,
        )
        return 1
    print(f"curate-lint: clean ({n_files} target(s))", file=sys.stderr)
    return 0
