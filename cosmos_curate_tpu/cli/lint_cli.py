"""`cosmos-curate-tpu lint`: run the static-analysis passes.

Usage:

    cosmos-curate-tpu lint                       # AST rules over cosmos_curate_tpu/
    cosmos-curate-tpu lint path/a.py dir/        # specific targets
    cosmos-curate-tpu lint --rules min-python    # subset of rules
    cosmos-curate-tpu lint --shard-check         # + sharding/shape contracts
    cosmos-curate-tpu lint --shard-check --mesh data=2,seq=2 --hbm-gb 16
    cosmos-curate-tpu lint --concurrency         # + whole-repo lock analysis
    cosmos-curate-tpu lint --json                # NDJSON findings (CI)
    cosmos-curate-tpu lint --list-rules

``--shard-check`` adds the device-free shardcheck pass
(analysis/shard_check.py): every registered sharded entry point is
eval_shape'd against the declared mesh (default from ``[tool.curate-lint]``
``shard-mesh``) with zero device allocation — run it under
``JAX_PLATFORMS=cpu`` anywhere.

``--concurrency`` adds the whole-repo concurrency verifier
(analysis/concurrency_check.py): lock registry + acquisition-order graph
(cycle = potential deadlock), blocking-calls-under-lock, and
guarded-by/holds-lock contract checking. Its dynamic twin is the
``CURATE_LOCKCHECK=1`` runtime sanitizer (analysis/lock_runtime.py).

``--json`` switches findings to machine-readable NDJSON (one object per
line: rule/file/line/severity/message) across every pillar, for
``run_ci_checks.sh`` and the GitHub workflow's PR annotations.

Exit status: 0 clean, 1 error findings, 2 usage error. Warnings print but
do not fail the gate. Findings print as ``file:line rule-id message``; see
docs/STATIC_ANALYSIS.md for the rule catalogue, the ``[tool.curate-lint]``
config section and suppression comments.
"""

from __future__ import annotations

import argparse
import sys


def register(sub: "argparse._SubParsersAction") -> None:
    lint = sub.add_parser(
        "lint",
        help="static analysis: engine lock discipline, interpreter-floor "
        "APIs, jit transfer smells, silent exception swallows, mesh-axis "
        "hygiene; --shard-check adds device-free sharding contracts",
    )
    lint.add_argument(
        "paths",
        nargs="*",
        default=["cosmos_curate_tpu"],
        help="files or directories to lint (default: cosmos_curate_tpu/)",
    )
    lint.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule ids to run (default: all enabled in "
        "[tool.curate-lint])",
    )
    lint.add_argument(
        "--shard-check",
        action="store_true",
        help="also run the sharding/shape contract pass (device-free: "
        "jax.eval_shape over an AbstractMesh, no TPUs needed)",
    )
    lint.add_argument(
        "--mesh",
        default=None,
        help='mesh extents for --shard-check, e.g. "data=2,seq=2" '
        "(unnamed axes = 1; default from [tool.curate-lint] shard-mesh)",
    )
    lint.add_argument(
        "--devices",
        type=int,
        default=None,
        help="device count a -1 mesh axis absorbs (default: the product of "
        "the fixed extents — still zero device discovery)",
    )
    lint.add_argument(
        "--hbm-gb",
        type=float,
        default=None,
        help="per-device HBM budget in GiB for the replicated-params "
        "estimate (default from [tool.curate-lint] shard-hbm-gb; 0 skips)",
    )
    lint.add_argument(
        "--concurrency",
        action="store_true",
        help="also run the whole-repo concurrency verifier: lock-order "
        "graph (deadlock cycles), blocking-under-lock, guarded-by / "
        "holds-lock contracts",
    )
    lint.add_argument(
        "--schema",
        action="store_true",
        help="also run the schema & wire-compat verifier: protocol frames "
        "and durable JSON formats diffed against analysis/schemas/ goldens; "
        "drift without a version bump (or without a migration shim for "
        "breaking durable drift) fails the gate",
    )
    lint.add_argument(
        "--update",
        action="store_true",
        help="with --schema: regenerate the golden snapshots under "
        "analysis/schemas/ from the current code instead of diffing",
    )
    lint.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="emit findings as NDJSON (rule/file/line/severity/message), "
        "one object per line, across all pillars",
    )
    lint.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue and exit"
    )
    lint.set_defaults(func=_cmd_lint)


def _cmd_lint(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.analysis.ast_lint import run_lint
    from cosmos_curate_tpu.analysis.common import Severity
    from cosmos_curate_tpu.analysis.rules import all_rules

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.rule_id:32s} {rule.description}")
        print(f"{'(pass) shard-check':32s} device-free sharding/shape contracts "
              "(--shard-check; rule ids shard-*)")
        print(f"{'(pass) concurrency':32s} whole-repo lock-order graph, "
              "blocking-under-lock, guarded-by contracts (--concurrency; "
              "rule ids lock-order, lock-blocking, unguarded-shared)")
        print(f"{'(pass) schema':32s} protocol-frame + durable-format "
              "golden-schema diff (--schema [--update]; rule ids schema-*)")
        return 0
    rule_ids = [r.strip() for r in args.rules.split(",")] if args.rules else None
    try:
        findings = run_lint(args.paths, rule_ids=rule_ids)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.shard_check:
        from cosmos_curate_tpu.analysis.shard_check import parse_mesh_spec, run_shard_check

        try:
            mesh_spec = parse_mesh_spec(args.mesh) if args.mesh else None
            findings.extend(
                run_shard_check(
                    mesh_spec, num_devices=args.devices, hbm_gb=args.hbm_gb
                )
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.concurrency:
        from cosmos_curate_tpu.analysis.concurrency_check import run_concurrency_check

        try:
            findings.extend(run_concurrency_check(args.paths))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.update and not args.schema:
        print("error: --update requires --schema", file=sys.stderr)
        return 2
    if args.schema:
        from cosmos_curate_tpu.analysis.schema_check import run_schema_check

        findings.extend(run_schema_check(update=args.update))
        if args.update:
            print(
                "curate-lint: schema goldens regenerated under "
                "cosmos_curate_tpu/analysis/schemas/ — review and commit them",
                file=sys.stderr,
            )
    for f in findings:
        print(f.to_json() if args.as_json else f.render())
    errors = [f for f in findings if f.severity is Severity.ERROR]
    n_files = len(args.paths)
    if errors:
        print(
            f"curate-lint: {len(errors)} error(s), "
            f"{len(findings) - len(errors)} warning(s) "
            f"(suppress AST rules with '# curate-lint: disable=<rule>')",
            file=sys.stderr,
        )
        return 1
    if findings:
        print(f"curate-lint: {len(findings)} warning(s), no errors", file=sys.stderr)
        return 0
    print(f"curate-lint: clean ({n_files} target(s))", file=sys.stderr)
    return 0
