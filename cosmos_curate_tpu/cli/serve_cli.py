"""`cosmos-curate-tpu serve` — run the job service."""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    serve = sub.add_parser("serve", help="run the HTTP job service")
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8080)
    serve.add_argument("--work-root", default="/tmp/curate_service")
    serve.set_defaults(func=_cmd_serve)


def _cmd_serve(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.service.app import serve

    serve(host=args.host, port=args.port, work_root=args.work_root)
    return 0
