"""`cosmos-curate-tpu serve` — run the durable multi-tenant job service.

See docs/SERVICE.md for the API, tenancy/quota model, journal layout and
drain semantics. The defaults match :class:`ServiceConfig` /
:class:`QuotaConfig`; every admission knob is exposed so a deployment can
size quotas to its box without code changes.
"""

from __future__ import annotations

import argparse


def register(sub: argparse._SubParsersAction) -> None:
    serve = sub.add_parser("serve", help="run the HTTP job service")
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8080)
    serve.add_argument(
        "--work-root", default="/tmp/curate_service",
        help="job work dirs + the crash-safe journal live here; restart "
        "against the same root to resume interrupted jobs",
    )
    serve.add_argument(
        "--max-concurrent", type=int, default=2,
        help="dispatcher cap (additionally clamped by host CPU/memory)",
    )
    serve.add_argument("--max-running-per-tenant", type=int, default=2)
    serve.add_argument("--max-queued-per-tenant", type=int, default=8)
    serve.add_argument("--max-queued-total", type=int, default=64)
    serve.add_argument(
        "--cpus-per-job", type=float, default=1.0,
        help="host-budget cost estimate per job (0 disables the CPU clamp)",
    )
    serve.add_argument(
        "--memory-gb-per-job", type=float, default=0.0,
        help="host-budget memory cost per job (0 disables the memory clamp)",
    )
    serve.add_argument(
        "--max-attempts", type=int, default=3,
        help="per-job retry budget before dead_lettered (request may lower it)",
    )
    serve.add_argument(
        "--drain-s", type=float, default=30.0,
        help="SIGTERM grace: running jobs get this long to finish before "
        "being checkpointed as interrupted for the next boot",
    )
    serve.add_argument(
        "--term-grace-s", type=float, default=5.0,
        help="terminate endpoint: SIGTERM → SIGKILL escalation window",
    )
    serve.add_argument(
        "--metrics-port", type=int, default=None,
        help="expose service_*/pipeline_* prometheus metrics on this port",
    )
    serve.add_argument(
        "--slo-queue-wait-s", type=float, default=0.0,
        help="per-tenant SLO: max pending->running wait before a breach "
        "(0 disables; breaches land in service_slo_breaches_total and "
        "GET /v1/slo)",
    )
    serve.add_argument(
        "--slo-run-duration-s", type=float, default=0.0,
        help="per-tenant SLO: max run duration for a successful job (0 "
        "disables)",
    )
    serve.add_argument(
        "--slo-success-rate", type=float, default=0.0,
        help="per-tenant SLO: min done-fraction over the rolling outcome "
        "window, in (0, 1] (0 disables)",
    )
    serve.add_argument(
        "--slo-window", type=int, default=100,
        help="rolling terminal-outcome window per tenant for the "
        "success-rate SLO",
    )
    serve.add_argument(
        "--index-path", default="",
        help="corpus index root: enables POST /v1/search (index-server "
        "read path with its own admission lane — see docs/SERVICE.md)",
    )
    serve.add_argument(
        "--search-max-inflight", type=int, default=8,
        help="search admission lane: requests actively served",
    )
    serve.add_argument(
        "--search-max-waiting", type=int, default=32,
        help="search admission lane: queued beyond inflight before 429",
    )
    serve.add_argument(
        "--search-text-model", default="clip-text-b-tpu",
        help="CLIP text tower for text-to-clip queries (provenance-gated)",
    )
    serve.add_argument(
        "--search-cache-mb", type=int, default=0,
        help="warm shard cache byte budget in MB (0 = "
        "CURATE_INDEX_CACHE_BYTES or the 256 MB default)",
    )
    serve.add_argument(
        "--compact-interval-s", type=float, default=0.0,
        help="background index compaction cadence (0 disables; readers "
        "adopt new generations between requests)",
    )
    serve.set_defaults(func=_cmd_serve)


def _cmd_serve(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.service.admission import QuotaConfig
    from cosmos_curate_tpu.service.app import ServiceConfig, serve
    from cosmos_curate_tpu.service.slo import SloConfig

    config = ServiceConfig(
        slo=SloConfig(
            queue_wait_s=args.slo_queue_wait_s,
            run_duration_s=args.slo_run_duration_s,
            success_rate=args.slo_success_rate,
            window=args.slo_window,
        ),
        quota=QuotaConfig(
            max_concurrent_jobs=args.max_concurrent,
            max_running_per_tenant=args.max_running_per_tenant,
            max_queued_per_tenant=args.max_queued_per_tenant,
            max_queued_total=args.max_queued_total,
            cpus_per_job=args.cpus_per_job,
            memory_gb_per_job=args.memory_gb_per_job,
        ),
        max_attempts=args.max_attempts,
        drain_s=args.drain_s,
        term_grace_s=args.term_grace_s,
        metrics_port=args.metrics_port,
    )
    search_config = None
    if args.index_path:
        from cosmos_curate_tpu.service.search import SearchConfig

        search_config = SearchConfig(
            index_path=args.index_path,
            max_inflight=args.search_max_inflight,
            max_waiting=args.search_max_waiting,
            text_model=args.search_text_model,
            cache_bytes=(args.search_cache_mb << 20) or None,
            compact_interval_s=args.compact_interval_s,
        )
    serve(
        host=args.host, port=args.port, work_root=args.work_root, config=config,
        search_config=search_config,
    )
    return 0
