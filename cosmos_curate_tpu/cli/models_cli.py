"""`cosmos-curate-tpu models` — weights registry management.

Equivalent capability of the reference's model manager CLI
(cosmos_curate/core/managers/model_cli.py — in-container weight download /
listing; weights flow HF → cloud cache → per-node dir, model_utils.py):
list registered models, show staging status, stage a checkpoint file into
the registry location, and export a randomly-initialized checkpoint (useful
for smoke tests and as a template for converters).
"""

from __future__ import annotations

import argparse
import shutil
from pathlib import Path


def register(sub: argparse._SubParsersAction) -> None:
    models = sub.add_parser("models", help="model weights registry")
    msub = models.add_subparsers(dest="subcommand", metavar="action")

    ls = msub.add_parser("list", help="registered models + staging status")
    ls.set_defaults(func=_cmd_list)

    stage = msub.add_parser("stage", help="copy a params.msgpack into the registry")
    stage.add_argument("model_id")
    stage.add_argument("checkpoint", help="path to a flax msgpack checkpoint")
    stage.set_defaults(func=_cmd_stage)

    init = msub.add_parser("init-random", help="write a seeded random checkpoint")
    init.add_argument("model_id")
    init.add_argument("--seed", type=int, default=0)
    init.set_defaults(func=_cmd_init_random)

    pull = msub.add_parser(
        "pull-hf",
        help="download files from a Hugging Face repo (SDK-free, resumable) "
        "for conversion by the converters",
    )
    pull.add_argument("repo_id", help="e.g. Qwen/Qwen2-VL-2B-Instruct")
    pull.add_argument("files", nargs="+", help="repo-relative file names")
    pull.add_argument("--revision", default="main")
    pull.add_argument(
        "--dest", default="", help="destination dir (default: hf/<repo_id> under the weights root)"
    )
    pull.add_argument("--sha256", default="", help="expected sha256 (single file only)")
    pull.set_defaults(func=_cmd_pull_hf)

    models.set_defaults(func=lambda args: (models.print_help(), 2)[1])


def _cmd_list(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.models import registry

    for mid in registry.registered_models():
        ckpt = registry.local_dir_for(mid) / "params.msgpack"
        status = f"staged ({ckpt.stat().st_size >> 20} MiB)" if ckpt.exists() else "not staged"
        print(f"{mid:28s} {status}")
    print(f"\nweights root: {registry.weights_root()}")
    return 0


def _cmd_stage(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.models import registry

    if args.model_id not in registry.registered_models():
        print(f"error: unknown model id {args.model_id!r}; see `models list`")
        return 2
    src = Path(args.checkpoint)
    if not src.is_file():
        print(f"error: {src} not found")
        return 2
    dst = registry.local_dir_for(args.model_id) / "params.msgpack"
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.copyfile(src, dst)
    print(f"staged {src} -> {dst}")
    return 0


def _cmd_pull_hf(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.models import registry
    from cosmos_curate_tpu.models.hf_hub import HubDownloadError, pull_repo_files

    dest_dir = Path(args.dest) if args.dest else registry.weights_root() / "hf" / args.repo_id
    if args.sha256 and len(args.files) != 1:
        print("error: --sha256 applies to a single file")
        return 2
    try:
        for dest in pull_repo_files(
            args.repo_id,
            args.files,
            dest_dir,
            revision=args.revision,
            expected_sha256={args.files[0]: args.sha256} if args.sha256 else None,
        ):
            print(f"pulled {dest}")
    except HubDownloadError as e:
        print(f"error: {e}")
        return 1
    return 0


def _cmd_init_random(args: argparse.Namespace) -> int:
    from cosmos_curate_tpu.models import registry

    builders = _init_builders()
    builder = builders.get(args.model_id)
    if builder is None:
        print(
            f"error: no random-init builder for {args.model_id!r}; "
            f"have {sorted(builders)}"
        )
        return 2
    params = builder(args.seed)
    path = registry.save_params(args.model_id, params)
    print(f"wrote {path}")
    return 0


def _init_builders():
    """model_id -> (seed -> params): RAW ``model.init`` with the given seed,
    never through the registry (which would reload staged weights and
    ignore the seed)."""
    import jax
    import jax.numpy as jnp

    def transnet(seed):
        from cosmos_curate_tpu.models.transnetv2 import INPUT_H, INPUT_W, WINDOW, TransNet

        return TransNet().init(
            jax.random.PRNGKey(seed), jnp.zeros((1, WINDOW, INPUT_H, INPUT_W, 3), jnp.uint8)
        )

    def clip_b16(seed):
        from cosmos_curate_tpu.models.vit import VIT_B_16, ViT, preprocess_frames

        dummy = jnp.zeros((1, VIT_B_16.image_size, VIT_B_16.image_size, 3), jnp.uint8)
        return ViT(VIT_B_16).init(
            jax.random.PRNGKey(seed), preprocess_frames(dummy, image_size=VIT_B_16.image_size)
        )

    def aesthetics(seed):
        from cosmos_curate_tpu.models.clip import AestheticMLP

        # 768-d input: the default scorer composes with the L/14 tower
        # (CLIPAestheticScorer), matching the published head's input width.
        return AestheticMLP().init(jax.random.PRNGKey(seed), jnp.zeros((1, 768)))

    def video_embed(seed):
        from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_BASE, VideoEmbedModel

        s = VIDEO_EMBED_BASE.vit.image_size
        dummy = jnp.zeros((1, VIDEO_EMBED_BASE.num_frames, s, s, 3), jnp.uint8)
        return VideoEmbedModel(VIDEO_EMBED_BASE).init(jax.random.PRNGKey(seed), dummy)

    def caption_vlm(seed):
        from cosmos_curate_tpu.models.vlm import VLM, VLM_BASE
        from cosmos_curate_tpu.models.vlm.model import init_cache

        model = VLM(VLM_BASE)
        size = VLM_BASE.vision.image_size
        ck, cv = init_cache(VLM_BASE, 1)
        return model.init(
            jax.random.PRNGKey(seed),
            jnp.zeros((1, 1, size, size, 3), jnp.uint8),
            jnp.zeros((1, 4), jnp.int32),
            ck,
            cv,
            method=model.init_everything,
        )

    def t5(seed):
        from cosmos_curate_tpu.models.t5 import T5_BASE, TextEncoder

        return TextEncoder(T5_BASE).init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32), jnp.ones((1, 8), bool)
        )

    return {
        "transnetv2-tpu": transnet,
        "clip-vit-b16-tpu": clip_b16,
        "aesthetics-mlp-tpu": aesthetics,
        "video-embed-tpu": video_embed,
        "caption-vlm-tpu": caption_vlm,
        "t5-encoder-tpu": t5,
    }
