"""`cosmos-curate-tpu top …` — htop for pipelines.

Renders a refreshing per-stage table from the live ops plane:

- ``top <run-output-dir>`` — read the run's atomically-swapped live
  snapshot (``<out>/report/live/status.json``) straight off disk. Works
  for any local run (CLI, bench, a service job's output root).
- ``top http://host:port`` — a job service: readiness + queue depths
  (/health), per-tenant SLO standing (/v1/slo), and the running jobs.
- ``top http://host:port --job <id>`` (or a full
  ``…/v1/jobs/<id>/status`` URL) — one service job's live snapshot as
  served by ``GET /v1/jobs/<id>/status``.

``--once`` prints a single frame (scripts/tests); otherwise the screen
refreshes every ``--interval`` seconds until Ctrl-C. Stale snapshots (a
publisher that stopped while the job claims to be running) are flagged —
that staleness IS the wedged-job signal for single-threaded runners.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def register(sub: argparse._SubParsersAction) -> None:
    top = sub.add_parser(
        "top",
        help="live per-stage view of a running pipeline or job service "
        "(reads the live ops snapshot / service status endpoints)",
    )
    top.add_argument(
        "target",
        help="run output dir, service URL (http://host:port), or a full "
        "/v1/jobs/<id>/status URL",
    )
    top.add_argument("--job", default="", help="job id (with a service URL)")
    top.add_argument(
        "--interval", type=float, default=2.0, help="refresh period seconds"
    )
    top.add_argument("--once", action="store_true", help="print one frame and exit")
    top.add_argument("--json", action="store_true", dest="as_json", help="raw JSON frame")
    top.set_defaults(func=_cmd_top)


def _http_get(url: str, timeout: float = 5.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read().decode("utf-8"))


def _render_service(base: str) -> tuple[str, dict]:
    """(rendered text, raw payload) for a bare service URL — the payload
    carries the actual health + SLO documents so --json is scriptable."""
    from cosmos_curate_tpu.service.job_queue import LANES

    health = _http_get(f"{base}/health")
    lines = [
        f"service: {base}  status={health.get('status')}  "
        f"ready={health.get('ready')}  dispatcher={health.get('dispatcher_running')}  "
        f"journal_writable={health.get('journal_writable')}"
    ]
    queued = health.get("queued") or {}
    lines.append(
        "queues: "
        + "  ".join(f"{lane}={queued.get(lane, 0)}" for lane in LANES)
        + f"  max_concurrent={health.get('max_concurrent')}"
    )
    states = health.get("states") or {}
    if states:
        lines.append(
            "jobs: " + "  ".join(f"{s}={n}" for s, n in sorted(states.items()) if n)
        )
    if "index_generation" in health:
        lines.append(f"search: serving index generation {health['index_generation']}")
    running = health.get("running_jobs") or []
    if running:
        lines.append(f"running: {', '.join(running)}  (drill in with --job <id>)")
    try:
        slo = _http_get(f"{base}/v1/slo")
    except Exception:
        slo = None
    if slo and slo.get("tenants"):
        lines.append("per-tenant SLO standing:")
        lines.append(
            f"  {'tenant':<20} {'wait mean/max':>14} {'dur mean/max':>14} "
            f"{'success':>8} {'breaches':>8}"
        )
        for tenant, t in slo["tenants"].items():
            qw, rd, sr = t["queue_wait"], t["run_duration"], t["success_rate"]
            rate = sr.get("rate")
            lines.append(
                f"  {tenant:<20} "
                f"{qw['mean_s']:>6.1f}/{qw['max_s']:<6.1f} "
                f"{rd['mean_s']:>6.1f}/{rd['max_s']:<6.1f} "
                f"{(f'{rate:.0%}' if rate is not None else '—'):>8} "
                f"{t['breaches_total']:>8}"
            )
    return "\n".join(lines), {"health": health, "slo": slo}


def _frame(args: argparse.Namespace) -> tuple[str, dict | None]:
    """One rendered frame + the raw payload (None = nothing to show yet)."""
    from cosmos_curate_tpu.observability.live_status import read_status, render_status

    target = args.target.rstrip("/")
    if target.startswith(("http://", "https://")):
        if "/v1/jobs/" in target:
            doc = _http_get(target)
        elif args.job:
            doc = _http_get(f"{target}/v1/jobs/{args.job}/status")
        else:
            return _render_service(target)
        snap = doc.get("snapshot")
        header = (
            f"job {doc.get('job_id')}  state={doc.get('state')}  "
            f"tenant={doc.get('tenant')}  attempts={doc.get('attempts')}"
        )
        if snap is None:
            return f"{header}\n  {doc.get('detail', 'no live snapshot')}", doc
        return f"{header}\n{render_status(snap)}", doc
    snap = read_status(target)
    if snap is None:
        return (
            f"no live snapshot under {target} (run not started, finished "
            "long ago, or live status disabled)",
            None,
        )
    return render_status(snap), snap


def _cmd_top(args: argparse.Namespace) -> int:
    try:
        while True:
            try:
                rendered, payload = _frame(args)
            except Exception as e:
                rendered, payload = f"error: {e}", None
            if args.as_json:
                print(json.dumps(payload or {}))
            else:
                if not args.once:
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear screen, home
                print(rendered)
            if args.once:
                return 0 if payload is not None else 2
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 130
