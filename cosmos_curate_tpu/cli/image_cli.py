"""Image-build and fleet-deploy CLI.

Equivalent capability of the reference's packaging/deploy tooling
(cosmos_curate/client/image_cli/image_app.py:30-242 — docker build/push with
cache sources — and client/nvcf_cli/ — cloud function deployment). The TPU
deployment target is Kubernetes/GKE, so deploy drives the Helm chart in
deploy/helm/ instead of NVCF: ``deploy render`` expands the chart with a
built-in renderer (covers this chart's template constructs; no helm binary
needed), and ``deploy apply`` pipes the manifests to kubectl.

docker/helm/kubectl are host tools: commands print exactly what they run,
``--dry-run`` shows it without executing, and a missing binary is a clear
error — not an import-time crash.
"""

from __future__ import annotations

import argparse
import re
import shutil
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_DOCKERFILE = REPO_ROOT / "deploy" / "Dockerfile"
DEFAULT_CHART = REPO_ROOT / "deploy" / "helm" / "cosmos-curate-tpu"


# ---------------------------------------------------------------------------
# docker image build/push


def _run_or_print(
    cmd: list[str], *, dry_run: bool, tool: str, stdin: bytes | None = None
) -> int:
    print("+ " + " ".join(cmd))
    if dry_run:
        if stdin is not None:
            print(stdin.decode())
        return 0
    if shutil.which(cmd[0]) is None:
        print(f"error: {tool} not found on PATH — install it or use --dry-run", file=sys.stderr)
        return 3
    return subprocess.run(cmd, input=stdin).returncode


def cmd_build(args: argparse.Namespace) -> int:
    label = f"{args.image_name}:{args.image_tag}"
    cmd = [
        args.docker, "build",
        "-f", str(args.dockerfile),
        "-t", label,
    ]
    for c in args.cache_from or []:
        cmd += ["--cache-from", c]
    if args.cache_to:
        cmd += ["--cache-to", args.cache_to]
    if args.platform:
        cmd += ["--platform", args.platform]
    cmd.append(str(args.context))
    rc = _run_or_print(cmd, dry_run=args.dry_run, tool="docker")
    if rc == 0 and args.push:
        rc = _run_or_print(
            [args.docker, "push", label], dry_run=args.dry_run, tool="docker"
        )
    return rc


def cmd_push(args: argparse.Namespace) -> int:
    return _run_or_print(
        [args.docker, "push", f"{args.image_name}:{args.image_tag}"],
        dry_run=args.dry_run,
        tool="docker",
    )


# ---------------------------------------------------------------------------
# chart rendering (helm-template subset sufficient for deploy/helm/*)

_PIPE_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")


def _deep_get(values: dict, dotted: str):
    cur = values
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _eval_expr(expr: str, ctx: dict):
    """Evaluate one {{ ... }} expression: .Values paths, .Release/.Chart
    fields, `index` lookups, and the default/quote pipe functions. An
    unresolvable path (no `default` rescue) raises — a typo'd values key
    must never ship as the literal string 'None'."""
    stages = [s.strip() for s in expr.split("|")]
    value = _eval_atom(stages[0], ctx)
    for stage in stages[1:]:
        if stage == "quote":
            escaped = str(value).replace("\\", "\\\\").replace('"', '\\"')
            value = f'"{escaped}"'
        elif stage.startswith("default "):
            if value in (None, ""):
                value = _eval_atom(stage[len("default "):].strip(), ctx)
        else:
            raise ValueError(f"unsupported template pipe {stage!r}")
    if value is None:
        raise ValueError(f"template expression {expr!r} resolved to nothing")
    return value


def _eval_atom(atom: str, ctx: dict):
    atom = atom.strip()
    if atom.startswith("include "):
        # include "name" . — chart helpers; none defined in-tree, so the
        # default pipe supplies the value
        return None
    if atom.startswith("index "):
        parts = atom.split(None, 2)  # index <path> "key"
        base = _eval_atom(parts[1], ctx)
        key = parts[2].strip('"')
        return (base or {}).get(key)
    if atom.startswith(".Values."):
        return _deep_get(ctx["Values"], atom[len(".Values."):])
    if atom.startswith(".Release."):
        return ctx["Release"].get(atom[len(".Release."):])
    if atom.startswith(".Chart."):
        return ctx["Chart"].get(atom[len(".Chart."):])
    if atom.startswith('"') and atom.endswith('"'):
        return atom.strip('"')
    if atom.startswith(".") and "item" in ctx:
        # inside a range block: bare .field resolves against the loop item
        return _deep_get(ctx["item"], atom[1:])
    raise ValueError(f"unsupported template atom {atom!r}")


# {{- trims preceding whitespace/newline, -}} trailing (Go template rules);
# range/end sit on their own lines in the in-tree chart
_RANGE_RE = re.compile(
    r"\n?[ \t]*\{\{-\s*range\s+(\.[\w.]+)\s*\}\}(.*?)\n?[ \t]*\{\{-\s*end\s*\}\}",
    re.DOTALL,
)


def _expand_ranges(text: str, ctx: dict) -> str:
    """Expand {{- range .Values.x }} ... {{- end }} blocks (list iteration,
    loop fields as bare .name atoms)."""

    def repl(m: re.Match) -> str:
        items = _eval_atom(m.group(1), ctx) or []
        body = m.group(2)
        out = []
        for item in items:
            inner = dict(ctx, item=item)
            expanded = _PIPE_RE.sub(lambda mm: str(_eval_expr(mm.group(1), inner)), body)
            # values are literals, never re-expanded (helm semantics): mask
            # any braces the substituted values contain from the global pass
            out.append(expanded.replace("{{", "\x00LB\x00").replace("}}", "\x00RB\x00"))
        return "".join(out)

    return _RANGE_RE.sub(repl, text)


def _unmask(text: str) -> str:
    return text.replace("\x00LB\x00", "{{").replace("\x00RB\x00", "}}")


def render_chart(
    chart_dir: Path, *, release: str = "curate", set_values: list[str] | None = None
) -> dict[str, str]:
    """-> {template filename: rendered manifest}. Covers the template
    constructs used by the in-tree chart; unknown constructs raise so a
    chart outgrowing the renderer fails loudly (use real helm then)."""
    import yaml

    values = yaml.safe_load((chart_dir / "values.yaml").read_text()) or {}
    chart_meta = yaml.safe_load((chart_dir / "Chart.yaml").read_text()) or {}
    for assignment in set_values or []:
        key, _, raw = assignment.partition("=")
        cur = values
        parts = key.split(".")
        for i, p in enumerate(parts[:-1]):
            cur = cur.setdefault(p, {})
            if not isinstance(cur, dict):
                raise ValueError(
                    f"cannot override {key!r}: {'.'.join(parts[: i + 1])} is not a mapping"
                )
        cur[parts[-1]] = yaml.safe_load(raw)
    ctx = {
        "Values": values,
        "Release": {"Name": release, "Namespace": "default"},
        "Chart": {"Name": chart_meta.get("name", chart_dir.name)},
    }

    out: dict[str, str] = {}
    for tmpl in sorted((chart_dir / "templates").glob("*.yaml")):
        text = _expand_ranges(tmpl.read_text(), ctx)
        rendered = _unmask(_PIPE_RE.sub(lambda m: str(_eval_expr(m.group(1), ctx)), text))
        # validate: every rendered manifest must parse as YAML
        list(yaml.safe_load_all(rendered))
        out[tmpl.name] = rendered
    return out


def cmd_render(args: argparse.Namespace) -> int:
    try:
        manifests = render_chart(
            Path(args.chart), release=args.release, set_values=args.set or []
        )
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # yaml parse errors etc. — user input, not a bug
        import yaml

        if isinstance(e, yaml.YAMLError):
            print(f"error: invalid YAML in chart or --set value: {e}", file=sys.stderr)
            return 2
        raise
    if args.output_dir:
        outdir = Path(args.output_dir)
        outdir.mkdir(parents=True, exist_ok=True)
        for name, text in manifests.items():
            (outdir / name).write_text(text)
            print(f"wrote {outdir / name}")
    else:
        for name, text in manifests.items():
            print(f"---\n# Source: {name}\n{text}")
    return 0


def cmd_apply(args: argparse.Namespace) -> int:
    try:
        manifests = render_chart(
            Path(args.chart), release=args.release, set_values=args.set or []
        )
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # yaml parse errors etc. — user input, not a bug
        import yaml

        if isinstance(e, yaml.YAMLError):
            print(f"error: invalid YAML in chart or --set value: {e}", file=sys.stderr)
            return 2
        raise
    doc = "\n---\n".join(manifests.values())
    cmd = [args.kubectl, "apply", "-f", "-"]
    if args.namespace:
        cmd += ["-n", args.namespace]
    return _run_or_print(cmd, dry_run=args.dry_run, tool="kubectl", stdin=doc.encode())


# ---------------------------------------------------------------------------
# argparse wiring


def register(sub) -> None:
    """Same lazy-registration convention as the other cli modules."""
    add_image_parser(sub)
    add_deploy_parser(sub)


def add_image_parser(sub) -> None:
    image = sub.add_parser("image", help="build/push the container image")
    isub = image.add_subparsers(dest="image_cmd", required=True)

    build = isub.add_parser("build", help="docker build the curate image")
    build.add_argument("--image-name", default="cosmos-curate-tpu")
    build.add_argument("--image-tag", default="0.1.0")
    build.add_argument("--dockerfile", default=str(DEFAULT_DOCKERFILE))
    build.add_argument("--context", default=str(REPO_ROOT))
    build.add_argument("--cache-from", action="append", default=None)
    build.add_argument("--cache-to", default=None)
    build.add_argument("--platform", default=None)
    build.add_argument("--push", action="store_true")
    build.add_argument("--docker", default="docker")
    build.add_argument("--dry-run", action="store_true")
    build.set_defaults(func=cmd_build)

    push = isub.add_parser("push", help="docker push the curate image")
    push.add_argument("--image-name", default="cosmos-curate-tpu")
    push.add_argument("--image-tag", default="0.1.0")
    push.add_argument("--docker", default="docker")
    push.add_argument("--dry-run", action="store_true")
    push.set_defaults(func=cmd_push)


def add_deploy_parser(sub) -> None:
    deploy = sub.add_parser("deploy", help="render/apply the k8s deployment")
    dsub = deploy.add_subparsers(dest="deploy_cmd", required=True)

    render = dsub.add_parser("render", help="expand the Helm chart to manifests")
    render.add_argument("--chart", default=str(DEFAULT_CHART))
    render.add_argument("--release", default="curate")
    render.add_argument("--set", action="append", help="values override key=val")
    render.add_argument("--output-dir", default=None)
    render.set_defaults(func=cmd_render)

    apply = dsub.add_parser("apply", help="kubectl-apply the rendered manifests")
    apply.add_argument("--chart", default=str(DEFAULT_CHART))
    apply.add_argument("--release", default="curate")
    apply.add_argument("--set", action="append")
    apply.add_argument("--namespace", default=None)
    apply.add_argument("--kubectl", default="kubectl")
    apply.add_argument("--dry-run", action="store_true")
    apply.set_defaults(func=cmd_apply)
